"""Pallas TPU kernel for decode-time GQA attention over the main KV cache.

Why a kernel: the XLA einsum path maps GQA decode badly — per (batch, kv
head) the score matmul is [G=8, hd=64] × [hd, W], a sliver of the 128×128
MXU, and measured effective bandwidth over the cache was ~110 GB/s.  The
kernel streams each (b, k) cache slice through VMEM once and fuses mask +
softmax-statistics + weighted sum, so HBM traffic is exactly one read of
K/V.

The kernel returns *unnormalized* output plus the softmax statistics
``(m, z)`` so the caller can fold in the fresh-token ring (tiny, handled in
plain XLA) with the same logsumexp merge used by the XLA path — the kernel
never needs to know about the ring.

Grid: one program per (batch row, kv head).  The whole [W, hd] slice sits in
VMEM (W=4096, hd=64, bf16 → 512 KB per operand; VMEM is ~16 MB), so no
inner blocking is needed at current window sizes.

Validated in interpret mode on CPU (tests); opt-in on hardware via
``RuntimeConfig(attention_impl="pallas")`` until profiled on a real chip.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _decode_attn_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, z_ref):
    """One (batch, kv-head) program: masked scores + softmax stats + PV."""
    q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
    k = k_ref[0, 0].astype(jnp.float32)  # [W, hd]
    v = v_ref[0, 0].astype(jnp.float32)  # [W, hd]
    scale = 1.0 / math.sqrt(q.shape[-1])

    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, W]
    valid = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) < lens_ref[0]
    scores = jnp.where(valid, scores, -1e30)

    m = jnp.max(scores, axis=-1, keepdims=True)  # [G, 1]
    m = jnp.maximum(m, -1e29)  # fresh rows stay finite
    p = jnp.exp(scores - m)
    z = jnp.sum(p, axis=-1, keepdims=True)  # [G, 1]
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [G, hd] — unnormalized

    o_ref[0, 0] = o
    m_ref[0, 0] = m[:, 0]
    z_ref[0, 0] = z[:, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention_pallas(
    q: jax.Array,  # [B, K, G, hd]
    k_cache: jax.Array,  # [B, K, W, hd]
    v_cache: jax.Array,  # [B, K, W, hd]
    base_lens: jax.Array,  # [B] valid kv per row
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """→ (o [B,K,G,hd] f32 unnormalized, m [B,K,G] f32, z [B,K,G] f32)."""
    from jax.experimental import pallas as pl

    B, K, G, hd = q.shape
    W = k_cache.shape[2]

    grid = (B, K)
    out_shapes = (
        jax.ShapeDtypeStruct((B, K, G, hd), jnp.float32),
        jax.ShapeDtypeStruct((B, K, G), jnp.float32),
        jax.ShapeDtypeStruct((B, K, G), jnp.float32),
    )
    return pl.pallas_call(
        _decode_attn_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, k: (b,)),  # lens: this row's scalar
            pl.BlockSpec((1, 1, G, hd), lambda b, k: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, W, hd), lambda b, k: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, W, hd), lambda b, k: (b, k, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, G, hd), lambda b, k: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, G), lambda b, k: (b, k, 0)),
            pl.BlockSpec((1, 1, G), lambda b, k: (b, k, 0)),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(base_lens, q, k_cache, v_cache)


def _paged_attn_kernel(
    layer_ref, tables_ref, lens_ref,  # scalar-prefetch (SMEM)
    q_ref, k_ref, v_ref,  # tensor blocks (VMEM)
    o_ref, m_ref, z_ref,  # outputs
    acc, m_s, z_s,  # VMEM scratch carried across the page grid dim
):
    """One (batch row, kv head, page) program with flash accumulation.

    The page grid dimension is innermost (sequential on TPU), so the
    VMEM scratch carries softmax statistics across a row's pages; the block
    table is scalar-prefetched and drives the K/V BlockSpec index_map — each
    program DMAs exactly one page, nothing is gathered/materialized.
    """
    import jax.lax as lax

    b = pl.program_id(0)
    p = pl.program_id(2)
    page = k_ref.shape[3]

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, -1e30)
        z_s[...] = jnp.zeros_like(z_s)

    q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
    k = k_ref[0, 0, 0].astype(jnp.float32)  # [page, hd]
    v = v_ref[0, 0, 0].astype(jnp.float32)  # [page, hd]
    scale = 1.0 / math.sqrt(q.shape[-1])

    scores = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, page]
    pos = p * page + lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(pos < lens_ref[b], scores, -1e30)

    m_new = jnp.maximum(m_s[...], jnp.max(scores, axis=-1, keepdims=True))
    m_new = jnp.maximum(m_new, -1e29)  # fresh rows stay finite
    alpha = jnp.exp(m_s[...] - m_new)
    pexp = jnp.exp(scores - m_new)
    z_s[...] = z_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc[...] = acc[...] * alpha + lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_s[...] = m_new

    @pl.when(p == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = acc[...]
        m_ref[0, 0] = m_s[...][:, 0]
        z_ref[0, 0] = z_s[...][:, 0]


@functools.partial(jax.jit, static_argnames=("wpages", "interpret"))
def paged_decode_attention_pallas(
    q: jax.Array,  # [B, K, G, hd]
    pool_k: jax.Array,  # [L, N, K, page, hd] the WHOLE pool (no slicing)
    pool_v: jax.Array,
    layer: jax.Array,  # scalar int32 — which layer's pages to read
    tables: jax.Array,  # [B, Pmax] int32 block tables
    base_lens: jax.Array,  # [B]
    *,
    wpages: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Paged decode attention: block tables drive page DMA via scalar
    prefetch → (o unnormalized, m, z), same contract as the dense kernel.

    Taking the full pool (not a sliced layer) matters: slicing
    ``pool[layer]`` in XLA before a pallas_call would materialize a copy of
    the layer's pages every (layer, step); here the layer index rides the
    index_map and only the addressed pages move.
    """
    from jax.experimental.pallas import tpu as pltpu

    B, K, G, hd = q.shape
    page = pool_k.shape[3]

    grid = (B, K, wpages)
    kv_spec = pl.BlockSpec(
        (1, 1, 1, page, hd),
        lambda b, k, p, layer_ref, tables_ref, lens_ref: (
            layer_ref[0], tables_ref[b, p], k, 0, 0
        ),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, G, hd),
                lambda b, k, p, *_refs: (b, k, 0, 0),
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, G, hd), lambda b, k, p, *_refs: (b, k, 0, 0)
            ),
            pl.BlockSpec((1, 1, G), lambda b, k, p, *_refs: (b, k, 0)),
            pl.BlockSpec((1, 1, G), lambda b, k, p, *_refs: (b, k, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, hd), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
        ],
    )
    out_shapes = (
        jax.ShapeDtypeStruct((B, K, G, hd), jnp.float32),
        jax.ShapeDtypeStruct((B, K, G), jnp.float32),
        jax.ShapeDtypeStruct((B, K, G), jnp.float32),
    )
    return pl.pallas_call(
        _paged_attn_kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        tables.astype(jnp.int32),
        base_lens.astype(jnp.int32),
        q, pool_k, pool_v,
    )


def merged_paged_decode_attention_pallas(
    q: jax.Array,  # [B, 1, H, hd]
    pool_k: jax.Array,  # [L, N, K, page, hd]
    pool_v: jax.Array,
    layer: jax.Array,  # scalar int32
    tables: jax.Array,  # [B, Pmax]
    ring_k: jax.Array,  # [T, B, K, hd]
    ring_v: jax.Array,
    base_lens: jax.Array,  # [B]
    t: jax.Array,
    *,
    wpages: int,
    interpret: bool = False,
) -> jax.Array:
    """Paged analog of :func:`merged_decode_attention_pallas`: main-cache
    source from the paged kernel, ring folded in via the shared merge."""
    from calfkit_tpu.inference.model import logsumexp_merge, ring_attention_source

    B, _, H, hd = q.shape
    K = pool_k.shape[2]
    G = H // K
    qg = q.reshape(B, K, G, hd)

    o1, m1, z1 = paged_decode_attention_pallas(
        qg, pool_k, pool_v, layer, tables, base_lens,
        wpages=wpages, interpret=interpret,
    )
    o2, m2, z2 = ring_attention_source(qg, ring_k, ring_v, t)
    out = logsumexp_merge((o1, m1[..., None], z1[..., None]), (o2, m2, z2))
    return out.reshape(B, 1, H, hd).astype(q.dtype)


def merged_decode_attention_pallas(
    q: jax.Array,  # [B, 1, H, hd]
    k_cache: jax.Array,  # [B, K, W, hd]
    v_cache: jax.Array,
    ring_k: jax.Array,  # [T, B, K, hd]
    ring_v: jax.Array,
    base_lens: jax.Array,  # [B]
    t: jax.Array,  # current ring step
    *,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for :func:`model._merged_decode_attention` with the main-cache
    source computed by the Pallas kernel and the (tiny) ring folded in via
    the same logsumexp merge in plain XLA."""
    from calfkit_tpu.inference.model import logsumexp_merge, ring_attention_source

    B, _, H, hd = q.shape
    K = k_cache.shape[1]
    G = H // K
    qg = q.reshape(B, K, G, hd)

    o1, m1, z1 = decode_attention_pallas(
        qg, k_cache, v_cache, base_lens, interpret=interpret
    )
    o2, m2, z2 = ring_attention_source(qg, ring_k, ring_v, t)
    out = logsumexp_merge((o1, m1[..., None], z1[..., None]), (o2, m2, z2))
    return out.reshape(B, 1, H, hd).astype(q.dtype)


# --------------------------------------------------------------------------- #
# ragged unified attention: mixed decode / prefill-chunk / verify rows
# (ISSUE 6; the Ragged Paged Attention shape, arXiv:2604.15464)
# --------------------------------------------------------------------------- #

# kv positions streamed per grid step of the dense ragged kernel (the
# window is a power-of-two bucket, so divisibility holds; windows smaller
# than this run as one chunk)
RAGGED_KV_CHUNK = 512


def _ragged_attn_kernel(
    starts_ref, lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, z_ref,
    acc, m_s, z_s,
):
    """One (batch row, kv head, kv chunk) program of the ragged kernel.

    The q block carries ALL of a row's queries (S = the wave's padded
    q_len — 1 for decode rows, chunk for prefill rows, k+1 for verify
    rows), flattened to [S·G, hd] so one MXU matmul scores every
    (query, group) pair against the kv chunk.  THE ragged mask law (see
    inference/ragged.py): query j attends kv positions
    < min(kv_len, start + j + 1).  Flash accumulation across the kv grid
    dimension in VMEM scratch — the window streams through VMEM exactly
    once for the whole multi-query block, which is the amortization the
    per-position decomposition paid S times for.
    """
    import jax.lax as lax

    c = pl.program_id(2)
    S, G, hd = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
    C = k_ref.shape[2]

    @pl.when(c == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, -1e30)
        z_s[...] = jnp.zeros_like(z_s)

    q = q_ref[0, 0].astype(jnp.float32).reshape(S * G, hd)
    k = k_ref[0, 0].astype(jnp.float32)  # [C, hd]
    v = v_ref[0, 0].astype(jnp.float32)
    scale = 1.0 / math.sqrt(hd)

    scores = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [S*G, C]
    kv_pos = c * C + lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    j = lax.broadcasted_iota(jnp.int32, scores.shape, 0) // G  # query index
    limit = jnp.minimum(lens_ref[0], starts_ref[0] + j + 1)
    scores = jnp.where(kv_pos < limit, scores, -1e30)

    m_new = jnp.maximum(m_s[...], jnp.max(scores, axis=-1, keepdims=True))
    m_new = jnp.maximum(m_new, -1e29)  # padding queries stay finite
    alpha = jnp.exp(m_s[...] - m_new)
    pexp = jnp.exp(scores - m_new)
    z_s[...] = z_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc[...] = acc[...] * alpha + lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_s[...] = m_new

    @pl.when(c == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = acc[...].reshape(S, G, hd)
        m_ref[0, 0] = m_s[...].reshape(S, G)
        z_ref[0, 0] = z_s[...].reshape(S, G)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_attention_pallas(
    q: jax.Array,  # [B, K, S, G, hd] kv-head-major ragged queries
    k_cache: jax.Array,  # [B, K, W, hd]
    v_cache: jax.Array,
    q_starts: jax.Array,  # [B] absolute position of each row's query 0
    kv_lens: jax.Array,  # [B] valid kv length each row may attend
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Ragged unified attention over a dense window → (o [B,K,S,G,hd] f32
    unnormalized, m [B,K,S,G], z [B,K,S,G]) — one kernel serving decode
    (S=1), prefill-chunk (S=chunk), and verify (S=k+1) rows through the
    shared mask law; same source contract as the single-query kernel so
    the logsumexp merge composes unchanged."""
    B, K, S, G, hd = q.shape
    W = k_cache.shape[2]
    kv_chunk = min(RAGGED_KV_CHUNK, W)
    if W % kv_chunk:
        kv_chunk = W  # non-power-of-two window: stream it whole

    grid = (B, K, W // kv_chunk)
    out_shapes = (
        jax.ShapeDtypeStruct((B, K, S, G, hd), jnp.float32),
        jax.ShapeDtypeStruct((B, K, S, G), jnp.float32),
        jax.ShapeDtypeStruct((B, K, S, G), jnp.float32),
    )
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _ragged_attn_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, k, c: (b,)),  # q_starts
            pl.BlockSpec((1,), lambda b, k, c: (b,)),  # kv_lens
            pl.BlockSpec((1, 1, S, G, hd), lambda b, k, c: (b, k, 0, 0, 0)),
            pl.BlockSpec((1, 1, kv_chunk, hd), lambda b, k, c: (b, k, c, 0)),
            pl.BlockSpec((1, 1, kv_chunk, hd), lambda b, k, c: (b, k, c, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, S, G, hd), lambda b, k, c: (b, k, 0, 0, 0)),
            pl.BlockSpec((1, 1, S, G), lambda b, k, c: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, S, G), lambda b, k, c: (b, k, 0, 0)),
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((S * G, hd), jnp.float32),
            pltpu.VMEM((S * G, 1), jnp.float32),
            pltpu.VMEM((S * G, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        q_starts.astype(jnp.int32), kv_lens.astype(jnp.int32),
        q, k_cache, v_cache,
    )


def _ragged_paged_attn_kernel(
    layer_ref, tables_ref, starts_ref, lens_ref,  # scalar-prefetch (SMEM)
    q_ref, k_ref, v_ref,  # tensor blocks (VMEM)
    o_ref, m_ref, z_ref,  # outputs
    acc, m_s, z_s,  # VMEM scratch carried across the page grid dim
):
    """Paged ragged program: the block table drives page DMA (scalar
    prefetch, like the single-query paged kernel) and every one of the
    row's S queries scores against each page as it streams through — one
    page read amortized over the whole ragged block."""
    import jax.lax as lax

    b = pl.program_id(0)
    p = pl.program_id(2)
    S, G, hd = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
    page = k_ref.shape[3]

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, -1e30)
        z_s[...] = jnp.zeros_like(z_s)

    q = q_ref[0, 0].astype(jnp.float32).reshape(S * G, hd)
    k = k_ref[0, 0, 0].astype(jnp.float32)  # [page, hd]
    v = v_ref[0, 0, 0].astype(jnp.float32)
    scale = 1.0 / math.sqrt(hd)

    scores = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [S*G, page]
    kv_pos = p * page + lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    j = lax.broadcasted_iota(jnp.int32, scores.shape, 0) // G
    limit = jnp.minimum(lens_ref[b], starts_ref[b] + j + 1)
    scores = jnp.where(kv_pos < limit, scores, -1e30)

    m_new = jnp.maximum(m_s[...], jnp.max(scores, axis=-1, keepdims=True))
    m_new = jnp.maximum(m_new, -1e29)
    alpha = jnp.exp(m_s[...] - m_new)
    pexp = jnp.exp(scores - m_new)
    z_s[...] = z_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc[...] = acc[...] * alpha + lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_s[...] = m_new

    @pl.when(p == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = acc[...].reshape(S, G, hd)
        m_ref[0, 0] = m_s[...].reshape(S, G)
        z_ref[0, 0] = z_s[...].reshape(S, G)


@functools.partial(jax.jit, static_argnames=("wpages", "interpret"))
def ragged_attention_paged_pallas(
    q: jax.Array,  # [B, K, S, G, hd]
    pool_k: jax.Array,  # [L, N, K, page, hd] the WHOLE pool (no slicing)
    pool_v: jax.Array,
    layer: jax.Array,  # scalar int32
    tables: jax.Array,  # [B, Pmax] int32 block tables
    q_starts: jax.Array,  # [B]
    kv_lens: jax.Array,  # [B]
    *,
    wpages: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Ragged unified attention through the block tables → (o, m, z), the
    paged analog of :func:`ragged_attention_pallas` (same full-pool
    no-materialization contract as the single-query paged kernel)."""
    from jax.experimental.pallas import tpu as pltpu

    B, K, S, G, hd = q.shape
    page = pool_k.shape[3]

    grid = (B, K, wpages)
    kv_spec = pl.BlockSpec(
        (1, 1, 1, page, hd),
        lambda b, k, p, layer_ref, tables_ref, starts_ref, lens_ref: (
            layer_ref[0], tables_ref[b, p], k, 0, 0
        ),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, S, G, hd), lambda b, k, p, *_refs: (b, k, 0, 0, 0)
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, S, G, hd), lambda b, k, p, *_refs: (b, k, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, S, G), lambda b, k, p, *_refs: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, S, G), lambda b, k, p, *_refs: (b, k, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((S * G, hd), jnp.float32),
            pltpu.VMEM((S * G, 1), jnp.float32),
            pltpu.VMEM((S * G, 1), jnp.float32),
        ],
    )
    out_shapes = (
        jax.ShapeDtypeStruct((B, K, S, G, hd), jnp.float32),
        jax.ShapeDtypeStruct((B, K, S, G), jnp.float32),
        jax.ShapeDtypeStruct((B, K, S, G), jnp.float32),
    )
    return pl.pallas_call(
        _ragged_paged_attn_kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        tables.astype(jnp.int32),
        q_starts.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        q, pool_k, pool_v,
    )


# --------------------------------------------------------------------------- #
# speculative verify: k+1 queries per row against (main cache ⊕ chunk)
# --------------------------------------------------------------------------- #


def verify_attention_pallas(
    q: jax.Array,  # [B, S, H, hd] the verify chunk's queries
    k_cache: jax.Array,  # [B, K, W, hd] main-cache window
    v_cache: jax.Array,
    chunk_k: jax.Array,  # [S, B, K, hd] this layer's chunk K (ring layout)
    chunk_v: jax.Array,
    base_lens: jax.Array,  # [B]
    *,
    interpret: bool = False,
) -> jax.Array:
    """Multi-query verify attention on the Pallas lane.

    ONE ragged-kernel call scores all S = k+1 queries against the window
    (one window DMA amortized over the whole block — the Ragged Paged
    Attention shape this used to decompose into S single-query calls);
    the (tiny) chunk's causal self-attention folds in via the shared
    logsumexp merge, exactly like the XLA path.  The verify rows reduce
    to the ragged law with start = kv_len = base_lens.
    """
    from calfkit_tpu.inference.model import logsumexp_merge, verify_chunk_source

    B, S, H, hd = q.shape
    K = k_cache.shape[1]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    o1, m1, z1 = ragged_attention_pallas(
        jnp.transpose(qg, (0, 2, 1, 3, 4)), k_cache, v_cache,
        base_lens, base_lens, interpret=interpret,
    )  # [B, K, S, G, hd] / [B, K, S, G] x2 → merge layout [B, K, G, S, ·]
    o1 = jnp.transpose(o1, (0, 1, 3, 2, 4))
    m1 = jnp.transpose(m1, (0, 1, 3, 2))[..., None]
    z1 = jnp.transpose(z1, (0, 1, 3, 2))[..., None]
    o2, m2, z2 = verify_chunk_source(qg, chunk_k, chunk_v)
    out = logsumexp_merge((o1, m1, z1), (o2, m2, z2))  # [B, K, G, S, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd).astype(q.dtype)


def verify_attention_paged_pallas(
    q: jax.Array,  # [B, S, H, hd]
    pool_k: jax.Array,  # [L, N, K, page, hd]
    pool_v: jax.Array,
    layer: jax.Array,  # scalar int32
    tables: jax.Array,  # [B, Pmax]
    chunk_k: jax.Array,  # [S, B, K, hd]
    chunk_v: jax.Array,
    base_lens: jax.Array,
    *,
    wpages: int,
    interpret: bool = False,
) -> jax.Array:
    """Paged analog of :func:`verify_attention_pallas`: one ragged
    block-table kernel call reads each page exactly once for all S
    queries; the chunk folds in as the second source."""
    from calfkit_tpu.inference.model import logsumexp_merge, verify_chunk_source

    B, S, H, hd = q.shape
    K = pool_k.shape[2]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    o1, m1, z1 = ragged_attention_paged_pallas(
        jnp.transpose(qg, (0, 2, 1, 3, 4)), pool_k, pool_v, layer, tables,
        base_lens, base_lens, wpages=wpages, interpret=interpret,
    )
    o1 = jnp.transpose(o1, (0, 1, 3, 2, 4))
    m1 = jnp.transpose(m1, (0, 1, 3, 2))[..., None]
    z1 = jnp.transpose(z1, (0, 1, 3, 2))[..., None]
    o2, m2, z2 = verify_chunk_source(qg, chunk_k, chunk_v)
    out = logsumexp_merge((o1, m1, z1), (o2, m2, z2))
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd).astype(q.dtype)


# --------------------------------------------------------------------------- #
# prefill: flash attention over the (chunk-updated) cache
# --------------------------------------------------------------------------- #

# shared with model.prefill_attention's eligibility check — retune in ONE
# place after hardware profiling
PREFILL_BLOCK_Q = 128
PREFILL_KV_CHUNK = 512


def _prefill_attn_kernel(
    qpos_ref, lens_ref, q_ref, k_ref, v_ref, o_ref, *, kv_chunk: int
):
    """One (batch, kv-head, q-block) program: flash accumulation over kv.

    The whole [Skv, hd] K/V slice for this (b, k) sits in VMEM (≤ ~1 MB at
    Skv=4096); the scores for each kv chunk are [BQ, kv_chunk] per query
    group — never the full [Sq, Skv] matrix the XLA path materializes.
    """
    q_all = q_ref[0, 0].astype(jnp.float32)  # [G, BQ, hd]
    k_all = k_ref[0, 0].astype(jnp.float32)  # [Skv, hd]
    v_all = v_ref[0, 0].astype(jnp.float32)  # [Skv, hd]
    q_pos = qpos_ref[0]  # [BQ] absolute positions of this q block
    kv_len = lens_ref[0]  # scalar: valid kv for this row
    G, BQ, hd = q_all.shape
    Skv = k_all.shape[0]
    scale = 1.0 / math.sqrt(hd)
    n_chunks = Skv // kv_chunk

    def chunk_body(ci, carry):
        m, z, acc = carry  # [G,BQ,1], [G,BQ,1], [G,BQ,hd]
        start = ci * kv_chunk
        k_c = jax.lax.dynamic_slice_in_dim(k_all, start, kv_chunk, 0)
        v_c = jax.lax.dynamic_slice_in_dim(v_all, start, kv_chunk, 0)
        kv_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (BQ, kv_chunk), 1
        )
        mask = (kv_pos <= q_pos[:, None]) & (kv_pos < kv_len)  # [BQ, kv_chunk]

        new_m, new_z, new_acc = [], [], []
        for g in range(G):  # static unroll: G is 1-8
            scores = jax.lax.dot_general(
                q_all[g], k_c, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [BQ, kv_chunk]
            scores = jnp.where(mask, scores, -1e30)
            m_c = jnp.maximum(m[g], jnp.max(scores, axis=-1, keepdims=True))
            m_c = jnp.maximum(m_c, -1e29)  # all-masked chunks stay finite
            alpha = jnp.exp(m[g] - m_c)
            p = jnp.exp(scores - m_c)  # [BQ, kv_chunk]
            new_z.append(z[g] * alpha + jnp.sum(p, axis=-1, keepdims=True))
            new_acc.append(
                acc[g] * alpha
                + jax.lax.dot_general(
                    p, v_c, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
            new_m.append(m_c)
        return (
            jnp.stack(new_m), jnp.stack(new_z), jnp.stack(new_acc)
        )

    init = (
        jnp.full((G, BQ, 1), -1e30, jnp.float32),
        jnp.zeros((G, BQ, 1), jnp.float32),
        jnp.zeros((G, BQ, hd), jnp.float32),
    )
    m, z, acc = jax.lax.fori_loop(0, n_chunks, chunk_body, init)
    o_ref[0, 0] = acc / jnp.maximum(z, 1e-30)


@functools.partial(
    jax.jit, static_argnames=("interpret", "block_q", "kv_chunk")
)
def prefill_attention_pallas(
    q: jax.Array,  # [B, Sq, H, hd]
    k_cache: jax.Array,  # [B, K, Skv, hd]
    v_cache: jax.Array,  # [B, K, Skv, hd]
    q_pos: jax.Array,  # [B, Sq] absolute positions
    seq_lens: jax.Array,  # [B] valid kv per row
    *,
    block_q: int = PREFILL_BLOCK_Q,
    kv_chunk: int = PREFILL_KV_CHUNK,
    interpret: bool = False,
) -> jax.Array:
    """Flash-attention prefill — drop-in for :func:`model.attention_xla`.

    Requires ``Sq % block_q == 0`` (or ``Sq < block_q``, which shrinks the
    block) and ``Skv % kv_chunk == 0`` (ditto); the engine's power-of-two
    prefill chunks and window buckets satisfy both.  Callers should fall
    back to the XLA path otherwise (see ``model.prefill_attention``).
    """
    B, Sq, H, hd = q.shape
    K, Skv = k_cache.shape[1], k_cache.shape[2]
    G = H // K
    block_q = min(block_q, Sq)
    kv_chunk = min(kv_chunk, Skv)
    if Sq % block_q or Skv % kv_chunk:
        raise ValueError(
            f"prefill_attention_pallas: Sq={Sq} %% block_q={block_q} and "
            f"Skv={Skv} %% kv_chunk={kv_chunk} must be 0"
        )
    nq = Sq // block_q

    # [B, Sq, H, hd] -> [B, K, G, Sq, hd]: kv-head-major query layout
    qg = q.reshape(B, Sq, K, G, hd).transpose(0, 2, 3, 1, 4)

    out = pl.pallas_call(
        functools.partial(_prefill_attn_kernel, kv_chunk=kv_chunk),
        grid=(B, K, nq),
        in_specs=[
            pl.BlockSpec((1, block_q), lambda b, k, qi: (b, qi)),  # q_pos
            pl.BlockSpec((1,), lambda b, k, qi: (b,)),  # seq_lens
            pl.BlockSpec(
                (1, 1, G, block_q, hd), lambda b, k, qi: (b, k, 0, qi, 0)
            ),
            pl.BlockSpec((1, 1, Skv, hd), lambda b, k, qi: (b, k, 0, 0)),
            pl.BlockSpec((1, 1, Skv, hd), lambda b, k, qi: (b, k, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G, block_q, hd), lambda b, k, qi: (b, k, 0, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, Sq, hd), jnp.float32),
        interpret=interpret,
    )(q_pos, seq_lens, qg, k_cache, v_cache)

    # [B, K, G, Sq, hd] -> [B, Sq, H, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, hd).astype(q.dtype)
