"""HF checkpoint → sharded JAX params.

Loads a *local* Llama-family HF directory (config.json + safetensors) into
the stacked-layer pytree of :mod:`calfkit_tpu.inference.model`, placing each
tensor straight onto its NamedSharding so no host copy of the full model
lingers (model-side "checkpointing is loading", SURVEY.md §5).

Weight name mapping (HF → ours):
    model.embed_tokens.weight                     → embed [V, D]
    model.layers.{i}.self_attn.{q,k,v}_proj.weight→ wq/wk/wv (transposed,
                                                    reshaped to [D, N, hd])
    model.layers.{i}.self_attn.o_proj.weight      → wo [H, hd, D]
    model.layers.{i}.mlp.{gate,up,down}_proj.weight → w_gate/w_up/w_down
    model.layers.{i}.{input,post_attention}_layernorm.weight → norms
    model.norm.weight                              → final_norm
    lm_head.weight                                 → lm_head [D, V]
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import numpy as np

from calfkit_tpu.inference.config import ModelConfig

logger = logging.getLogger(__name__)


def config_from_hf(path: str | Path) -> ModelConfig:
    raw = json.loads((Path(path) / "config.json").read_text())
    return ModelConfig(
        name=raw.get("_name_or_path", str(path)),
        vocab_size=raw["vocab_size"],
        d_model=raw["hidden_size"],
        n_layers=raw["num_hidden_layers"],
        n_heads=raw["num_attention_heads"],
        n_kv_heads=raw.get("num_key_value_heads", raw["num_attention_heads"]),
        d_ff=raw["intermediate_size"],
        rope_theta=raw.get("rope_theta", 10000.0),
        norm_eps=raw.get("rms_norm_eps", 1e-5),
        max_seq_len=raw.get("max_position_embeddings", 2048),
        tie_embeddings=raw.get("tie_word_embeddings", False),
    )


def _open_safetensors(path: Path) -> dict[str, Any]:
    """name -> lazy tensor getter across all shards."""
    from safetensors import safe_open  # ships with transformers

    index_file = path / "model.safetensors.index.json"
    files: dict[str, Path] = {}
    if index_file.exists():
        index = json.loads(index_file.read_text())
        for name, shard in index["weight_map"].items():
            files[name] = path / shard
    else:
        single = path / "model.safetensors"
        if not single.exists():
            raise FileNotFoundError(f"no safetensors found under {path}")
        with safe_open(str(single), framework="np") as f:
            for name in f.keys():
                files[name] = single
    return files


def load_params(
    path: str | Path,
    config: ModelConfig,
    shardings: dict[str, Any],
    *,
    quantize: str | None = None,
) -> dict[str, Any]:
    """Load + transpose + stack + shard-place the checkpoint.

    ``quantize="int8"``/``"int4"`` quantizes each matmul weight ON HOST
    before the device_put, so device memory never holds a full-precision
    copy — the path that fits Llama-3-8B on one 16 GB chip (int8) or in
    ~4 GB of weights (int4, packed nibbles + group scales).  Pass
    shardings already expanded by
    :func:`calfkit_tpu.inference.quant.quantize_shardings`.
    """
    import jax
    from safetensors import safe_open

    if quantize not in (None, "int8", "int4"):
        raise ValueError(f"unsupported quantization {quantize!r}")

    path = Path(path)
    files = _open_safetensors(path)
    handles: dict[Path, Any] = {}

    def get(name: str) -> np.ndarray:
        f = files[name]
        if f not in handles:
            handles[f] = safe_open(str(f), framework="np").__enter__()
        return handles[f].get_tensor(name)

    try:
        return _build_params(config, shardings, get, quantize)
    finally:
        for handle in handles.values():
            handle.__exit__(None, None, None)


def _build_params(
    config: ModelConfig,
    shardings: dict[str, Any],
    get: Any,
    quantize: str | None,
) -> dict[str, Any]:
    import jax

    D, H, K, hd = config.d_model, config.n_heads, config.n_kv_heads, config.head_dim
    L = config.n_layers
    _quant_axes: dict[str, tuple[int, ...]] = {}
    _bits = 8 if quantize == "int8" else 4
    if quantize in ("int8", "int4"):
        from calfkit_tpu.inference.quant import (
            LAYER_REDUCTION_AXES,
            LM_HEAD_REDUCTION_AXES,
        )

        _quant_axes = {**LAYER_REDUCTION_AXES, "lm_head": LM_HEAD_REDUCTION_AXES}

    def put(arr: np.ndarray, sharding: Any, name: str = "") -> Any:
        axes = _quant_axes.get(name)
        if axes is not None:
            from calfkit_tpu.inference.quant import quantize_array_host

            q = quantize_array_host(arr, axes, bits=_bits)
            packed_key = next(k for k in q if k != "scale")
            packed_sh = sharding.get(packed_key, sharding.get("__q4__"))
            if packed_sh is None:
                # a silent fallback here would device_put int4 bytes under
                # an int8 spec — fail loudly on the bits mismatch instead
                raise ValueError(
                    f"shardings for {name!r} were expanded for a different "
                    f"quantization than quantize={'int4' if _bits == 4 else 'int8'!r}"
                )
            return {
                packed_key: jax.device_put(q[packed_key], packed_sh),
                "scale": jax.device_put(q["scale"], sharding["scale"]),
            }
        return jax.device_put(arr.astype(np.dtype(config.dtype)), sharding)

    def stack(fmt: str, transform: Any) -> np.ndarray:
        return np.stack([transform(get(fmt.format(i))) for i in range(L)])

    ls = shardings["layers"]
    params: dict[str, Any] = {
        "embed": put(get("model.embed_tokens.weight"), shardings["embed"]),
        "layers": {
            # HF projections are [out, in]; ours are [in, heads, hd]
            "wq": put(
                stack(
                    "model.layers.{}.self_attn.q_proj.weight",
                    lambda w: w.T.reshape(D, H, hd),
                ),
                ls["wq"],
                "wq",
            ),
            "wk": put(
                stack(
                    "model.layers.{}.self_attn.k_proj.weight",
                    lambda w: w.T.reshape(D, K, hd),
                ),
                ls["wk"],
                "wk",
            ),
            "wv": put(
                stack(
                    "model.layers.{}.self_attn.v_proj.weight",
                    lambda w: w.T.reshape(D, K, hd),
                ),
                ls["wv"],
                "wv",
            ),
            "wo": put(
                stack(
                    "model.layers.{}.self_attn.o_proj.weight",
                    lambda w: w.T.reshape(H, hd, D),
                ),
                ls["wo"],
                "wo",
            ),
            "w_gate": put(
                stack("model.layers.{}.mlp.gate_proj.weight", lambda w: w.T),
                ls["w_gate"],
                "w_gate",
            ),
            "w_up": put(
                stack("model.layers.{}.mlp.up_proj.weight", lambda w: w.T),
                ls["w_up"],
                "w_up",
            ),
            "w_down": put(
                stack("model.layers.{}.mlp.down_proj.weight", lambda w: w.T),
                ls["w_down"],
                "w_down",
            ),
            "attn_norm": put(
                stack("model.layers.{}.input_layernorm.weight", lambda w: w),
                ls["attn_norm"],
            ),
            "mlp_norm": put(
                stack(
                    "model.layers.{}.post_attention_layernorm.weight", lambda w: w
                ),
                ls["mlp_norm"],
            ),
        },
        "final_norm": put(get("model.norm.weight"), shardings["final_norm"]),
    }
    if not config.tie_embeddings:
        params["lm_head"] = put(
            get("lm_head.weight").T, shardings["lm_head"], "lm_head"
        )
    logger.info("loaded %s params", config.name)
    return params
