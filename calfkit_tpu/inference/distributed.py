"""Multi-host initialization for the inference backend.

The two-tier distributed design (SURVEY §2.4 / §5 "distributed
communication backend"):

- **DCN tier — the agent mesh.** Envelopes, control-plane tables, and
  fan-out state travel over the mesh transport (Kafka/meshd/in-memory).
  This tier is host-count-agnostic: more Workers in a consumer group IS
  the scale-out story, exactly like the reference's Kafka backend.
- **ICI/DCN tier — inside the engine.** jax collectives under GSPMD.  On
  one host this needs nothing.  On a TPU pod slice spanning hosts, every
  host runs the SAME engine process and jax must be initialized for
  multi-process so ``jax.devices()`` is the GLOBAL device list and the
  engine's dp×tp (and sp) meshes span the pod — XLA then routes
  collectives over ICI within a slice and DCN across slices.

This module owns that second tier's bring-up.  It is deliberately thin:
the heavy lifting IS ``jax.distributed.initialize``, and TPU pod runtimes
(GKE, queued resources) set the cluster-discovery env vars themselves —
on those, ``initialize_multihost()`` with no arguments does the right
thing.  For manual bring-up (e.g. two CPU hosts in tests, or bare-metal),
pass/export the three coordinates explicitly:

    CALFKIT_COORDINATOR=10.0.0.1:8476 CALFKIT_NUM_PROCESSES=2 \
    CALFKIT_PROCESS_ID=0 python serve.py

Reference seam: the reference has no analog (its compute tier is a remote
HTTPS service); this is the NCCL/MPI-equivalent bring-up the TPU build
owns, mapped onto jax's runtime.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

logger = logging.getLogger(__name__)

_ENV_COORDINATOR = "CALFKIT_COORDINATOR"
_ENV_NUM_PROCESSES = "CALFKIT_NUM_PROCESSES"
_ENV_PROCESS_ID = "CALFKIT_PROCESS_ID"


@dataclass(frozen=True)
class MultihostInfo:
    """What the engine needs to know after bring-up."""

    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1


def initialize_multihost(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> MultihostInfo:
    """Initialize jax for multi-process serving; safe to call on one host.

    Resolution order per coordinate: explicit argument →
    ``CALFKIT_COORDINATOR``/``CALFKIT_NUM_PROCESSES``/``CALFKIT_PROCESS_ID``
    env vars → jax's own cluster auto-detection (TPU pod runtimes).  With
    no coordinates from any source, this is a no-op single-process setup —
    the quickstart path never pays for distribution.

    Call BEFORE constructing an :class:`InferenceEngine` (backend init
    must not have happened yet, per jax's contract).  After it returns,
    build engines with ``tp``/``dp`` sized to the GLOBAL device count;
    each host admits only its own requests, but compilation and
    collectives span the pod.
    """
    import jax

    coordinator = coordinator or os.environ.get(_ENV_COORDINATOR)
    if num_processes is None and (raw := os.environ.get(_ENV_NUM_PROCESSES)):
        num_processes = int(raw)
    if process_id is None and (raw := os.environ.get(_ENV_PROCESS_ID)):
        process_id = int(raw)

    given = {
        "coordinator": coordinator is not None,
        "num_processes": num_processes is not None,
        "process_id": process_id is not None,
    }
    if any(given.values()) and not all(given.values()):
        # fail HERE with a config error, not deep inside jax with None fields
        missing = [k for k, ok in given.items() if not ok]
        raise ValueError(
            "multi-host coordinates must be set together "
            f"(missing: {', '.join(missing)}); set all three of "
            f"{_ENV_COORDINATOR}/{_ENV_NUM_PROCESSES}/{_ENV_PROCESS_ID} "
            "or none"
        )

    if all(given.values()):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.info(
            "jax.distributed initialized: process %s of %s via %s",
            jax.process_index(), jax.process_count(), coordinator,
        )
    else:
        # TPU pod runtimes are auto-detected by jax.distributed.initialize
        # with no args — but bare single-host (CPU/dev) raises there, so
        # only attempt when jax reports a cluster environment
        try:
            from jax._src.clusters import ClusterEnv

            detected = ClusterEnv.auto_detect_unset_distributed_params(
                None, None, None, None, None, None
            )[0] is not None
        except Exception:  # noqa: BLE001 - private API; see warning below
            # LOUD degradation: if this private probe breaks on a jax
            # upgrade, a real pod would silently serve host-local meshes —
            # make that failure mode visible in logs
            logger.warning(
                "cluster auto-detection unavailable (jax internals moved?); "
                "assuming single-process — on a pod, set %s/%s/%s explicitly",
                _ENV_COORDINATOR, _ENV_NUM_PROCESSES, _ENV_PROCESS_ID,
                exc_info=True,
            )
            detected = False
        if detected:
            jax.distributed.initialize()
            logger.info(
                "jax.distributed auto-initialized: process %s of %s",
                jax.process_index(), jax.process_count(),
            )

    return MultihostInfo(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
    )


def assert_engine_fits(info: MultihostInfo, tp: int, dp: int) -> None:
    """Loudly reject a mesh that over-asks — or, multi-host, under-uses —
    the pod.

    Single-host under-use is legitimate (an engine on 1 of 8 chips).
    Multi-host under-use is not: a mesh that omits another process's
    addressable devices hangs or errors at the first collective, so every
    pod device must be in the mesh.
    """
    need = tp * dp
    if need > info.global_devices:
        raise ValueError(
            f"engine mesh tp={tp} x dp={dp} needs {need} devices but the "
            f"{'pod' if info.is_multihost else 'host'} has "
            f"{info.global_devices}"
        )
    if info.is_multihost and need != info.global_devices:
        raise ValueError(
            f"multi-host engine mesh must span the whole pod: tp x dp = "
            f"{need} but {info.num_processes} processes contribute "
            f"{info.global_devices} devices (a partial mesh omits another "
            "process's devices and deadlocks at the first collective)"
        )
