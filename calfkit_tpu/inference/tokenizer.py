"""Tokenizer seam: HF tokenizers when a checkpoint directory is given, a
dependency-free byte tokenizer otherwise (tests / zero-weights smoke runs).
"""

from __future__ import annotations

from typing import Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...

    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """UTF-8 bytes + 3 specials: deterministic, vocab 259, no deps."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _OFFSET = 3

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    def encode(self, text: str) -> list[int]:
        return [b + self._OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: list[int]) -> str:
        # ids beyond the byte range (a model vocab can be larger) are dropped
        data = bytes(
            i - self._OFFSET
            for i in ids
            if self._OFFSET <= i < 256 + self._OFFSET
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers AutoTokenizer over a LOCAL directory (zero egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # lazy: heavyweight import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        # id 0 is a legitimate special-token id — never `or` these
        def _id(value: int | None, default: int) -> int:
            return value if value is not None else default

        self.bos_id = _id(self._tok.bos_token_id, 1)
        self.eos_id = _id(self._tok.eos_token_id, 2)
        self.pad_id = _id(self._tok.pad_token_id, 0)

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)
