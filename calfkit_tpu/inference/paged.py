"""Paged KV-cache management (host side).

Why paging (reference anchor: SURVEY.md §5 long-context — "Ragged Paged
Attention for TPU"; VERDICT r1 weak #4): the dense cache allocates
``[L, B, K, max_seq, hd]`` up front — Llama-3-8B at B=128, S=1024 is ~17 GB
of KV, over a 16 GB chip before weights.  Paging allocates a fixed pool of
``page_size``-token pages and gives each request only the pages its actual
(prompt + requested max_new) footprint needs, so many short streams fit
where few dense rows would.

Design decisions:

- **Page 0 is the trash page.**  Never allocated.  Block-table rows start
  as zeros, and consolidation scatters from *inactive* batch rows into page
  0 — a retired slot's stale row can keep "writing" harmlessly even after
  its real pages were reused by another request.
- **Reserve at admission.**  A request's full worst-case footprint
  (``prompt + max_new`` tokens, capped by ``max_seq``) is allocated before
  prefill; if the pool can't cover it the request waits in the queue.  No
  mid-flight OOM, no preemption machinery.  (On-demand growth would pack
  tighter when generations stop early at EOS; noted as future work.)
- The allocator is plain host Python.  It is only touched from the engine's
  scheduler flow (admission on the event loop, retirement on the decode
  thread — never concurrently, same discipline as the slot free-list).
"""

from __future__ import annotations

import numpy as np

TRASH_PAGE = 0


class PageAllocator:
    """Fixed pool of KV pages; page 0 reserved as the trash page."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._held: dict[int, list[int]] = {}  # slot -> pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def held_slots(self) -> dict[int, int]:
        """slot -> page count currently reserved (public, for stats/tests)."""
        return {slot: len(pages) for slot, pages in self._held.items()}

    def alloc(self, slot: int, n: int) -> list[int] | None:
        """Reserve ``n`` pages for ``slot``; None if the pool can't cover it."""
        if slot in self._held:
            raise ValueError(f"slot {slot} already holds pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._held[slot] = pages
        return pages

    def free(self, slot: int) -> None:
        """Return ``slot``'s pages to the pool (idempotent)."""
        self._free.extend(self._held.pop(slot, ()))


def pages_needed(total_tokens: int, page_size: int) -> int:
    return -(-total_tokens // page_size)


def table_row(pages: list[int], max_pages: int) -> np.ndarray:
    """A block-table row: allocated page ids, padded with the trash page."""
    row = np.full((max_pages,), TRASH_PAGE, np.int32)
    row[: len(pages)] = pages
    return row
