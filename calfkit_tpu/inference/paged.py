"""Paged KV-cache management (host side).

Why paging (reference anchor: SURVEY.md §5 long-context — "Ragged Paged
Attention for TPU"; VERDICT r1 weak #4): the dense cache allocates
``[L, B, K, max_seq, hd]`` up front — Llama-3-8B at B=128, S=1024 is ~17 GB
of KV, over a 16 GB chip before weights.  Paging allocates a fixed pool of
``page_size``-token pages and gives each request only the pages its actual
(prompt + requested max_new) footprint needs, so many short streams fit
where few dense rows would.

Design decisions:

- **Page 0 is the trash page.**  Never allocated.  Block-table rows start
  as zeros, and consolidation scatters from *inactive* batch rows into page
  0 — a retired slot's stale row can keep "writing" harmlessly even after
  its real pages were reused by another request.
- **Reserve at admission.**  A request's full worst-case footprint
  (``prompt + max_new`` tokens, capped by ``max_seq``) is allocated before
  prefill; if the pool can't cover it the request waits in the queue.  No
  mid-flight OOM, no preemption machinery.  (On-demand growth would pack
  tighter when generations stop early at EOS; noted as future work.)
- The allocator is plain host Python.  It is only touched from the engine's
  scheduler flow (admission on the event loop, retirement on the decode
  thread — never concurrently, same discipline as the slot free-list).

Speculative decoding and pages: a verify wave writes k+1 chunk positions
through the block tables, then acceptance advances each row's length by
only ``accepted + 1`` — the rejected tail's K/V sits in the row's OWN
reserved pages beyond its valid length and is overwritten by the next
wave, so "rollback" is a length update, never a page operation.  Prefix-
cache hashing stays consistent automatically: only FULL PAGES OF THE
PROMPT are ever registered (``chain_hashes`` runs over the prompt alone),
and the chunk's first write lands at ``lens >= prompt_len``, past every
registered page — partially-accepted blocks are always private pages.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

TRASH_PAGE = 0


class PageAllocator:
    """Fixed pool of KV pages; page 0 reserved as the trash page."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._held: dict[int, list[int]] = {}  # slot -> pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def held_slots(self) -> dict[int, int]:
        """slot -> page count currently reserved (public, for stats/tests)."""
        return {slot: len(pages) for slot, pages in self._held.items()}

    def alloc(self, slot: int, n: int) -> list[int] | None:
        """Reserve ``n`` pages for ``slot``; None if the pool can't cover it."""
        if slot in self._held:
            raise ValueError(f"slot {slot} already holds pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._held[slot] = pages
        return pages

    def free(self, slot: int) -> None:
        """Return ``slot``'s pages to the pool (idempotent)."""
        self._free.extend(self._held.pop(slot, ()))

    def transfer_out(self, slot: int, pages: "list[int]") -> None:
        """Move ``pages`` out of ``slot``'s holding WITHOUT freeing them —
        ownership passes to the prefix cache (so a later ``free(slot)``
        cannot return shared pages to the pool under live readers)."""
        held = self._held.get(slot)
        if held is None:
            return
        moving = set(pages)
        self._held[slot] = [p for p in held if p not in moving]

    def give_back(self, pages: "list[int]") -> None:
        """Return cache-owned pages to the pool (prefix-cache eviction)."""
        self._free.extend(pages)


def chain_hashes(prompt: "list[int]", page_size: int) -> "list[bytes]":
    """Position-dependent content hash per FULL page of the prompt:
    hash_i = H(hash_{i-1} || tokens[i*ps:(i+1)*ps]).  Chaining makes a
    page's identity its entire prefix, so equal pages at different
    positions (or after different histories) never alias."""
    import hashlib

    out: list[bytes] = []
    prev = b""
    for i in range(len(prompt) // page_size):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        # blocking-ok: host token LIST → bytes for hashing, never a
        # device array — nothing syncs
        h.update(np.asarray(
            prompt[i * page_size:(i + 1) * page_size], np.int32
        ).tobytes())
        prev = h.digest()
        out.append(prev)
    return out


class PrefixCache:
    """Automatic prefix caching over the page pool (the vLLM-APC analog,
    sized for agent serving: every run of the same agent re-sends the
    same instruction/history prefix, so its KV pages are recomputed
    per-turn without this).

    Ownership protocol: a landed request's full-prompt pages transfer
    from the allocator to this cache (``PageAllocator.transfer_out``);
    live requests hold references; zero-reference entries sit in an LRU
    and are evicted back to the allocator when admission runs dry.  All
    mutation happens from the engine's scheduler flow (same
    single-writer discipline as the allocator)."""

    def __init__(self) -> None:
        self._entries: dict[bytes, int] = {}      # chain hash -> page
        self._hash_of: dict[int, bytes] = {}
        self._refs: dict[int, int] = {}            # live slot references
        self._lru: "OrderedDict[bytes, None]" = OrderedDict()

    @property
    def size(self) -> int:
        return len(self._entries)

    def lookup(self, hashes: "list[bytes]") -> "list[int]":
        """Longest cached chain prefix → its pages, in sequence order."""
        pages: list[int] = []
        for h in hashes:
            page = self._entries.get(h)
            if page is None:
                break
            pages.append(page)
        return pages

    def acquire(self, pages: "list[int]") -> None:
        for page in pages:
            self._refs[page] += 1
            self._lru.pop(self._hash_of[page], None)

    def release(self, pages: "list[int]") -> None:
        for page in pages:
            self._refs[page] -= 1
            if self._refs[page] <= 0:
                self._lru[self._hash_of[page]] = None

    def register(self, h: bytes, page: int) -> bool:
        """False when the hash is already cached (the caller's duplicate
        page stays private to its slot and frees at retirement)."""
        if h in self._entries:
            return False
        self._entries[h] = page
        self._hash_of[page] = h
        self._refs[page] = 0
        return True

    def evict(
        self, need: int, allocator: PageAllocator, *, ledger=None
    ) -> int:
        """Pop up to ``need`` zero-reference entries (oldest released
        first) back into the allocator's free list.  Evicting a chain's
        middle page strands its suffix entries (unreachable by lookup);
        they drain through this same LRU once released.  ``ledger`` is
        the capacity observatory's per-page hook (ISSUE 19): only the
        cache knows WHICH pages the LRU picked, so attribution must be
        told here, at the reclaim itself."""
        freed = 0
        while freed < need and self._lru:
            h, _ = self._lru.popitem(last=False)
            page = self._entries.pop(h)
            del self._hash_of[page]
            del self._refs[page]
            allocator.give_back([page])
            if ledger is not None:
                ledger.evicted(page)
            freed += 1
        return freed


def pages_needed(total_tokens: int, page_size: int) -> int:
    return -(-total_tokens // page_size)


def table_row(pages: list[int], max_pages: int) -> np.ndarray:
    """A block-table row: allocated page ids, padded with the trash page."""
    row = np.full((max_pages,), TRASH_PAGE, np.int32)
    row[: len(pages)] = pages
    return row
