"""Ring attention: sequence-parallel causal attention over an ``sp`` mesh axis.

The long-context scaling path (SURVEY §5 long-context; the build brief makes
sequence/context parallelism first-class): when a prompt is too long for one
chip's HBM (activations + KV), shard the SEQUENCE over devices and rotate
K/V blocks around the ring with ``ppermute`` while each device keeps its
query shard resident.  Per rotation step every device computes one
(Q-block × K/V-block) partial attention and folds it into a running
flash-style (o·z, m, z) accumulator; after ``sp`` rotations each device
holds exact attention output for its own query block.

Design notes (tpu-first, not a port):

- expressed with ``shard_map`` so the collective schedule is explicit —
  ppermute rides ICI neighbor links, never DCN, and XLA can overlap the
  rotation's communication with the current block's compute;
- causal + validity masking is decided per (query-block, kv-block) pair
  from absolute positions and per-sequence lengths;
- the final rotation is skipped (its result would be discarded): n-1
  ppermute hops move every block all the way around;
- the accumulator is the same (unnormalized o, max, z) triple used by the
  decode kernels (:func:`model.logsumexp_merge`) — one merge law everywhere;
- block layout is ``[sp, block, ...]``: block i on device i is sequence
  positions ``[i·block, (i+1)·block)`` — contiguous shards, so the output
  reassembles with a plain reshape;
- the transformer block math in :func:`prefill_sequence_parallel` is the
  SAME helpers (:func:`model.attn_qkv` / :func:`model.attn_out_mlp` /
  :func:`model.lm_logits`) the dense prefill and decode paths use.
"""

from __future__ import annotations

import functools
import inspect
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma in jax 0.8
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, **kwargs):
    if "check_rep" in kwargs:
        kwargs[_CHECK_KW] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def ring_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,  # [B, S, K, hd]
    mesh: Mesh,
    *,
    axis: str = "sp",
    seq_lens: jax.Array | None = None,  # [B] valid tokens; None = all S
) -> jax.Array:
    """Causal GQA attention with the sequence dimension sharded over
    ``axis``; → [B, S, H, hd] sharded the same way.

    ``seq_lens`` masks ragged batches: positions ≥ a row's length neither
    attend usefully nor get attended (their outputs are garbage and must be
    ignored by the caller, exactly like the dense path's pad positions).
    Requires ``S % mesh.shape[axis] == 0``.
    """
    sp = mesh.shape[axis]
    B, S, H, hd = q.shape
    if S % sp:
        raise ValueError(f"sequence {S} must divide over {axis}={sp}")
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)
    spec = P(None, axis, None, None)
    len_spec = P(None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec, len_spec),
        out_specs=spec,
        check_rep=False,
    )
    def ring(q_blk, k_blk, v_blk, lens):
        # q_blk: [B, S/sp, H, hd] — this device's query block (resident)
        # k_blk/v_blk: rotating K/V block, starts as our own
        my_idx = lax.axis_index(axis)
        n = lax.psum(1, axis)
        blk = q_blk.shape[1]
        scale = 1.0 / math.sqrt(hd)
        Kh = k_blk.shape[2]
        G = H // Kh
        qg = (q_blk * scale).astype(jnp.float32).reshape(B, blk, Kh, G, hd)
        q_pos = my_idx * blk + jnp.arange(blk)  # absolute query positions

        def fold(acc, kc, vc, r):
            o, m, z = acc
            # kv block r originated on device (my_idx - r) mod n
            src = (my_idx - r) % n
            kv_pos = src * blk + jnp.arange(blk)
            s = jnp.einsum(
                "bqkgh,bskh->bkgqs",
                qg,
                kc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )  # [B, K, G, blk_q, blk_kv]
            causal = kv_pos[None, :] <= q_pos[:, None]  # [blk_q, blk_kv]
            valid = kv_pos[None, :] < lens[:, None]  # [B, blk_kv]
            mask = causal[None] & valid[:, None]  # [B, blk_q, blk_kv]
            s = jnp.where(mask[:, None, None], s, -1e30)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            m_new = jnp.maximum(m_new, -1e29)  # all-masked steps stay finite
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            z_new = z * alpha + jnp.sum(p, axis=-1, keepdims=True)
            o_new = o * alpha + jnp.einsum(
                "bkgqs,bskh->bkgqh",
                p,
                vc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return o_new, m_new, z_new

        def step(carry, r):
            acc, kc, vc = carry
            acc = fold(acc, kc, vc, r)
            # rotate K/V one hop around the ring (device d -> d+1)
            perm = [(d, (d + 1) % n) for d in range(n)]
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (acc, kc, vc), None

        acc0 = (
            jnp.zeros((B, Kh, G, blk, hd), jnp.float32),
            jnp.full((B, Kh, G, blk, 1), -1e30, jnp.float32),
            jnp.zeros((B, Kh, G, blk, 1), jnp.float32),
        )
        # n-1 rotating steps + one final fold WITHOUT the rotation (its
        # result would be discarded — that last ppermute pair is pure waste)
        (acc, kc, vc), _ = lax.scan(step, (acc0, k_blk, v_blk), jnp.arange(n - 1))
        o, m, z = fold(acc, kc, vc, n - 1)
        out = o / jnp.maximum(z, 1e-30)  # [B, K, G, blk, hd]
        out = jnp.moveaxis(out, 3, 1).reshape(B, blk, H, hd)
        return out.astype(q_blk.dtype)

    return ring(q, k, v, seq_lens.astype(jnp.int32))


def single_device_causal_attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,  # [B, S, K, hd]
    seq_lens: jax.Array | None = None,
) -> jax.Array:
    """The dense reference the ring must match — a thin wrapper over the
    serving path's :func:`model.attention_xla` (one attention math)."""
    from calfkit_tpu.inference.model import attention_xla

    B, S, _, _ = q.shape
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    return attention_xla(
        q, jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2), positions, seq_lens
    )


# --------------------------------------------------------------------------- #
# sequence-parallel prefill
# --------------------------------------------------------------------------- #


def prefill_sequence_parallel(
    params: dict,
    config,
    tokens: jax.Array,  # [B, S] int32 — S divides the sp axis
    mesh: Mesh,
    *,
    axis: str = "sp",
    seq_lens: jax.Array | None = None,  # [B] true prompt lengths (ragged)
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Run a long-prompt prefill with the sequence sharded over ``axis``.

    Activations AND the produced KV stay sequence-sharded on device
    throughout (each chip holds S/sp of every layer's K/V); only attention
    communicates, via the ring.  Returns:

    - ``last_logits`` [B, V] — logits at each row's LAST VALID position
      (``seq_lens - 1``), what sampling needs;
    - ``(k, v)`` [L, B, K, S, hd] sequence-sharded over ``axis``; positions
      ≥ a row's length hold garbage exactly like the dense path's scratch
      (mask with ``seq_lens`` downstream).

    Reference seam: this is the long-context entry SURVEY §5 prescribes
    leaving block-wise; the serving engine uses it when a prompt exceeds
    single-chip prefill capacity.
    """
    B, S = tokens.shape
    sp = mesh.shape[axis]
    if S % sp:
        raise ValueError(f"prompt length {S} must divide over {axis}={sp}")
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)

    tok_spec = P(None, axis)
    tokens = jax.device_put(tokens, NamedSharding(mesh, tok_spec))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    positions = jax.device_put(positions, NamedSharding(mesh, tok_spec))

    try:
        fn = _prefill_sp_jit(config, mesh, axis)
    except TypeError:
        # unhashable config/mesh: fall back to an uncached jit (correct,
        # just re-traced per call) rather than narrowing the contract
        fn = _build_prefill_sp(config, mesh, axis)
    return fn(params, tokens, positions, seq_lens.astype(jnp.int32))


@functools.lru_cache(maxsize=32)
def _prefill_sp_jit(config, mesh: Mesh, axis: str):
    """One traced+compiled sp prefill per (config, mesh, axis) — eager
    re-tracing of the L-layer scan per call would dominate short prompts."""
    return _build_prefill_sp(config, mesh, axis)


def _build_prefill_sp(config, mesh: Mesh, axis: str):
    from calfkit_tpu.inference import model as M

    eps = config.norm_eps

    def fn(params, tokens, positions, seq_lens):
        S = tokens.shape[1]
        x = params["embed"][tokens]  # [B, S, D] sequence-sharded (gather)
        cos, sin = M.rope_tables(positions, config.head_dim, config.rope_theta)

        def layer_body(x, lp):
            q, k, v = M.attn_qkv(x, lp, cos, sin, eps)
            attn = ring_attention(q, k, v, mesh, axis=axis, seq_lens=seq_lens)
            return M.attn_out_mlp(x, attn, lp, eps), (k, v)

        x, (ks, vs) = lax.scan(layer_body, x, params["layers"])
        # ks/vs: [L, B, S, K, hd] sequence-sharded; cache wants K-major
        k_cache = jnp.swapaxes(ks, 2, 3)  # [L, B, K, S, hd]
        v_cache = jnp.swapaxes(vs, 2, 3)

        # gather the last-valid hidden state FIRST, then the head:
        # full-sequence logits would materialize [B, S, V] (gigabytes at
        # 128k vocab and long S) for one row each
        idx = jnp.clip(seq_lens - 1, 0, S - 1)
        x_last = jnp.take_along_axis(x, idx[:, None, None], axis=1)
        last_logits = M.lm_logits(x_last, params, eps)[:, 0]
        return last_logits, (k_cache, v_cache)

    return jax.jit(fn)


# --------------------------------------------------------------------------- #
# context-parallel decode over a sequence-sharded prefix
# --------------------------------------------------------------------------- #


def context_parallel_attention(
    q: jax.Array,  # [B, 1, H, hd] one decode step's queries
    k_prefix: jax.Array,  # [B, K, S, hd] sequence-sharded over `axis` (dim 2)
    v_prefix: jax.Array,
    prefix_lens: jax.Array,  # [B] valid prefix tokens
    mesh: Mesh,
    *,
    axis: str = "sp",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Decode attention over a prefix that STAYS sequence-sharded.

    Each device scores its own shard (no rotation needed — a decode query
    attends everywhere, so partial (o, m, z) merge exactly via the global
    max + rescaled sums: two psum/pmax collectives instead of moving any
    KV).  Returns the (unnormalized o [B,K,G,hd], m [B,K,G,1], z [B,K,G,1])
    triple for :func:`model.logsumexp_merge` with the fresh-token source —
    the seam that makes ring-prefilled caches directly decodable.
    """
    B, _, H, hd = q.shape
    Kh = k_prefix.shape[1]
    G = H // Kh
    S = k_prefix.shape[2]
    sp = mesh.shape[axis]
    if S % sp:
        raise ValueError(f"prefix length {S} must divide over {axis}={sp}")
    blk = S // sp

    q_spec = P(None, None, None, None)
    kv_spec = P(None, None, axis, None)
    len_spec = P(None)
    out_spec = P(None, None, None, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, len_spec),
        out_specs=(out_spec, out_spec, out_spec),
        check_rep=False,
    )
    def cp(qr, kb, vb, lens):
        from calfkit_tpu.inference.model import masked_attention_source

        my_idx = lax.axis_index(axis)
        qg = qr[:, 0].reshape(B, Kh, G, hd)
        pos = my_idx * blk + jnp.arange(blk)  # this shard's absolute span
        valid = pos[None, :] < lens[:, None]  # [B, blk]
        o, m, z = masked_attention_source(qg, kb, vb, valid)
        # exact global merge: rescale every shard to the global max, sum
        m_all = lax.pmax(m, axis)
        w = jnp.exp(m - m_all)
        o_all = lax.psum(o * w, axis)
        z_all = lax.psum(z * w, axis)
        return o_all, m_all, z_all

    return cp(q, k_prefix, v_prefix, prefix_lens.astype(jnp.int32))


def decode_with_sharded_prefix(
    params: dict,
    config,
    first_token: jax.Array,  # [B] the token sampled from the prefill logits
    prefix: tuple[jax.Array, jax.Array],  # [L, B, K, S, hd] sharded over axis
    prefix_lens: jax.Array,  # [B]
    mesh: Mesh,
    steps: int,
    *,
    axis: str = "sp",
) -> jax.Array:
    """Greedy-decode ``steps`` tokens directly against a ring-prefilled,
    still-sequence-sharded KV prefix — no resharding, no consolidation.

    One-shot convenience over :func:`decode_sp_dispatch` (the serving
    engine's carried unit): fresh K/V accumulates in a small replicated
    cache merged with the context-parallel prefix source via the shared
    logsumexp law.  → [B, steps] int32 greedy tokens.
    """
    k_prefix, v_prefix = prefix
    B = first_token.shape[0]
    L, Kh, hd = config.n_layers, config.n_kv_heads, config.head_dim
    fresh = (
        jnp.zeros((L, B, Kh, steps, hd), jnp.float32),
        jnp.zeros((L, B, Kh, steps, hd), jnp.float32),
    )
    toks, _last, _fresh = decode_sp_dispatch(
        params, config, first_token, (k_prefix, v_prefix), prefix_lens,
        fresh, jnp.int32(0), mesh, steps, axis=axis,
    )
    return toks


def decode_sp_dispatch(
    params: dict,
    config,
    token: jax.Array,  # [B] last sampled token (enters this dispatch)
    prefix: tuple[jax.Array, jax.Array],  # [L, B, K, S, hd] sharded over axis
    prefix_lens: jax.Array,  # [B]
    fresh: tuple[jax.Array, jax.Array],  # [L, B, K, cap, hd] replicated carry
    t0: jax.Array,  # scalar int32: fresh tokens already generated
    mesh: Mesh,
    steps: int,
    *,
    axis: str = "sp",
) -> tuple[jax.Array, jax.Array, tuple[jax.Array, jax.Array]]:
    """One long-lane decode DISPATCH: ``steps`` greedy tokens against a
    sequence-sharded prefix, carrying the replicated fresh cache across
    dispatches (this is the serving engine's long-context unit of work —
    the analog of the short lane's ring-buffer decode tick).

    → (toks [B, steps], last_token [B], fresh) with fresh slots
    [t0, t0+steps) filled; the cap bounds total generation per request.
    """
    k_prefix, v_prefix = prefix
    cap = fresh[0].shape[3]
    try:
        fn = _decode_sp_jit(
            config, mesh, axis, steps, token.shape[0], cap
        )
    except TypeError:  # unhashable config/mesh: uncached fallback
        fn = _build_decode_sp(
            config, mesh, axis, steps, token.shape[0], cap
        )
    return fn(
        params, token, k_prefix, v_prefix, prefix_lens,
        fresh[0], fresh[1], jnp.asarray(t0, jnp.int32),
    )


@functools.lru_cache(maxsize=32)
def _decode_sp_jit(config, mesh: Mesh, axis: str, steps: int, B: int, cap: int):
    """One compile per (config, mesh, axis, steps, B, cap) — the multi-step
    decode program is seconds of trace+compile per shape."""
    return _build_decode_sp(config, mesh, axis, steps, B, cap)


def _build_decode_sp(config, mesh: Mesh, axis: str, steps: int, B: int,
                     cap: int):
    from calfkit_tpu.inference import model as M

    Kh, hd, eps = config.n_kv_heads, config.head_dim, config.norm_eps

    def fn(params, first_token, k_prefix, v_prefix, prefix_lens,
           fresh_k0, fresh_v0, t0):
        def one_step(carry, i):
            token, fresh = carry
            fresh_k, fresh_v = fresh
            t = t0 + i  # global fresh index: carries across dispatches
            positions = (prefix_lens + t)[:, None]
            x = params["embed"][token[:, None]]
            cos, sin = M.rope_tables(positions, hd, config.rope_theta)

            def layer_body(x, inputs):
                lp, kp, vp, fk, fv = inputs
                q, k, v = M.attn_qkv(x, lp, cos, sin, eps)
                fk = lax.dynamic_update_slice(
                    fk, jnp.swapaxes(k, 1, 2).astype(fk.dtype), (0, 0, t, 0)
                )
                fv = lax.dynamic_update_slice(
                    fv, jnp.swapaxes(v, 1, 2).astype(fv.dtype), (0, 0, t, 0)
                )
                o1, m1, z1 = context_parallel_attention(
                    q, kp, vp, prefix_lens, mesh, axis=axis
                )
                qg = q.reshape(B, Kh, -1, hd)
                o2, m2, z2 = M.ring_attention_source(
                    qg,
                    jnp.transpose(fk, (2, 0, 1, 3)),  # -> [cap, B, K, hd]
                    jnp.transpose(fv, (2, 0, 1, 3)),
                    t,
                )
                attn = M.logsumexp_merge((o1, m1, z1), (o2, m2, z2))
                attn = attn.reshape(B, 1, -1, hd).astype(x.dtype)
                return M.attn_out_mlp(x, attn, lp, eps), (fk, fv)

            x, (fresh_k, fresh_v) = lax.scan(
                layer_body,
                x,
                (params["layers"], k_prefix, v_prefix, fresh_k, fresh_v),
            )
            logits = M.lm_logits(x, params, eps)[:, -1]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, (fresh_k, fresh_v)), nxt

        (last, fresh), toks = lax.scan(
            one_step, (first_token, (fresh_k0, fresh_v0)), jnp.arange(steps)
        )
        return jnp.swapaxes(toks, 0, 1), last, fresh  # toks [B, steps]

    return jax.jit(fn, donate_argnums=(5, 6))
