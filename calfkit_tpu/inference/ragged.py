"""Ragged unified prefill+decode wave math (ISSUE 6).

Pure host-side helpers for the engine's ragged wave scheduler: per-row
query descriptors for the unified attention kernel, and the token-budget
arithmetic that decides how much pending prefill a half-empty decode wave
may absorb.  No jax imports — these run at wave-formation time on the
event loop and inside the dispatch-thread packing loop, both of which
``scripts/lint_hotpath.py`` keeps free of device syncs and formatting;
keeping the module dependency-free also keeps it trivially typeable
(it sits under the real mypy gate with the rest of ``inference.*``).

The descriptor vocabulary mirrors Ragged Paged Attention (PAPERS.md,
arXiv:2604.15464): one kernel invocation consumes a batch whose rows mix

- ``decode`` rows — q_len = 1, one fresh query at position ``start``;
- ``prefill`` rows — q_len = chunk, queries at ``start .. start+chunk``;
- ``verify`` rows — q_len = k+1, the speculative multi-query read.

All three share ONE masking law: query ``j`` of a row attends kv
positions ``< min(kv_len, start + j + 1)`` — causal within the row's own
fresh span, bounded by the row's valid cache length.

:class:`RaggedRow` / :func:`build_descriptors` are the SPEC vocabulary:
tests pin the kernels' mask law against descriptors built here
(``tests/test_ragged_waves.py`` — the executable definition of what a
mixed wave means), and formation-time tooling can reason in rows.  The
engine's hot path ships the ``(q_starts, q_lens, kv_lens)`` arrays
directly (decode/verify rows derive them from ``lens``/``base_lens``
inside the jit — building python objects per dispatch would be
allocation on the packing loop).  The budget functions below ARE the
hot-path consumers: the engine calls them at formation and absorption
time every tick.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable

KIND_DECODE = 0
KIND_PREFILL = 1
KIND_VERIFY = 2

_KIND_NAMES = {KIND_DECODE: "decode", KIND_PREFILL: "prefill",
               KIND_VERIFY: "verify"}


@dataclass(frozen=True)
class RaggedRow:
    """One row of a ragged wave: what kind of work it carries, where its
    queries start (absolute cache position of query 0), how many queries
    it contributes, and how much cache is valid for it."""

    kind: int  # KIND_DECODE | KIND_PREFILL | KIND_VERIFY
    start: int  # absolute position of the row's first query
    q_len: int  # 1 (decode) | chunk (prefill) | k+1 (verify)
    kv_len: int  # valid kv length the row may attend (before its span)

    @property
    def kind_name(self) -> str:
        return _KIND_NAMES.get(self.kind, "?")

    def tokens(self) -> int:
        """Query tokens the row contributes to the wave's budget."""
        return self.q_len


def build_descriptors(
    rows: "Iterable[RaggedRow]",
) -> "tuple[list[int], list[int], list[int]]":
    """Flatten rows into the (q_starts, q_lens, kv_lens) arrays the
    unified attention entry points take (the ``kind`` is not shipped to
    the device — the mask law above is kind-agnostic by construction)."""
    starts: list[int] = []
    q_lens: list[int] = []
    kv_lens: list[int] = []
    for row in rows:
        starts.append(row.start)
        q_lens.append(row.q_len)
        kv_lens.append(row.kv_len)
    return starts, q_lens, kv_lens


def token_budget(
    configured: int, max_batch_size: int, steps: int, chunk: int,
    max_prefill_wave: int,
) -> int:
    """Resolve the wave token budget (``RuntimeConfig.ragged_token_budget``;
    0 = auto).

    Auto is deliberately generous: a full decode wave plus a full-width
    prefill wave — admission is already bounded by free slots and
    ``max_prefill_wave``, so the default budget never second-guesses it.
    Set an explicit budget to bound per-dispatch latency instead: the
    fused dispatch's compute grows with the absorbed chunk tokens, so a
    tighter budget trades prefill absorption for steadier inter-token
    latency (see the knob table in docs/inference.md)."""
    if configured > 0:
        return configured
    return max_batch_size * steps + max_prefill_wave * chunk


def fits_budget(
    budget: int, active_rows: int, steps: int, chunk_rows: int, chunk: int
) -> bool:
    """May a dispatch carrying ``active_rows`` decode rows absorb a
    ``chunk_rows``-wide prefill chunk?  Token accounting: decode
    contributes ``active_rows * steps`` query tokens (the scan), the
    chunk contributes ``chunk_rows * chunk``."""
    return active_rows * steps + chunk_rows * chunk <= budget


def wave_width_cap(
    budget: int, active_rows: int, steps: int, chunk: int
) -> int:
    """Widest prefill wave the budget lets a dispatch absorb alongside
    ``active_rows`` decode rows — never below 1 (the wave head always
    forms; a head that can't absorb simply advances in its own
    invocation until decode slack opens up)."""
    slack = budget - active_rows * steps
    return max(1, slack // chunk)
