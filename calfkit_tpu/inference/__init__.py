"""The local TPU inference backend — the seam the reference filled with
remote HTTPS APIs (SURVEY.md §1 layer 4, §2.3).

Compute path: JAX/XLA with GSPMD tensor-parallel sharding over a device mesh;
Pallas paged-attention kernels for decode; a continuous-batching engine that
the Worker drives from Kafka-partition consumption.

Import is lazy at the package boundary: nothing here pulls in jax until an
inference class is actually constructed.
"""

from typing import Any

from calfkit_tpu.inference.config import (
    ModelConfig,
    PRESETS,
    RuntimeConfig,
    SpecConfig,
)

__all__ = [
    "JaxLocalModelClient",
    "ModelConfig",
    "PRESETS",
    "RuntimeConfig",
    "SpecConfig",
    "assert_engine_fits",
    "initialize_multihost",
]


def __getattr__(name: str) -> Any:
    # lazy: importing calfkit_tpu.inference must not pull in jax
    if name == "JaxLocalModelClient":
        from calfkit_tpu.inference.client import JaxLocalModelClient

        return JaxLocalModelClient
    if name in ("initialize_multihost", "assert_engine_fits"):
        from calfkit_tpu.inference import distributed

        return getattr(distributed, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
