"""GSPMD sharding layout for the inference backend.

The scaling-model recipe: pick a mesh, annotate param/cache shardings, let
XLA insert the collectives (all-reduce on attention/MLP outputs, all-gather
on logits), profile, iterate.  Axes:

- ``tp`` — tensor parallelism *inside* one model replica: attention heads,
  MLP hidden, and vocab are split over ``tp``; XLA emits psum/all-gathers
  that ride ICI.
- ``dp`` — independent serving replicas: the batch dimension of the KV cache
  and token buffers is split over ``dp``.

Weights that don't divide evenly by the axis (e.g. 4 KV heads on tp=8) fall
back to replication for that tensor — GSPMD remains correct either way, this
just keeps layouts predictable.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from calfkit_tpu.inference.config import ModelConfig

Params = dict[str, Any]


def make_mesh(
    tp: int = 1, dp: int = 1, *, devices: list[jax.Device] | None = None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    need = tp * dp
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices (tp={tp} × dp={dp}), have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def _spec(mesh: Mesh, dims: list[tuple[int, str | None]]) -> P:
    """Build a PartitionSpec, dropping axis names whose size doesn't divide
    the dim (replicate instead)."""
    parts: list[str | None] = []
    for size, axis in dims:
        if axis is None or size % mesh.shape[axis] != 0:
            parts.append(None)
        else:
            parts.append(axis)
    return P(*parts)


def param_shardings(config: ModelConfig, mesh: Mesh) -> Params:
    """NamedSharding pytree matching :func:`model.init_params` structure."""
    D, H, K, hd, F, V = (
        config.d_model,
        config.n_heads,
        config.n_kv_heads,
        config.head_dim,
        config.d_ff,
        config.vocab_size,
    )

    def ns(dims: list[tuple[int, str | None]]) -> NamedSharding:
        return NamedSharding(mesh, _spec(mesh, dims))

    L = (config.n_layers, None)
    shardings: Params = {
        "embed": ns([(V, "tp"), (D, None)]),
        "layers": {
            "wq": ns([L, (D, None), (H, "tp"), (hd, None)]),
            "wk": ns([L, (D, None), (K, "tp"), (hd, None)]),
            "wv": ns([L, (D, None), (K, "tp"), (hd, None)]),
            "wo": ns([L, (H, "tp"), (hd, None), (D, None)]),
            "w_gate": ns([L, (D, None), (F, "tp")]),
            "w_up": ns([L, (D, None), (F, "tp")]),
            "w_down": ns([L, (F, "tp"), (D, None)]),
            "attn_norm": ns([L, (D, None)]),
            "mlp_norm": ns([L, (D, None)]),
        },
        "final_norm": ns([(D, None)]),
    }
    if not config.tie_embeddings:
        shardings["lm_head"] = ns([(D, None), (V, "tp")])
    return shardings


def cache_sharding(config: ModelConfig, mesh: Mesh, batch: int) -> NamedSharding:
    """KV cache [L, B, K, S, hd]: batch over dp, kv heads over tp."""
    return NamedSharding(
        mesh,
        _spec(
            mesh,
            [
                (config.n_layers, None),
                (batch, "dp"),
                (config.n_kv_heads, "tp"),
                (1, None),
                (config.head_dim, None),
            ],
        ),
    )


def pool_sharding(config: ModelConfig, mesh: Mesh) -> NamedSharding:
    """Paged KV pool [L, N, K, page, hd]: kv heads over tp.

    Pages are NOT split over dp — block tables address the whole pool, and
    proving page locality to GSPMD isn't worth it at current dp targets
    (paged mode exists to fit one big replica; dp replicas each hold a
    pool).
    """
    return NamedSharding(
        mesh,
        _spec(
            mesh,
            [
                (config.n_layers, None),
                (1, None),
                (config.n_kv_heads, "tp"),
                (1, None),
                (config.head_dim, None),
            ],
        ),
    )


def batch_sharding(mesh: Mesh, batch: int) -> NamedSharding:
    return NamedSharding(mesh, _spec(mesh, [(batch, "dp")]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def place_params(params: Params, shardings: Params) -> Params:
    """Device-put the param pytree onto its sharding layout."""
    return jax.tree.map(
        lambda arr, sh: jax.device_put(arr, sh), params, shardings
    )
