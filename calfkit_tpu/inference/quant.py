"""Weight-only int8 quantization.

Decode is HBM-bound: weights are read once per generated token, so storing
matmul weights as int8 (+ bf16 per-output-channel scales) halves the
dominant traffic and lets Llama-3-8B fit a single 16 GB v5e chip.  XLA fuses
the dequant (convert+multiply) into the matmul's operand load — no
materialized bf16 copy.

Representation: a quantized tensor is the pytree leaf-pair
``{"q8": int8[...], "scale": f32 broadcastable}``; :func:`dequant` is the
single read-side seam (identity for plain arrays), applied at every weight
use in :mod:`calfkit_tpu.inference.model`.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# which layer weights quantize, and their INPUT (reduction/contraction)
# axes — scales are per-output-channel (max over these axes)
LAYER_REDUCTION_AXES: dict[str, tuple[int, ...]] = {
    "wq": (1,),  # [L, D, H, hd] — reduce D
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),  # [L, H, hd, D] — reduce (H, hd)
    "w_gate": (1,),  # [L, D, F]
    "w_up": (1,),
    "w_down": (1,),  # [L, F, D]
}
LM_HEAD_REDUCTION_AXES: tuple[int, ...] = (0,)  # [D, V] — reduce D


def quantize_tensor(w: jax.Array, reduction_axes: tuple[int, ...]) -> dict[str, jax.Array]:
    """int8 symmetric quantization with per-output-channel scales.

    ``reduction_axes`` are the matmul's contraction dims; every other dim
    keeps its own scale (rank preserved — the scale broadcasts and reuses
    the full tensor's sharding spec).
    """
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduction_axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale.astype(jnp.float32)}


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q8" in leaf and "scale" in leaf


def dequant(leaf: Any, dtype: Any = jnp.bfloat16) -> jax.Array:
    """The read-side seam: plain arrays pass through.  The multiply runs in
    f32 (the scale's storage precision) and casts once — XLA fuses the
    convert+multiply into the consuming matmul's operand load."""
    if is_quantized(leaf):
        return (leaf["q8"].astype(jnp.float32) * leaf["scale"]).astype(dtype)
    return leaf


def quantize_params(params: Params, *, consume: bool = False) -> Params:
    """Quantize the large matmul weights; norms and embeddings stay bf16.

    ``consume=True`` pops tensors out of the input tree as they quantize so
    each full-precision original frees before the next allocates — peak
    memory stays ~1x model size instead of 1.5x (this is what lets an 8B
    random-init quantize on a 16 GB chip).

    The embedding table stays unquantized: it is a gather at the bottom and
    (when untied) the lm_head handles the top; quantizing gathers gives no
    bandwidth win proportional to its complexity.
    """
    layers = params["layers"]
    out: Params = {"embed": params["embed"], "final_norm": params["final_norm"]}
    qlayers: Params = {}
    for name in list(layers):
        w = layers.pop(name) if consume else layers[name]
        if name in LAYER_REDUCTION_AXES:
            qlayers[name] = quantize_tensor(w, LAYER_REDUCTION_AXES[name])
        else:
            qlayers[name] = w  # norms
        del w
    out["layers"] = qlayers
    if "lm_head" in params:
        head = params.pop("lm_head") if consume else params["lm_head"]
        out["lm_head"] = quantize_tensor(head, LM_HEAD_REDUCTION_AXES)
    return out


def quantize_array_host(w: Any, reduction_axes: tuple[int, ...]) -> dict[str, Any]:
    """Numpy-side quantization for the checkpoint loader: only the int8
    tensor + small scale ever reach the device, so a 16 GB chip loads an 8B
    model without a transient bf16 copy."""
    import numpy as np

    w32 = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w32), axis=reduction_axes, keepdims=True)
    scale = np.maximum(absmax / 127.0, 1e-8).astype(np.float32)
    q8 = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"q8": q8, "scale": scale}


def quantize_shardings(shardings: Params) -> Params:
    """Mirror a sharding pytree onto the quantized structure: q8 keeps the
    tensor's spec; the scale clears the spec at reduction axes (those dims
    are singletons after keepdims and can't stay sharded — scales are tiny,
    replicating them is free)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def expand(ns: Any, reduction_axes: tuple[int, ...]) -> Any:
        spec = list(ns.spec) + [None] * 8  # pad: P() may be shorter than rank
        for axis in reduction_axes:
            spec[axis] = None
        scale_ns = NamedSharding(ns.mesh, P(*spec[: len(ns.spec)]))
        return {"q8": ns, "scale": scale_ns}

    out: Params = {
        "embed": shardings["embed"],
        "final_norm": shardings["final_norm"],
    }
    layers = shardings["layers"]
    qlayers: Params = {}
    for name, ns in layers.items():
        if name in LAYER_REDUCTION_AXES:
            qlayers[name] = expand(ns, LAYER_REDUCTION_AXES[name])
        else:
            qlayers[name] = ns
    out["layers"] = qlayers
    if "lm_head" in shardings:
        out["lm_head"] = expand(shardings["lm_head"], LM_HEAD_REDUCTION_AXES)
    return out


def random_quantized_params_host(
    config: Any, seed: int = 0, dtype: Any = None
) -> Params:
    """Random 8B-SHAPED params built quantized on the host.

    For benchmarking big models without a checkpoint: a device-side random
    init would transiently hold the full bf16 tree (~16 GB for Llama-3-8B —
    the whole chip), so instead generate int8 weights + unit-ish scales in
    numpy, one tensor at a time, and let the caller device_put them into
    quantized shardings.  Values are meaningless; shapes, dtypes, and HBM
    traffic are exactly the serving path's.
    """
    import ml_dtypes  # jax dependency: numpy bfloat16 support
    import numpy as np

    rng = np.random.default_rng(seed)
    np_dtype = np.dtype(dtype) if dtype else np.dtype(ml_dtypes.bfloat16)

    L, D, H, K, hd, F, V = (
        config.n_layers, config.d_model, config.n_heads, config.n_kv_heads,
        config.head_dim, config.d_ff, config.vocab_size,
    )

    def q(shape, reduction_axes):
        q8 = rng.integers(-127, 128, size=shape, dtype=np.int8)
        scale_shape = tuple(
            1 if i in reduction_axes else s for i, s in enumerate(shape)
        )
        fan_in = math.prod(shape[a] for a in reduction_axes)
        scale = np.full(
            scale_shape, 1.0 / (127.0 * np.sqrt(fan_in)), np.float32
        )
        return {"q8": q8, "scale": scale}

    def dense(shape, fan_in):
        return (rng.standard_normal(shape, dtype=np.float32)
                / np.sqrt(fan_in)).astype(np_dtype)

    params: Params = {
        "embed": dense((V, D), D),
        "layers": {
            "wq": q((L, D, H, hd), LAYER_REDUCTION_AXES["wq"]),
            "wk": q((L, D, K, hd), LAYER_REDUCTION_AXES["wk"]),
            "wv": q((L, D, K, hd), LAYER_REDUCTION_AXES["wv"]),
            "wo": q((L, H, hd, D), LAYER_REDUCTION_AXES["wo"]),
            "w_gate": q((L, D, F), LAYER_REDUCTION_AXES["w_gate"]),
            "w_up": q((L, D, F), LAYER_REDUCTION_AXES["w_up"]),
            "w_down": q((L, F, D), LAYER_REDUCTION_AXES["w_down"]),
            "attn_norm": np.ones((L, D), np_dtype),
            "mlp_norm": np.ones((L, D), np_dtype),
        },
        "final_norm": np.ones((D,), np_dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = q((D, V), LM_HEAD_REDUCTION_AXES)
    return params
