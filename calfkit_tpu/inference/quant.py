"""Weight-only int8 quantization.

Decode is HBM-bound: weights are read once per generated token, so storing
matmul weights as int8 (+ bf16 per-output-channel scales) halves the
dominant traffic and lets Llama-3-8B fit a single 16 GB v5e chip.  XLA fuses
the dequant (convert+multiply) into the matmul's operand load — no
materialized bf16 copy.

Representation: a quantized tensor is the pytree leaf-pair
``{"q8": int8[...], "scale": f32 broadcastable}``; :func:`dequant` is the
single read-side seam (identity for plain arrays), applied at every weight
use in :mod:`calfkit_tpu.inference.model`.
"""

from __future__ import annotations

import math
import re
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# which layer weights quantize, and their INPUT (reduction/contraction)
# axes — scales are per-output-channel (max over these axes)
LAYER_REDUCTION_AXES: dict[str, tuple[int, ...]] = {
    "wq": (1,),  # [L, D, H, hd] — reduce D
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),  # [L, H, hd, D] — reduce (H, hd)
    "w_gate": (1,),  # [L, D, F]
    "w_up": (1,),
    "w_down": (1,),  # [L, F, D]
}
LM_HEAD_REDUCTION_AXES: tuple[int, ...] = (0,)  # [D, V] — reduce D


def quantize_tensor(w: jax.Array, reduction_axes: tuple[int, ...]) -> dict[str, jax.Array]:
    """int8 symmetric quantization with per-output-channel scales.

    ``reduction_axes`` are the matmul's contraction dims; every other dim
    keeps its own scale (rank preserved — the scale broadcasts and reuses
    the full tensor's sharding spec).
    """
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduction_axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale.astype(jnp.float32)}


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q8" in leaf and "scale" in leaf


# int4 leaves carry their packing axis + group size IN THE KEY
# (``q4an<n>g<group>`` where the packing axis is the n-th FROM THE RIGHT,
# i.e. axis = ndim - n): pytree leaves must stay arrays (device_put /
# sharding trees map over values), so the two static ints ride the dict
# structure instead of a side-channel.  Right-relative indexing is what
# keeps the key valid after ``lax.scan`` slices the layer axis off the
# LEFT of every per-layer weight.
_Q4_KEY = "q4an{n}g{group}"
_Q4_RE = re.compile(r"^q4an(\d+)g(\d+)$")


def q4_key_of(leaf: dict) -> "tuple[str, int, int] | None":
    """→ (key, n_from_right, group); axis = array.ndim - n_from_right."""
    for key in leaf:
        m = _Q4_RE.match(key)
        if m:
            return key, int(m.group(1)), int(m.group(2))
    return None


def is_quantized4(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "scale" in leaf and q4_key_of(leaf) is not None


def dequant(leaf: Any, dtype: Any = jnp.bfloat16) -> jax.Array:
    """The read-side seam: plain arrays pass through.  The multiply runs in
    f32 (the scale's storage precision) and casts once — XLA fuses the
    convert+multiply into the consuming matmul's operand load."""
    if is_quantized(leaf):
        return (leaf["q8"].astype(jnp.float32) * leaf["scale"]).astype(dtype)
    if isinstance(leaf, dict) and "scale" in leaf:
        found = q4_key_of(leaf)
        if found is not None:
            key, n_right, group = found
            axis = leaf[key].ndim - n_right
            return _dequant4(leaf[key], leaf["scale"], axis, group, dtype)
    return leaf


def _dequant4(
    packed: jax.Array, scale: jax.Array, axis: int, group: int, dtype: Any
) -> jax.Array:
    """Unpack two 4-bit values per byte along ``axis`` (low nibble = even
    element, high = odd; values biased by +8) and apply the group-wise
    scales."""
    lo = (packed & 0x0F).astype(jnp.int8) - 8
    hi = ((packed >> 4) & 0x0F).astype(jnp.int8) - 8
    w = jnp.stack([lo, hi], axis=axis + 1)
    shape = list(packed.shape)
    shape[axis] *= 2
    w = w.reshape(shape)
    n = shape[axis]
    n_groups = n // group
    if n_groups > 1:
        gshape = shape[:axis] + [n_groups, group] + shape[axis + 1:]
        sshape = (
            list(scale.shape[:axis]) + [n_groups, 1]
            + list(scale.shape[axis + 1:])
        )
        w = (
            w.reshape(gshape).astype(jnp.float32) * scale.reshape(sshape)
        ).reshape(shape)
    else:
        w = w.astype(jnp.float32) * scale
    return w.astype(dtype)


DEFAULT_Q4_GROUP = 128


def _q4_group_for(n: int, group: int) -> int:
    """Group size along the packing axis: the requested group when it
    divides the axis, else the whole axis (per-channel fallback)."""
    return group if group and n % group == 0 else n


def quantize_tensor4(
    w: jax.Array, reduction_axes: tuple[int, ...],
    group: int = DEFAULT_Q4_GROUP,
) -> dict[str, jax.Array]:
    """int4 symmetric quantization: values in [-7, 7] biased to [1, 15],
    two per byte packed along the LAST reduction axis, with group-wise
    scales along that axis (finer than int8's per-output-channel — the
    standard accuracy recovery for 4-bit).  Other reduction axes keep
    per-element scale granularity (scale dims stay full there), which is
    strictly finer than int8's reduce-over-everything."""
    axis = reduction_axes[-1]
    n = w.shape[axis]
    if n % 2:
        raise ValueError(f"int4 packing needs an even axis, got {n}")
    g = _q4_group_for(n, group)
    n_groups = n // g
    shape = list(w.shape)
    gshape = shape[:axis] + [n_groups, g] + shape[axis + 1:]
    w32 = w.astype(jnp.float32).reshape(gshape)
    absmax = jnp.max(jnp.abs(w32), axis=axis + 1, keepdims=True)
    scale = jnp.maximum(absmax / 7.0, 1e-8)
    q = jnp.clip(jnp.round(w32 / scale), -7, 7).reshape(shape)
    biased = (q + 8).astype(jnp.uint8)
    lo = jax.lax.slice_in_dim(biased, 0, n, 2, axis)
    hi = jax.lax.slice_in_dim(biased, 1, n, 2, axis)
    packed = (lo | (hi << 4)).astype(jnp.uint8)
    # scale stored with n_groups at the packing axis (drop the kept-1 dim)
    scale = scale.reshape(
        list(scale.shape[:axis + 1]) + list(scale.shape[axis + 2:])
    )
    return {_Q4_KEY.format(n=w.ndim - axis, group=g): packed,
            "scale": scale.astype(jnp.float32)}


def quantize_params(
    params: Params, *, consume: bool = False, bits: int = 8
) -> Params:
    """Quantize the large matmul weights; norms and embeddings stay bf16.

    ``consume=True`` pops tensors out of the input tree as they quantize so
    each full-precision original frees before the next allocates — peak
    memory stays ~1x model size instead of 1.5x (this is what lets an 8B
    random-init quantize on a 16 GB chip).

    ``bits`` selects int8 (per-output-channel scales) or int4 (packed
    nibbles + group-wise scales — half the decode weight stream again).

    The embedding table stays unquantized: it is a gather at the bottom and
    (when untied) the lm_head handles the top; quantizing gathers gives no
    bandwidth win proportional to its complexity.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qt = quantize_tensor if bits == 8 else quantize_tensor4
    layers = params["layers"]
    out: Params = {"embed": params["embed"], "final_norm": params["final_norm"]}
    qlayers: Params = {}
    for name in list(layers):
        w = layers.pop(name) if consume else layers[name]
        if name in LAYER_REDUCTION_AXES:
            qlayers[name] = qt(w, LAYER_REDUCTION_AXES[name])
        else:
            qlayers[name] = w  # norms
        del w
    out["layers"] = qlayers
    if "lm_head" in params:
        head = params.pop("lm_head") if consume else params["lm_head"]
        out["lm_head"] = qt(head, LM_HEAD_REDUCTION_AXES)
    return out


def quantize_array_host(
    w: Any, reduction_axes: tuple[int, ...], *, bits: int = 8,
    group: int = DEFAULT_Q4_GROUP,
) -> dict[str, Any]:
    """Numpy-side quantization for the checkpoint loader: only the packed
    tensor + small scale ever reach the device, so a 16 GB chip loads an
    8B model without a transient bf16 copy."""
    import numpy as np

    w32 = np.asarray(w, dtype=np.float32)
    if bits == 8:
        absmax = np.max(np.abs(w32), axis=reduction_axes, keepdims=True)
        scale = np.maximum(absmax / 127.0, 1e-8).astype(np.float32)
        q8 = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
        return {"q8": q8, "scale": scale}
    axis = reduction_axes[-1]
    n = w32.shape[axis]
    if n % 2:  # same contract as quantize_tensor4, same clear error
        raise ValueError(f"int4 packing needs an even axis, got {n}")
    g = _q4_group_for(n, group)
    n_groups = n // g
    shape = list(w32.shape)
    gshape = shape[:axis] + [n_groups, g] + shape[axis + 1:]
    wg = w32.reshape(gshape)
    absmax = np.max(np.abs(wg), axis=axis + 1, keepdims=True)
    scale = np.maximum(absmax / 7.0, 1e-8).astype(np.float32)
    q = np.clip(np.round(wg / scale), -7, 7).reshape(shape)
    biased = (q + 8).astype(np.uint8)
    index_lo = [slice(None)] * len(shape)
    index_hi = [slice(None)] * len(shape)
    index_lo[axis] = slice(0, n, 2)
    index_hi[axis] = slice(1, n, 2)
    packed = biased[tuple(index_lo)] | (biased[tuple(index_hi)] << 4)
    scale = scale.reshape(
        list(scale.shape[:axis + 1]) + list(scale.shape[axis + 2:])
    )
    return {_Q4_KEY.format(n=w32.ndim - axis, group=g): packed, "scale": scale}


def quantize_shardings(shardings: Params, *, bits: int = 8) -> Params:
    """Mirror a sharding pytree onto the quantized structure.

    int8: q8 keeps the tensor's spec; the scale clears the spec at every
    reduction axis (those dims are singletons after keepdims and can't
    stay sharded — scales are tiny, replicating them is free).

    int4: the packed tensor keeps the spec (halving an axis preserves
    divisibility); the scale clears the spec ONLY at the packing axis
    (its dim becomes n_groups — replicated for divisibility safety) and
    keeps it elsewhere (other reduction dims stay full-size in int4's
    finer scale granularity, so e.g. wo's tp-sharded head axis stays
    sharded)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def expand(ns: Any, reduction_axes: tuple[int, ...]) -> Any:
        spec = list(ns.spec) + [None] * 8  # pad: P() may be shorter than rank
        cleared = reduction_axes if bits == 8 else reduction_axes[-1:]
        for axis in cleared:
            spec[axis] = None
        scale_ns = NamedSharding(ns.mesh, P(*spec[: len(ns.spec)]))
        if bits == 8:
            return {"q8": ns, "scale": scale_ns}
        # the key's group value is resolved at quantize time from the real
        # axis size; shardings are matched by STRUCTURE via tree-map over
        # the params tree, so mirror whatever key the params carry
        return {"__q4__": ns, "scale": scale_ns}

    out: Params = {
        "embed": shardings["embed"],
        "final_norm": shardings["final_norm"],
    }
    layers = shardings["layers"]
    qlayers: Params = {}
    for name, ns in layers.items():
        if name in LAYER_REDUCTION_AXES:
            qlayers[name] = expand(ns, LAYER_REDUCTION_AXES[name])
        else:
            qlayers[name] = ns
    out["layers"] = qlayers
    if "lm_head" in shardings:
        out["lm_head"] = expand(shardings["lm_head"], LM_HEAD_REDUCTION_AXES)
    return out


def align_quant_sharding_keys(shardings: Params, params: Params) -> Params:
    """Rename int4 placeholder keys (``__q4__``) in a sharding tree to the
    concrete ``q4a<axis>g<group>`` keys the params tree carries, so the
    two trees are structurally identical for device_put/jit donation."""

    def walk(sh: Any, pr: Any) -> Any:
        if isinstance(sh, dict) and "__q4__" in sh and isinstance(pr, dict):
            found = q4_key_of(pr)
            if found is None:
                raise ValueError("params leaf is not int4 but shardings are")
            key, _axis, _group = found
            return {key: sh["__q4__"], "scale": sh["scale"]}
        if isinstance(sh, dict):
            return {k: walk(v, pr[k] if isinstance(pr, dict) else pr)
                    for k, v in sh.items()}
        return sh

    return walk(shardings, params)


def random_quantized_params_host(
    config: Any, seed: int = 0, dtype: Any = None, *, bits: int = 8
) -> Params:
    """Random 8B-SHAPED params built quantized on the host.

    For benchmarking big models without a checkpoint: a device-side random
    init would transiently hold the full bf16 tree (~16 GB for Llama-3-8B —
    the whole chip), so instead generate int8 weights + unit-ish scales in
    numpy, one tensor at a time, and let the caller device_put them into
    quantized shardings.  Values are meaningless; shapes, dtypes, and HBM
    traffic are exactly the serving path's.
    """
    import ml_dtypes  # jax dependency: numpy bfloat16 support
    import numpy as np

    rng = np.random.default_rng(seed)
    np_dtype = np.dtype(dtype) if dtype else np.dtype(ml_dtypes.bfloat16)

    L, D, H, K, hd, F, V = (
        config.n_layers, config.d_model, config.n_heads, config.n_kv_heads,
        config.head_dim, config.d_ff, config.vocab_size,
    )

    def q(shape, reduction_axes):
        fan_in = math.prod(shape[a] for a in reduction_axes)
        if bits == 8:
            q8 = rng.integers(-127, 128, size=shape, dtype=np.int8)
            scale_shape = tuple(
                1 if i in reduction_axes else s for i, s in enumerate(shape)
            )
            scale = np.full(
                scale_shape, 1.0 / (127.0 * np.sqrt(fan_in)), np.float32
            )
            return {"q8": q8, "scale": scale}
        axis = reduction_axes[-1]
        g = _q4_group_for(shape[axis], DEFAULT_Q4_GROUP)
        packed_shape = tuple(
            s // 2 if i == axis else s for i, s in enumerate(shape)
        )
        packed = rng.integers(0, 256, size=packed_shape, dtype=np.uint8)
        scale_shape = tuple(
            shape[axis] // g if i == axis else s for i, s in enumerate(shape)
        )
        scale = np.full(
            scale_shape, 1.0 / (7.0 * np.sqrt(fan_in)), np.float32
        )
        return {_Q4_KEY.format(n=len(shape) - axis, group=g): packed, "scale": scale}

    def dense(shape, fan_in):
        return (rng.standard_normal(shape, dtype=np.float32)
                / np.sqrt(fan_in)).astype(np_dtype)

    params: Params = {
        "embed": dense((V, D), D),
        "layers": {
            "wq": q((L, D, H, hd), LAYER_REDUCTION_AXES["wq"]),
            "wk": q((L, D, K, hd), LAYER_REDUCTION_AXES["wk"]),
            "wv": q((L, D, K, hd), LAYER_REDUCTION_AXES["wv"]),
            "wo": q((L, H, hd, D), LAYER_REDUCTION_AXES["wo"]),
            "w_gate": q((L, D, F), LAYER_REDUCTION_AXES["w_gate"]),
            "w_up": q((L, D, F), LAYER_REDUCTION_AXES["w_up"]),
            "w_down": q((L, F, D), LAYER_REDUCTION_AXES["w_down"]),
            "attn_norm": np.ones((L, D), np_dtype),
            "mlp_norm": np.ones((L, D), np_dtype),
        },
        "final_norm": np.ones((D,), np_dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = q((D, V), LM_HEAD_REDUCTION_AXES)
    return params
