"""Multi-tenant QoS (ISSUE 20) — priority classes and per-tenant
token budgets.

PR 5's bounded admission made overload SURVIVABLE (typed sheds instead
of queue collapse) but degraded every caller with equal probability:
one runaway batch tenant could starve every interactive agent on the
engine.  This module makes degradation SELECTIVE, in two layers:

- the **priority class** — ``interactive`` | ``batch``
  (:data:`calfkit_tpu.protocol.PRIORITY_CLASSES`), minted by the client
  as the ``x-mesh-priority`` header and forwarded by every hop
  (downstream tool calls run on the original caller's behalf, so they
  inherit its class).  Under overload the mesh sheds batch first,
  reaps batch first, and the router avoids interactive-deep replicas.
  A corrupt or missing header degrades to the DEFAULT class
  (interactive — batch is an explicit opt-in to LOWER priority; legacy
  callers must not be demoted) and never faults delivery (the PR 5
  law).  :data:`current_priority` carries the class through the
  in-process call chain exactly like ``leases.current_lease`` carries
  the lease: the node kernel sets it from the delivery's header, the
  engine reads it with no per-layer plumbing.
- the **per-tenant token bucket** (:class:`TenantRateLimiter`) — an
  admission-time budget at the NODE KERNEL, upstream of the engine's
  queues, so a storming tenant is refused before it occupies
  ``max_pending`` slots that well-behaved tenants need.  The tenant
  identity is the caller's lease id where present (one lease per
  caller process — the natural tenant grain), else the caller's client
  emitter id.  Refill rides THE deadline clock
  (:func:`calfkit_tpu.cancellation.wall_clock`), so the chaos virtual
  clock drives refill deterministically in the sim.  Refusals are the
  typed RETRIABLE ``mesh.rate_limited`` fault: the budget refills on a
  known schedule, so backoff-and-retry is exactly the right caller
  response (unlike a deadline, which is gone forever).

Only ENTERING work is budgeted: continuation deliveries (agent → tool,
tool results, consumer legs) are the tail of an already-admitted run —
rate-limiting them mid-run would strand slots and pages the admitted
run already holds.  This mirrors the drain gate's exemption in the
node kernel.

Everything here is fail-open advisory state, like the lease store: the
limiter defaults to DISABLED (``rate_per_s <= 0``), an unknown tenant
starts with a full burst, and the bucket table is capped — eviction
costs one free burst for a returning tenant, never correctness.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import threading
from collections import OrderedDict
from contextvars import ContextVar

from calfkit_tpu import cancellation
from calfkit_tpu.protocol import DEFAULT_PRIORITY, PRIORITY_CLASSES

__all__ = [
    "current_priority",
    "resolve_priority",
    "class_rank",
    "TenantRateLimiter",
]

# the current delivery's priority class, set by the node kernel from the
# x-mesh-priority header for the duration of one delivery — None outside
# any delivery (same channel shape as leases.current_lease); readers go
# through resolve_priority() so the missing/corrupt → default law has
# exactly one copy
current_priority: "ContextVar[str | None]" = ContextVar(
    "calfkit_caller_priority", default=None
)


def resolve_priority(value: "str | None" = None) -> str:
    """THE class-degradation law: an unknown/absent class is the
    DEFAULT class.  With no argument, resolves the current delivery's
    contextvar."""
    if value is None:
        value = current_priority.get()
    if value in PRIORITY_CLASSES:
        return value
    return DEFAULT_PRIORITY


@hotpath
def class_rank(priority: "str | None") -> int:
    """Shed/reap ordering key: HIGHER rank degrades FIRST (batch=1
    before interactive=0).  One copy, shared by the engine's victim
    selection, the reaper scan weighting, and the sim's model — the
    zero-interactive-sheds-while-batch-remains gate law is only as
    strong as this ordering being identical everywhere."""
    if priority == PRIORITY_CLASSES[-1]:  # "batch"
        return 1
    return 0


# bucket table cap, same scale (and same rationale) as leases._BEAT_CAP:
# eviction is cheap here — a returning tenant restarts with a full
# burst, which under-throttles for one burst rather than over-throttling
_BUCKET_CAP = 4096


class TenantRateLimiter:
    """Per-tenant token bucket: ``rate_per_s`` tokens/second refill up
    to ``burst``; each entering call spends one token.  ``admit``
    returns None to admit, else the seconds until a token exists — the
    retry hint carried in the ``mesh.rate_limited`` fault.

    Construction is cheap and the disabled form (``rate_per_s <= 0``,
    the default) is a no-op, so nodes can carry a limiter resource
    unconditionally and operators opt in per deployment.
    """

    def __init__(self, rate_per_s: float = 0.0, burst: float = 1.0):
        self.rate_per_s = float(rate_per_s)
        self.burst = max(1.0, float(burst))
        # tenant_id -> (tokens, stamped_at); LRU-capped
        self._buckets: "OrderedDict[str, tuple[float, float]]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.rate_per_s > 0

    @hotpath
    def admit(
        self, tenant_id: str, now: "float | None" = None
    ) -> "float | None":
        """Spend one token for ``tenant_id``.  None = admitted;
        otherwise the seconds until the bucket next holds a whole
        token (the caller's backoff hint).  Runs on the node kernel's
        per-delivery admission path — one dict probe, no allocation
        beyond the bucket tuple."""
        if self.rate_per_s <= 0 or not tenant_id:
            return None
        if now is None:
            now = cancellation.wall_clock()
        with self._lock:
            entry = self._buckets.get(tenant_id)
            if entry is None:
                tokens = self.burst
            else:
                tokens, stamped = entry
                if now > stamped:
                    tokens = min(
                        self.burst,
                        tokens + (now - stamped) * self.rate_per_s,
                    )
            if tokens >= 1.0:
                self._buckets[tenant_id] = (tokens - 1.0, now)
                self._buckets.move_to_end(tenant_id)
                if len(self._buckets) > _BUCKET_CAP:
                    self._buckets.popitem(last=False)
                return None
            # refusal does NOT restamp with drained tokens: a storming
            # tenant must not push its own refill horizon forward
            self._buckets[tenant_id] = (tokens, now)
            self._buckets.move_to_end(tenant_id)
            return max(0.0, (1.0 - tokens) / self.rate_per_s)

    def snapshot(self) -> "dict[str, float]":
        """tenant_id -> tokens remaining (no refill applied) — debug
        and test surface, not a hot read."""
        with self._lock:
            return {k: v[0] for k, v in self._buckets.items()}
