"""Tuning value-objects (reference: calfkit/tuning.py:20-74).

Strictly-validated knobs for the compacted-table machinery.  The important
semantic: catch-up is a GATE (a store/view must not serve until it has read
the table to its end) and barriers are read-your-own-writes freshness —
both are bounded by these timeouts so a dead broker turns into a loud
error instead of a silent hang.
"""

from __future__ import annotations

from pydantic import BaseModel, ConfigDict, Field


class TableTuning(BaseModel):
    """Compacted-table reader bounds (the KTableReaderTuning analog)."""

    model_config = ConfigDict(extra="forbid", frozen=True)

    catchup_timeout_s: float = Field(30.0, gt=0)
    barrier_timeout_s: float = Field(30.0, gt=0)


class FanoutConfig(BaseModel):
    """Durable fan-out store tuning, threaded via ``Worker(fanout=...)``.

    Raise the timeouts on slow brokers; the write-order and fold/close
    semantics are not configurable (they are the correctness story).
    """

    model_config = ConfigDict(extra="forbid", frozen=True)

    table: TableTuning = Field(default_factory=TableTuning)
