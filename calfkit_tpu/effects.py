"""Effect-constraint markers for the meshlint static analyzer (ISSUE 12).

These decorators are NO-OPS at runtime — zero wrapper, zero overhead;
they return the function unchanged.  Their only job is to declare, at
the definition site, that a function is the ROOT of a static constraint
that ``scripts/meshlint`` propagates through the transitive call closure
of the intra-project call graph:

- :func:`hotpath` — the function runs on a serving hot path (the decode
  dispatch loop, the fleet selection path, the lease sweep).  Nothing it
  transitively calls may block (``time.sleep``/``open``/``subprocess``/
  sockets), log (``logger.*``/``print``), read the wall clock
  (``time.time``/``datetime.now``; ``time.perf_counter`` stays legal —
  it is the sanctioned hot-path duration clock), or issue a blocking
  device→host sync (``np.asarray``/``jax.device_get``/
  ``.block_until_ready()``/``.item()``) outside an annotated sync point.
  A ``@hotpath`` function must also stay sync by shape (``def``, not
  ``async def``): the selection and dispatch paths are synchronous by
  contract.
- :func:`no_block` — no transitive blocking primitive (the subset of
  ``hotpath`` that an async admission helper can honor).
- :func:`no_wallclock` — no transitive host-clock read of ANY kind
  (``time.time``, ``time.monotonic``, ``time.perf_counter``,
  ``datetime.now`` and friends).  This is the determinism constraint:
  the simulator and the perf gate's metric computation must never
  observe host time (ISSUE 11 — timestamps flow through the
  ``cancellation.wall_clock`` seam only).
- :func:`no_log` — no transitive logging or ``print``.

Because the declaration lives ON the definition, a rename moves the
constraint with the function — the failure mode of the old
``lint_hotpath.py`` name lists (a renamed hot function silently dropped
out of coverage; only a separate loud-miss check caught it) is
structurally gone.

Individual effect SITES inside a guarded closure are waived with the
escape-comment vocabulary (one reasoned comment per site, on the line or
the comment block above it — never a suppression baseline file):

    # blocking-ok: <why this block/sync is safe here>
    # wallclock-ok: <why this host-clock read is safe here>
    # unbounded-ok: <which bound/permit/reaper makes this queue safe>
    # atomicity-ok: <why this read..await..write is not a lost update>

See docs/static-analysis.md for the full rule and vocabulary reference.
"""

from __future__ import annotations

from typing import Callable, TypeVar

__all__ = ["hotpath", "no_block", "no_wallclock", "no_log"]

F = TypeVar("F", bound=Callable)


def hotpath(fn: F) -> F:
    """Marker: serving-hot-path root (no block/log/wallclock/device-sync
    anywhere in the transitive call closure; must stay ``def``)."""
    return fn


def no_block(fn: F) -> F:
    """Marker: no transitive blocking primitive."""
    return fn


def no_wallclock(fn: F) -> F:
    """Marker: no transitive host-clock read (wall OR monotonic)."""
    return fn


def no_log(fn: F) -> F:
    """Marker: no transitive logging / ``print``."""
    return fn
