"""Shared spawn machinery for the in-repo native brokers (meshd, kafkad).

Both binaries follow the same contract: ``<binary> <port>`` where port 0
binds an OS-assigned port, and the bound port is reported on stdout as
``PORT <n>`` before serving begins.
"""

from __future__ import annotations

import os
import select
import subprocess
import time
from pathlib import Path


def find_native_binary(name: str, env_var: str) -> str | None:
    """Locate an in-repo native binary; ``$<env_var>`` overrides."""
    import os

    env = os.environ.get(env_var)
    if env and Path(env).exists():
        return env
    candidate = Path(__file__).resolve().parents[2] / "native" / "bin" / name
    return str(candidate) if candidate.exists() else None


def spawn_port_reporting(
    binary: str, port: int, *, name: str, start_new_session: bool = False,
    timeout: float = 10.0, extra_args=(),
) -> tuple[subprocess.Popen, int]:
    """Spawn a PORT-reporting broker and return (proc, bound_port).

    Handles the failure paths uniformly: immediate exit (bind failure on a
    taken fixed port) raises with the exit code instead of hanging in
    select; a binary that never prints ``PORT`` (stale build) is killed,
    reaped, and reported."""
    proc = subprocess.Popen(
        [binary, str(port), *extra_args],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        start_new_session=start_new_session,
    )

    def _kill(message: str, error: type) -> None:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except Exception:  # noqa: BLE001
            pass
        proc.stdout.close()
        raise error(message)

    # non-blocking accumulate until a full line: a child that writes a
    # partial line without a newline (stale/wedged binary) must hit the
    # deadline below, not hang the caller in a blocking readline
    os.set_blocking(proc.stdout.fileno(), False)
    deadline = time.time() + timeout
    buf = b""
    while b"\n" not in buf:
        if proc.poll() is not None and not buf:
            proc.stdout.close()
            raise RuntimeError(
                f"{name} exited immediately (code {proc.returncode}) — is "
                f"port {port} already in use?"
            )
        ready, _, _ = select.select(
            [proc.stdout], [], [], min(0.25, max(0.0, deadline - time.time()))
        )
        if ready:
            chunk = proc.stdout.read(4096)
            if chunk:
                buf += chunk
                continue
            if chunk == b"":  # pipe EOF: the child can never report now
                if proc.poll() is not None:
                    proc.stdout.close()
                    raise RuntimeError(
                        f"{name} exited immediately (code {proc.returncode}) "
                        f"— is port {port} already in use?"
                    )
                _kill(
                    f"{name} closed stdout without reporting its bound port "
                    "— stale binary? run `make -C native`",
                    RuntimeError,
                )
        if time.time() >= deadline:
            _kill(
                f"{name} did not report its bound port within {timeout:.0f}s "
                "— stale binary? run `make -C native`",
                TimeoutError,
            )
    line = buf.split(b"\n", 1)[0].decode(errors="replace").strip()
    try:
        reported = int(line.removeprefix("PORT "))
    except ValueError:
        reported = -1
    if not line.startswith("PORT ") or reported <= 0:
        _kill(
            f"{name} did not report its bound port (got {line!r}) — "
            "stale binary? run `make -C native`",
            RuntimeError,
        )
    proc.stdout.close()
    return proc, reported
