"""A full in-process mesh: the offline test substrate and the dev mesh.

Faithful to the Kafka semantics nodes rely on, in one process:

- topics have **partitions** (default 16); a record's partition is
  ``crc32(key) % P`` — so per-key ordering holds *across consumer-group
  members*, exactly as on a real broker;
- named consumer groups share partitions (round-robin assignment, recomputed
  on membership change = the rebalance analog);
- ``group_id=None`` subscribers are broadcast taps (own cursors, from latest
  by default);
- compacted table topics serve reader views with trivially-true catch-up and
  barrier (everything is local, read-your-own-writes holds by construction).

The reference leaned on FastStream's TestKafkaBroker for the offline lane and
a spawned Tansu binary for the dev mesh (SURVEY.md §4, §3.5); owning this
implementation removes both dependencies.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import zlib
from typing import Awaitable, Callable

from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.transport import (
    CallbackSubscription,
    MeshTransport,
    Record,
    RecordHandler,
    Subscription,
)

logger = logging.getLogger(__name__)

DEFAULT_PARTITIONS = 16


class _Topic:
    # past this per-partition length, unconsumed compacted topics are trimmed
    COMPACT_THRESHOLD = 512

    def __init__(self, name: str, partitions: int, compacted: bool):
        self.name = name
        self.compacted = compacted
        self.partitions: list[list[Record]] = [[] for _ in range(partitions)]
        self.changed = asyncio.Event()
        self.consumer_count = 0  # log-position consumers (pumps); gates trimming
        # compacted topics also maintain the folded view at publish time so
        # table reads are O(1) instead of re-folding the log; the version
        # counter bumps on every fold mutation (TableReader.version — the
        # fleet registry's O(1) no-change fast path reads it)
        self.table: dict[str, bytes] = {}
        self.table_version = 0
        # set by the mesh: remaps persisted group cursors after a log trim
        self.on_compact: Callable[["_Topic", int, list[Record], list[Record]], None] | None = None
        self._rr = itertools.count()
        self._offset = itertools.count()

    def partition_of(self, key: bytes | None) -> int:
        if key is None:
            return next(self._rr) % len(self.partitions)
        return zlib.crc32(key) % len(self.partitions)

    def append(self, key: bytes | None, value: bytes, headers: dict[str, str]) -> None:
        p = self.partition_of(key)
        record = Record(
            topic=self.name,
            key=key,
            value=value,
            headers=dict(headers),
            offset=next(self._offset),
        )
        self.partitions[p].append(record)
        if self.compacted and key is not None:
            k = key.decode("utf-8", errors="replace")
            self.table_version += 1
            if len(value) == 0:
                self.table.pop(k, None)  # tombstone
            else:
                self.table[k] = value
            # bound log growth (heartbeats rewrite the same keys forever);
            # only safe when no pump holds an index-based cursor on the log —
            # persisted group cursors are remapped via on_compact
            if self.consumer_count == 0 and len(self.partitions[p]) > self.COMPACT_THRESHOLD:
                old = self.partitions[p]
                latest: dict[bytes, Record] = {}
                for r in old:
                    if r.key is not None:
                        latest[r.key] = r
                kept = sorted(
                    (r for r in latest.values() if len(r.value) > 0),
                    key=lambda r: r.offset,
                )
                self.partitions[p] = kept
                if self.on_compact is not None:
                    self.on_compact(self, p, old, kept)
        self.changed.set()

    def ends(self) -> list[int]:
        return [len(p) for p in self.partitions]


class _Group:
    """Consumer-group state for one topic: shared cursors + assignment.

    ``locks[p]`` is the revocation barrier: a member holds the partition lock
    while pulling/delivering from it, so after a rebalance the new assignee
    cannot start until the old one's in-flight delivery completes — per-key
    ordering survives membership changes (a real broker achieves this with
    the rebalance protocol's revocation phase)."""

    def __init__(self, topic: _Topic):
        self.topic = topic
        self.cursors = [0] * len(topic.partitions)
        self.locks = [asyncio.Lock() for _ in topic.partitions]
        self.members: list["_GroupMember"] = []

    def rebalance(self) -> None:
        n = len(self.members)
        for i, member in enumerate(self.members):
            member.assigned = [p for p in range(len(self.topic.partitions)) if p % n == i]


class _GroupMember:
    def __init__(self) -> None:
        self.assigned: list[int] = []


class InMemoryMesh(MeshTransport):
    def __init__(
        self,
        *,
        partitions: int = DEFAULT_PARTITIONS,
        auto_create_topics: bool = True,
        max_message_bytes: int = 5 * 1024 * 1024,
    ):
        self._partitions = partitions
        self._auto_create = auto_create_topics
        self._max_bytes = max_message_bytes
        self._topics: dict[str, _Topic] = {}
        self._groups: dict[tuple[str, str], _Group] = {}  # (topic, group_id)
        self._pumps: list[asyncio.Task[None]] = []
        self._dispatchers: list[KeyOrderedDispatcher] = []
        self._started = False
        # chaos seam (tests/_chaos.py): a deterministic fault injector for
        # scripted broker-failure scenarios.  Called per publish with
        # (topic, headers); returning "drop" silently loses the record —
        # the broker-drop-during-return scenario.  None = transparent.
        self.chaos: "Callable[[str, dict[str, str]], str | None] | None" = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        self._started = True

    async def stop(self) -> None:
        self._started = False
        # swap-then-iterate (meshlint await-atomicity): detach before
        # the first await so a racing subscribe can't be silently dropped
        pumps, self._pumps = self._pumps, []
        for pump in pumps:
            pump.cancel()
        for pump in pumps:
            try:
                await pump
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        dispatchers, self._dispatchers = self._dispatchers, []
        for d in dispatchers:
            try:
                await d.stop()
            except Exception:  # noqa: BLE001
                logger.exception("dispatcher drain failed")

    @property
    def max_message_bytes(self) -> int:
        return self._max_bytes

    # ---------------------------------------------------------------- admin
    async def ensure_topics(self, names: list[str], *, compacted: bool = False) -> None:
        for name in names:
            self._topic(name, create=True, compacted=compacted)

    def _topic(self, name: str, *, create: bool | None = None, compacted: bool = False) -> _Topic:
        topic = self._topics.get(name)
        if topic is None:
            if not (create or (create is None and self._auto_create)):
                raise KeyError(f"unknown topic {name!r} (auto-create disabled)")
            topic = _Topic(name, self._partitions, compacted)
            topic.on_compact = self._remap_group_cursors
            self._topics[name] = topic
        elif compacted and not topic.compacted:
            # upgrade a topic auto-created by an early publish: backfill the
            # folded view from the log so table reads see prior records
            topic.compacted = True
            for record in sorted(
                (r for p in topic.partitions for r in p), key=lambda r: r.offset
            ):
                if record.key is None:
                    continue
                k = record.key.decode("utf-8", errors="replace")
                topic.table_version += 1
                if len(record.value) == 0:
                    topic.table.pop(k, None)
                else:
                    topic.table[k] = record.value
        return topic

    def topic_names(self) -> list[str]:
        return sorted(self._topics)

    def _remap_group_cursors(
        self, topic: _Topic, p: int, old: list[Record], kept: list[Record]
    ) -> None:
        """After a log trim, persisted cursors of (possibly stopped) groups
        index the OLD list; remap each to its position in the kept list so a
        returning group member resumes without skipping records."""
        for (topic_name, _gid), group in self._groups.items():
            if topic_name != topic.name:
                continue
            c = group.cursors[p]
            if c <= 0:
                continue
            boundary = old[c - 1].offset if c <= len(old) else old[-1].offset
            group.cursors[p] = sum(1 for r in kept if r.offset <= boundary)

    # -------------------------------------------------------------- produce
    async def publish(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        if len(value) > self._max_bytes:
            raise ValueError(
                f"message of {len(value)} bytes exceeds max_message_bytes={self._max_bytes}"
            )
        if self.chaos is not None and self.chaos(topic, headers or {}) == "drop":
            # injected broker loss: the record never lands (scripted
            # scenarios assert the timeout/cancel story downstream)
            await asyncio.sleep(0)
            return
        t = self._topic(topic)
        t.append(key, value, headers or {})
        # yield so same-task publish->consume chains interleave like real I/O
        await asyncio.sleep(0)

    # -------------------------------------------------------------- consume
    async def subscribe(
        self,
        topics: list[str],
        handler: RecordHandler,
        *,
        group_id: str | None,
        from_latest: bool | None = None,
        max_workers: int = 8,
        ordered: bool = True,
    ) -> Subscription:
        if not self._started:
            raise RuntimeError("mesh not started")
        if from_latest is None:
            from_latest = group_id is None  # taps from latest, groups from earliest

        deliver = handler
        dispatcher: KeyOrderedDispatcher | None = None
        if ordered:
            dispatcher = KeyOrderedDispatcher(
                handler, max_workers=max_workers, name=f"sub-{group_id or 'tap'}"
            )
            dispatcher.start()
            self._dispatchers.append(dispatcher)

            async def deliver(record: Record) -> None:  # type: ignore[misc]
                await dispatcher.submit(record)

        tasks: list[asyncio.Task[None]] = []
        members: list[tuple[_Group, _GroupMember]] = []
        attached: list[_Topic] = []
        for name in topics:
            topic = self._topic(name, create=True)
            topic.consumer_count += 1
            attached.append(topic)
            if group_id is None:
                cursors = [len(p) if from_latest else 0 for p in topic.partitions]
                tasks.append(
                    asyncio.get_running_loop().create_task(
                        self._pump_broadcast(topic, cursors, deliver),
                        name=f"pump-tap-{name}",
                    )
                )
            else:
                group = self._groups.setdefault((name, group_id), _Group(topic))
                member = _GroupMember()
                group.members.append(member)
                group.rebalance()
                if from_latest and len(group.members) == 1:
                    group.cursors = [len(p) for p in topic.partitions]
                members.append((group, member))
                tasks.append(
                    asyncio.get_running_loop().create_task(
                        self._pump_group(group, member, deliver),
                        name=f"pump-{group_id}-{name}",
                    )
                )
        self._pumps.extend(tasks)

        async def stop_fn() -> None:
            for topic in attached:
                topic.consumer_count -= 1
            for group, member in members:
                if member in group.members:
                    group.members.remove(member)
                    if group.members:
                        group.rebalance()
            for task in tasks:
                task.cancel()
            for task in tasks:
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            if dispatcher is not None:
                await dispatcher.stop()
                if dispatcher in self._dispatchers:
                    self._dispatchers.remove(dispatcher)

        return CallbackSubscription(stop_fn)

    async def _pump_broadcast(
        self,
        topic: _Topic,
        cursors: list[int],
        deliver: RecordHandler,
    ) -> None:
        while True:
            progressed = False
            for p, partition in enumerate(topic.partitions):
                while cursors[p] < len(partition):
                    record = partition[cursors[p]]
                    cursors[p] += 1
                    progressed = True
                    try:
                        await deliver(record)
                    except Exception:  # noqa: BLE001
                        logger.exception("broadcast tap handler failed on %s", topic.name)
            if not progressed:
                topic.changed.clear()
                # re-check before parking: a publish may have landed between
                # the scan and the clear (missed-wakeup race)
                if any(
                    cursors[p] < len(part) for p, part in enumerate(topic.partitions)
                ):
                    continue
                await topic.changed.wait()

    async def _pump_group(
        self,
        group: _Group,
        member: _GroupMember,
        deliver: RecordHandler,
    ) -> None:
        topic = group.topic
        while True:
            progressed = False
            for p in list(member.assigned):
                if group.locks[p].locked():
                    continue  # previous assignee mid-delivery; revisit next pass
                async with group.locks[p]:
                    while p in member.assigned and group.cursors[p] < len(topic.partitions[p]):
                        record = topic.partitions[p][group.cursors[p]]
                        # ACK-first: advance the cursor (the commit) before handling
                        group.cursors[p] += 1
                        progressed = True
                        try:
                            await deliver(record)
                        except Exception:  # noqa: BLE001
                            logger.exception(
                                "group delivery failed on %s[%d]", topic.name, p
                            )
            if not progressed:
                topic.changed.clear()
                if any(
                    p in member.assigned and group.cursors[p] < len(topic.partitions[p])
                    for p in range(len(topic.partitions))
                ):
                    continue
                try:
                    await asyncio.wait_for(topic.changed.wait(), timeout=0.2)
                except asyncio.TimeoutError:
                    pass  # re-check assignment after rebalances

    # --------------------------------------------------------------- tables
    def table_reader(self, topic: str) -> TableReader:
        return _MemoryTableReader(self, topic)

    def table_writer(self, topic: str) -> TableWriter:
        return _MemoryTableWriter(self, topic)


class _MemoryTableReader(TableReader):
    """A view over a local topic: always caught up, barrier is a yield."""

    def __init__(self, mesh: InMemoryMesh, topic: str):
        self._mesh = mesh
        self._topic_name = topic
        self._started = False

    async def start(self, *, timeout: float = 30.0) -> None:
        self._mesh._topic(self._topic_name, create=True, compacted=True)
        self._started = True

    async def stop(self) -> None:
        self._started = False

    async def barrier(self, *, timeout: float = 30.0) -> None:
        await asyncio.sleep(0)

    def _view(self) -> dict[str, bytes]:
        # the topic maintains its folded view at publish time (O(1) reads)
        return self._mesh._topic(self._topic_name, create=True, compacted=True).table

    def get(self, key: str) -> bytes | None:
        return self._view().get(key)

    def items(self) -> dict[str, bytes]:
        return dict(self._view())

    @property
    def is_caught_up(self) -> bool:
        return self._started

    @property
    def version(self) -> "int | None":
        # the topic folds at publish time, so its mutation counter IS the
        # view version (reads are always caught up on the local mesh)
        return self._mesh._topic(
            self._topic_name, create=True, compacted=True
        ).table_version


class _MemoryTableWriter(TableWriter):
    def __init__(self, mesh: InMemoryMesh, topic: str):
        self._mesh = mesh
        self._topic = topic

    async def put(self, key: str, value: bytes) -> None:
        await self._mesh.publish(self._topic, value, key=key.encode("utf-8"))

    async def tombstone(self, key: str) -> None:
        await self._mesh.publish(self._topic, b"", key=key.encode("utf-8"))
