"""The key-ordered dispatcher — the framework's concurrency model.

Invariants (reference: calfkit/_faststream_ext/_subscriber.py:102-350):

- N lanes; a record's lane is ``crc32(key) % N`` → strictly serial per key,
  parallel across keys.  Combined with task-keyed publishing this yields the
  single-writer-per-run property (see :mod:`calfkit_tpu.keying`).
- ONE global semaphore with bound ``2 × N`` is the sole backpressure:
  ``submit()`` blocks when 2N records are in flight, which stalls the
  consumer pull loop (broker-side flow control takes over from there).
- ACK-first: the caller acks/commits *before* ``submit()`` — crash-abandoned
  in-flight records are documented at-most-once.
- Graceful drain: ``stop()`` stops intake, then acquires every permit, which
  can only succeed once all in-flight handlers have finished.
- A permit-accounting bug must be loud, not a slow leak: releasing beyond the
  bound raises (the semaphore tripwire, reference :336-350).
- Keyless records are legal but warn once per dispatcher and serialize on
  lane 0 (they have no ordering contract to honor).
"""

from __future__ import annotations

import asyncio
import logging
import zlib
from typing import Awaitable, Callable

from calfkit_tpu.mesh.transport import Record

logger = logging.getLogger(__name__)


class _TripwireSemaphore(asyncio.Semaphore):
    """A semaphore whose value may never exceed its initial bound."""

    def __init__(self, value: int):
        super().__init__(value)
        self._bound = value

    def release(self) -> None:
        if self._value >= self._bound:
            raise RuntimeError(
                "key-ordered dispatcher permit over-release: accounting bug"
            )
        super().release()


class KeyOrderedDispatcher:
    def __init__(
        self,
        handler: Callable[[Record], Awaitable[None]],
        *,
        max_workers: int = 8,
        name: str = "dispatcher",
    ):
        if max_workers < 1:
            raise ValueError("max_workers must be >= 1")
        self._handler = handler
        self._lanes = max_workers
        self._name = name
        self._queues: list[asyncio.Queue[Record | None]] = [
            asyncio.Queue() for _ in range(max_workers)
        ]
        self._permits = _TripwireSemaphore(2 * max_workers)
        self._workers: list[asyncio.Task[None]] = []
        self._started = False
        self._stopping = False
        self._warned_keyless = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._workers = [
            asyncio.get_running_loop().create_task(
                self._serve_lane(i), name=f"{self._name}-lane-{i}"
            )
            for i in range(self._lanes)
        ]

    async def stop(self, *, drain_timeout: float = 5.0) -> None:
        """Stop intake and drain; wedged handlers are cancelled after
        ``drain_timeout`` so shutdown always terminates."""
        self._stopping = True
        drained = True
        try:
            # owning every permit proves no handler is still running
            async with asyncio.timeout(drain_timeout):
                for _ in range(2 * self._lanes):
                    await self._permits.acquire()
        except TimeoutError:
            drained = False
            logger.warning(
                "[%s] graceful drain timed out after %.1fs; cancelling in-flight handlers",
                self._name,
                drain_timeout,
            )
        for q in self._queues:
            q.put_nowait(None)
        for w in self._workers:
            if not drained:
                w.cancel()
        for w in self._workers:
            try:
                await asyncio.wait_for(w, timeout=1)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                w.cancel()
        self._workers = []
        self._started = False

    # -------------------------------------------------------------- intake
    def lane_of(self, key: bytes | None) -> int:
        if key is None:
            return 0
        return zlib.crc32(key) % self._lanes

    async def submit(self, record: Record) -> None:
        """Enqueue for ordered dispatch; blocks at the 2N in-flight bound."""
        if not self._started:
            raise RuntimeError("dispatcher not started")
        if self._stopping:
            return
        if record.key is None and not self._warned_keyless:
            self._warned_keyless = True
            logger.warning(
                "[%s] keyless record on %s: no ordering contract, using lane 0",
                self._name,
                record.topic,
            )
        await self._permits.acquire()
        self._queues[self.lane_of(record.key)].put_nowait(record)

    # -------------------------------------------------------------- lanes
    async def _serve_lane(self, lane: int) -> None:
        queue = self._queues[lane]
        while True:
            record = await queue.get()
            if record is None:
                return
            try:
                await self._handler(record)
            except asyncio.CancelledError:
                task = asyncio.current_task()
                if task is not None and task.cancelling():
                    raise  # stop() is cancelling this worker
                # handler-originated cancellation (e.g. it cancelled a child
                # and let the error escape): a fault, not a shutdown — the
                # lane must survive or its queued records leak permits
                logger.exception(
                    "[%s] handler leaked CancelledError on %s (lane %d)",
                    self._name,
                    record.topic,
                    lane,
                )
            except BaseException:
                # the handler owns its fault rail; anything escaping it is a
                # floor-level bug — log loudly, never kill the lane
                logger.exception(
                    "[%s] handler escaped its fault rail on %s (lane %d)",
                    self._name,
                    record.topic,
                    lane,
                )
            finally:
                self._permits.release()
