"""The key-ordered dispatcher — the framework's concurrency model.

Invariants (reference: calfkit/_faststream_ext/_subscriber.py:102-350):

- N lanes; a record's lane is ``crc32(key) % N`` → strictly serial per key,
  parallel across keys.  Combined with task-keyed publishing this yields the
  single-writer-per-run property (see :mod:`calfkit_tpu.keying`).
- ONE global semaphore with bound ``2 × N`` is the sole backpressure:
  ``submit()`` blocks when 2N records are in flight, which stalls the
  consumer pull loop (broker-side flow control takes over from there).
- ACK-first: the caller acks/commits *before* ``submit()`` — crash-abandoned
  in-flight records are documented at-most-once.
- Graceful drain: ``stop()`` stops intake, then acquires every permit, which
  can only succeed once all in-flight handlers have finished.
- A permit-accounting bug must be loud, not a slow leak: releasing beyond the
  bound raises (the semaphore tripwire, reference :336-350).
- Keyless records are legal but warn once per dispatcher and serialize on
  lane 0 (they have no ordering contract to honor).
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import asyncio
import logging
import threading
import time
import weakref
from typing import Awaitable, Callable

from calfkit_tpu import protocol
from calfkit_tpu.fleet import selection
from calfkit_tpu.mesh.transport import Record
from calfkit_tpu.observability.metrics import REGISTRY
from calfkit_tpu.observability.trace import TRACER, TraceContext

logger = logging.getLogger(__name__)

# lane telemetry: how long records sit queued behind their key's lane
# (the "where did the time go" gap between publish and handler start).
# Buckets span sub-ms (healthy lanes) through tens of seconds (a stalled
# lane is exactly what this metric exists to expose — capping at 1 s
# would hide the pathology in +Inf)
_LANE_WAIT_BUCKETS_MS = (
    0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
    1000.0, 5000.0, 10000.0, 30000.0,
)
_QUEUE_WAIT = REGISTRY.histogram(
    "calfkit_dispatch_queue_wait_ms",
    "time a record spent queued in its key-ordered lane (ms)",
    buckets=_LANE_WAIT_BUCKETS_MS,
)
_RECORDS = REGISTRY.counter(
    "calfkit_dispatch_records_total", "records dispatched through lanes"
)

# saturation signals (ISSUE 4 satellite): the queue-wait histogram only
# shows trouble AFTER records have waited — depth and in-flight gauges
# show the build-up while it happens.  Our exposition has no labels, so
# per-lane depth is surfaced as (total, deepest-single-lane): the max
# gauge is exactly the "one stalled key serializes its lane" pathology a
# per-lane breakdown exists to catch.  Values aggregate across every live
# dispatcher in the process (one per node), mirroring the engine's
# active-request gauge: last-writer-wins would let an idle node's
# dispatcher zero out a saturated one.
_QUEUE_DEPTH = REGISTRY.gauge(
    "calfkit_dispatch_queue_depth",
    "records queued in key-ordered lanes (summed over lanes + dispatchers)",
)
_LANE_DEPTH_MAX = REGISTRY.gauge(
    "calfkit_dispatch_lane_depth_max",
    "deepest single key-ordered lane across the process's dispatchers",
)
_IN_FLIGHT = REGISTRY.gauge(
    "calfkit_dispatch_records_in_flight",
    "records submitted but not yet finished (queued + in handlers)",
)
_DEPTH_LOCK = threading.Lock()
_DEPTH_BY_DISPATCHER: "dict[int, tuple[int, int, int]]" = {}


@hotpath
def _publish_depth(key: int, total: int, deepest: int, in_flight: int) -> None:
    with _DEPTH_LOCK:
        _DEPTH_BY_DISPATCHER[key] = (total, deepest, in_flight)
        totals = _DEPTH_BY_DISPATCHER.values()
        depth = sum(t for t, _, _ in totals)
        max_lane = max((d for _, d, _ in totals), default=0)
        flight = sum(f for _, _, f in totals)
    _QUEUE_DEPTH.set(depth)
    _LANE_DEPTH_MAX.set(max_lane)
    _IN_FLIGHT.set(flight)


def _drop_depth(key: int) -> None:
    """Remove a stopped/abandoned dispatcher from the aggregation and
    re-set the gauges, so its final counts never pin the exposition."""
    with _DEPTH_LOCK:
        if _DEPTH_BY_DISPATCHER.pop(key, None) is None:
            return
        totals = _DEPTH_BY_DISPATCHER.values()
        depth = sum(t for t, _, _ in totals)
        max_lane = max((d for _, d, _ in totals), default=0)
        flight = sum(f for _, _, f in totals)
    _QUEUE_DEPTH.set(depth)
    _LANE_DEPTH_MAX.set(max_lane)
    _IN_FLIGHT.set(flight)


class _LaneTask(asyncio.Task):
    """A lane worker task that records cancel() requests.

    ``Task.cancelling()`` is 3.11+; on the image's 3.10 a lane cannot
    otherwise distinguish "this task was cancelled" (stop(), asyncio.run
    teardown, an enclosing scope — must terminate) from "the handler
    raised CancelledError itself" (a fault the lane must survive).  The
    flag emulates exactly the cancelling() signal: set by ANY cancel()
    delivery, regardless of who called it."""

    _cancel_requested = False

    def cancel(self, msg: "str | None" = None) -> bool:
        self._cancel_requested = True
        return super().cancel(msg)


def _task_cancel_requested(task: "asyncio.Task | None") -> bool:
    if task is None:
        return False
    if getattr(task, "_cancel_requested", False):
        return True
    cancelling = getattr(task, "cancelling", None)  # 3.11+ native signal
    return cancelling is not None and bool(cancelling())


class _TripwireSemaphore(asyncio.Semaphore):
    """A semaphore whose value may never exceed its initial bound."""

    def __init__(self, value: int):
        super().__init__(value)
        self._bound = value

    def release(self) -> None:
        if self._value >= self._bound:
            raise RuntimeError(
                "key-ordered dispatcher permit over-release: accounting bug"
            )
        super().release()


class KeyOrderedDispatcher:
    def __init__(
        self,
        handler: Callable[[Record], Awaitable[None]],
        *,
        max_workers: int = 8,
        name: str = "dispatcher",
    ):
        if max_workers < 1:
            raise ValueError("max_workers must be >= 1")
        self._handler = handler
        self._lanes = max_workers
        self._name = name
        # queue items are (record, enqueue perf_counter) for queue-wait
        # attribution; None is the drain sentinel
        self._queues: list[asyncio.Queue[tuple[Record, float] | None]] = [
            # unbounded-ok: total queued records across all lanes are
            # bounded by the 2*max_workers permit semaphore submit()
            # acquires before enqueueing — a maxsize would deadlock the
            # permit holder
            asyncio.Queue() for _ in range(max_workers)
        ]
        self._permits = _TripwireSemaphore(2 * max_workers)
        self._workers: list[asyncio.Task[None]] = []
        self._started = False
        self._stopping = False
        self._warned_keyless = False
        # a dispatcher abandoned without stop() must not pin its last
        # depth/in-flight counts into the process gauges
        weakref.finalize(self, _drop_depth, id(self))

    @hotpath
    def _update_depth_gauges(self) -> None:
        """Recompute this dispatcher's saturation signals (O(lanes)) and
        fold them into the process gauges.  Called per submit and per lane
        dequeue/finish — the gauges track the live build-up, not a poll."""
        depths = [q.qsize() for q in self._queues]
        _publish_depth(
            id(self),
            sum(depths),
            max(depths, default=0),
            2 * self._lanes - self._permits._value,
        )

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        loop = asyncio.get_running_loop()
        self._workers = [
            _LaneTask(
                self._serve_lane(i), loop=loop, name=f"{self._name}-lane-{i}"
            )
            for i in range(self._lanes)
        ]

    async def stop(self, *, drain_timeout: float = 5.0) -> None:
        """Stop intake and drain; wedged handlers are cancelled after
        ``drain_timeout`` so shutdown always terminates."""
        self._stopping = True
        drained = True

        async def acquire_all() -> None:
            # owning every permit proves no handler is still running
            for _ in range(2 * self._lanes):
                await self._permits.acquire()

        try:
            # wait_for, not asyncio.timeout: the image runs 3.10, where
            # asyncio.timeout does not exist (stop() used to raise
            # AttributeError here and rely on callers suppressing it)
            await asyncio.wait_for(acquire_all(), drain_timeout)
        except (TimeoutError, asyncio.TimeoutError):
            drained = False
            logger.warning(
                "[%s] graceful drain timed out after %.1fs; cancelling in-flight handlers",
                self._name,
                drain_timeout,
            )
        for q in self._queues:
            q.put_nowait(None)
        # swap-then-iterate (meshlint await-atomicity): detach before the
        # awaits — _stopping is already set, so no new lane task can spawn
        # into a snapshot we already walked
        workers, self._workers = self._workers, []
        if not drained:
            for w in workers:
                w.cancel()
        for w in workers:
            try:
                await asyncio.wait_for(w, timeout=1)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                w.cancel()
        self._started = False
        _drop_depth(id(self))

    # -------------------------------------------------------------- intake
    @hotpath
    def lane_of(self, key: bytes | None) -> int:
        # the lane law lives in the fleet selection seam (ISSUE 7) so
        # lane assignment and replica placement share one set of
        # primitives; semantics unchanged (crc32, keyless -> lane 0)
        return selection.lane_of(key, self._lanes)

    async def submit(self, record: Record) -> None:
        """Enqueue for ordered dispatch; blocks at the 2N in-flight bound."""
        if not self._started:
            raise RuntimeError("dispatcher not started")
        if self._stopping:
            return
        if (record.headers or {}).get(protocol.HDR_KIND) == "cancel":
            # control-record preemption (ISSUE 5): a `cancel` rides the
            # same task key as the call it abandons, so the ordered lane
            # would queue it BEHIND that very call — undeliverable until
            # the work it exists to stop has finished.  Cancels are
            # advisory, body-less and idempotent: handle inline on the
            # pull task, skipping lanes and permits.  Fail-open.
            try:
                await self._handler(record)
            except Exception:  # noqa: BLE001 - advisory, never stalls intake
                logger.exception(
                    "[%s] cancel-record handler failed on %s",
                    self._name, record.topic,
                )
            return
        if record.key is None and not self._warned_keyless:
            self._warned_keyless = True
            logger.warning(
                "[%s] keyless record on %s: no ordering contract, using lane 0",
                self._name,
                record.topic,
            )
        await self._permits.acquire()
        self._queues[self.lane_of(record.key)].put_nowait(
            (record, time.perf_counter())
        )
        self._update_depth_gauges()

    # -------------------------------------------------------------- lanes
    async def _serve_lane(self, lane: int) -> None:
        queue = self._queues[lane]
        while True:
            item = await queue.get()
            if item is None:
                return
            record, enqueued = item
            wait_ms = (time.perf_counter() - enqueued) * 1000.0
            _QUEUE_WAIT.observe(wait_ms)
            _RECORDS.inc()
            self._update_depth_gauges()  # dequeued: depth down, in-flight holds
            # traced records get a dispatch span (parent: the emitting
            # hop's span) covering HANDLER time, with the preceding lane
            # wait carried as the queue_wait_ms attr; untraced records
            # (heartbeats, control plane) pay only the two
            # histogram/counter calls above
            span = None
            remote = TraceContext.from_headers(record.headers)
            if remote is not None:
                span = TRACER.start_span(
                    "mesh.dispatch",
                    parent=remote,
                    kind="dispatch",
                    emitter=self._name,
                    attrs={
                        "topic": record.topic,
                        "lane": lane,
                        "queue_wait_ms": round(wait_ms, 3),
                    },
                )
            status = None
            try:
                await self._handler(record)
            except asyncio.CancelledError:
                # was OUR task cancelled (stop(), asyncio.run teardown, an
                # enclosing scope — terminate), or did the handler raise
                # CancelledError itself (a fault the lane must survive)?
                # _LaneTask records cancel() deliveries so this works on
                # 3.10 too, where Task.cancelling() does not exist.
                if _task_cancel_requested(asyncio.current_task()):
                    if span is not None:
                        span.end(status="cancelled")
                        span = None
                    raise
                status = "error"
                logger.exception(
                    "[%s] handler leaked CancelledError on %s (lane %d)",
                    self._name,
                    record.topic,
                    lane,
                )
            except BaseException:
                # the handler owns its fault rail; anything escaping it is a
                # floor-level bug — log loudly, never kill the lane
                status = "error"
                logger.exception(
                    "[%s] handler escaped its fault rail on %s (lane %d)",
                    self._name,
                    record.topic,
                    lane,
                )
            finally:
                if span is not None:
                    span.end(status=status)
                self._permits.release()
                self._update_depth_gauges()  # handler done: in-flight down
