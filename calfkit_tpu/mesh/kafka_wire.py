"""Native Kafka wire-protocol client + MeshTransport (zero deps).

The reference's production transport depends on aiokafka against a real
broker; this image ships neither, so that lane could never run in-image
(VERDICT r3 item 4).  This module closes the gap natively: an
asyncio client speaking the REAL Kafka wire protocol — RecordBatch v2
(crc32c, zigzag varints), consumer groups with generations and
client-side range assignment, offset commit/fetch — against any
Kafka-compatible broker: the in-repo ``native/bin/kafkad``, or a real
Kafka/Redpanda cluster.

API versions spoken (fixed, non-flexible — accepted by kafkad and by
real brokers): ApiVersions v0, Metadata v1, Produce v3, Fetch v4,
ListOffsets v1, FindCoordinator v0, JoinGroup v2, SyncGroup v1,
Heartbeat v1, LeaveGroup v1, OffsetCommit v2, OffsetFetch v1,
CreateTopics v0.

``KafkaWireMesh`` maps the transport contract the same way KafkaMesh
does (ACK-first auto-commit, broadcast taps from latest, key-ordered
dispatch), but with zero third-party dependencies.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import logging
import os
import ssl as ssl_module
import struct
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Mapping

from calfkit_tpu.mesh.connection import DEFAULT_MAX_MESSAGE_BYTES
from calfkit_tpu.protocol import header_map as protocol_header_map
from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.transport import (
    CallbackSubscription,
    MeshTransport,
    Record,
    RecordHandler,
    Subscription,
)

logger = logging.getLogger(__name__)

def find_kafkad() -> str | None:
    """Locate the in-repo native broker binary ($CALFKIT_KAFKAD overrides)."""
    from calfkit_tpu.mesh._native import find_native_binary

    return find_native_binary("kafkad", "CALFKIT_KAFKAD")


def spawn_kafkad(port: int = 0, *, start_new_session: bool = False,
                 sasl: str | None = None, advertise_port: int | None = None,
                 log_dir: str | None = None):
    """Spawn the native Kafka-wire broker; port 0 = OS-assigned (reported
    on stdout as ``PORT <n>``, exposed as ``proc.kafkad_port``).
    ``sasl="user:pass"`` requires SASL/PLAIN from every connection;
    ``advertise_port`` is the ``advertised.listeners`` equivalent (what
    metadata/find_coordinator report — set it when a TLS terminator or
    port-forward sits in front of the broker); ``log_dir`` turns on the
    append-only WAL: topics, records, and committed offsets survive a
    broker restart (without it retention is memory-only)."""
    from calfkit_tpu.mesh._native import spawn_port_reporting

    binary = find_kafkad()
    if binary is None:
        raise FileNotFoundError(
            "kafkad binary not found: run `make -C native` or set "
            "CALFKIT_KAFKAD"
        )
    extra: list[str] = []
    if sasl:
        extra += ["--sasl", sasl]
    if advertise_port:
        extra += ["--advertise-port", str(advertise_port)]
    if log_dir:
        extra += ["--log-dir", str(log_dir)]
    proc, bound = spawn_port_reporting(
        binary, port, name="kafkad", start_new_session=start_new_session,
        extra_args=extra,
    )
    proc.kafkad_port = bound  # type: ignore[attr-defined]
    return proc


# ------------------------------------------------------------------ crc32c
_CRC_TABLE: list[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (0x82F63B78 ^ (_c >> 1)) if (_c & 1) else (_c >> 1)
    _CRC_TABLE.append(_c)


def _crc32c_py(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _load_crc32c():
    """Prefer the in-repo native library (SSE4.2 / slice-by-8 — memory
    speed) so always-on CRC verification can't stall the event loop; the
    pure-Python table is the dependency-free fallback."""
    try:
        from calfkit_tpu.mesh._native import find_native_binary

        path = find_native_binary("libcrc32c.so", "CALFKIT_CRC32C")
        if path is None:
            return _crc32c_py
        import ctypes

        lib = ctypes.CDLL(path)
        lib.calfkit_crc32c.restype = ctypes.c_uint32
        lib.calfkit_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        fn = lib.calfkit_crc32c
        if fn(b"123456789", 9) != 0xE3069283:  # self-check before trusting
            return _crc32c_py

        def _crc32c_native(data: bytes) -> int:
            return fn(data, len(data))

        return _crc32c_native
    except Exception:  # noqa: BLE001
        return _crc32c_py


crc32c = _load_crc32c()

# largest record_set decoded ON the event loop: with native crc32c the
# whole decode is memory-speed; the pure-Python fallback (~100 ns/byte)
# gets a much lower bar so crc verification can't starve heartbeats
_SYNC_DECODE_MAX = 65536 if crc32c.__name__ == "_crc32c_native" else 8192


# keys + header bytes get their own budget alongside the value budget —
# the fetch floor covers both, so the biggest legal RECORD always fits
KEY_HEADERS_CAP = 1024 * 1024


def fetch_floor(max_message_bytes: int) -> int:
    """The consumer fetch budget implied by the producer message budget
    (the ConnectionProfile coordinated-knob law): floored at 4 MiB, and
    always max_message_bytes + the key/headers cap + framing headroom so
    the biggest legal record is always fetchable."""
    return max(
        4 * 1024 * 1024, max_message_bytes + KEY_HEADERS_CAP + 64 * 1024
    )


async def _decode_off_loop(blob: bytes):
    """Decode a fetch's record_set, moving big blobs to a worker thread
    (mirrors the publish path's encode offload)."""
    if len(blob) > _SYNC_DECODE_MAX:
        return await asyncio.to_thread(decode_record_batches, blob)
    return decode_record_batches(blob)


# ------------------------------------------------------------------ codecs
class _W:
    __slots__ = ("parts",)

    def __init__(self):
        self.parts: list[bytes] = []

    def i8(self, v: int): self.parts.append(struct.pack(">b", v))
    def i16(self, v: int): self.parts.append(struct.pack(">h", v))
    def i32(self, v: int): self.parts.append(struct.pack(">i", v))
    def i64(self, v: int): self.parts.append(struct.pack(">q", v))
    def raw(self, b: bytes): self.parts.append(b)

    def varlong(self, v: int):
        z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        z &= (1 << 64) - 1
        out = bytearray()
        while z >= 0x80:
            out.append((z & 0x7F) | 0x80)
            z >>= 7
        out.append(z)
        self.parts.append(bytes(out))

    def string(self, s: str | None):
        if s is None:
            self.i16(-1)
        else:
            raw = s.encode("utf-8")
            self.i16(len(raw))
            self.raw(raw)

    def bytes_(self, b: bytes | None):
        if b is None:
            self.i32(-1)
        else:
            self.i32(len(b))
            self.raw(b)

    def done(self) -> bytes:
        return b"".join(self.parts)


class _R:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def i8(self) -> int:
        v = struct.unpack_from(">b", self.buf, self.pos)[0]
        self.pos += 1
        return v

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.buf, self.pos)[0]
        self.pos += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.buf, self.pos)[0]
        self.pos += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.buf, self.pos)[0]
        self.pos += 8
        return v

    def varlong(self) -> int:
        z = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (z >> 1) ^ -(z & 1)

    def string(self) -> str:
        n = self.i16()
        if n < 0:
            return ""
        s = self.buf[self.pos:self.pos + n].decode("utf-8", errors="replace")
        self.pos += n
        return s

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b


def encode_record_batch(
    records: "list[tuple[bytes | None, bytes | None, list[tuple[str, bytes]]]]",
    timestamp_ms: int,
) -> bytes:
    """[(key, value, headers)] → one RecordBatch v2 blob (baseOffset 0 —
    the broker assigns real offsets)."""
    recs = _W()
    for i, (key, value, headers) in enumerate(records):
        body = _W()
        body.i8(0)            # record attributes
        body.varlong(0)       # timestampDelta
        body.varlong(i)       # offsetDelta
        if key is None:
            body.varlong(-1)
        else:
            body.varlong(len(key))
            body.raw(key)
        if value is None:
            body.varlong(-1)
        else:
            body.varlong(len(value))
            body.raw(value)
        body.varlong(len(headers))
        for hk, hv in headers:
            hkb = hk.encode("utf-8")
            body.varlong(len(hkb))
            body.raw(hkb)
            body.varlong(len(hv))
            body.raw(hv)
        blob = body.done()
        recs.varlong(len(blob))
        recs.raw(blob)
    recblob = recs.done()

    crcbody = _W()
    crcbody.i16(0)                       # attributes (no compression)
    crcbody.i32(len(records) - 1)        # lastOffsetDelta
    crcbody.i64(timestamp_ms)
    crcbody.i64(timestamp_ms)
    crcbody.i64(-1)                      # producerId
    crcbody.i16(-1)                      # producerEpoch
    crcbody.i32(-1)                      # baseSequence
    crcbody.i32(len(records))
    crcbody.raw(recblob)
    crcblob = crcbody.done()

    crc = crc32c(crcblob)
    out = _W()
    out.i64(0)                           # baseOffset
    out.i32(4 + 1 + 4 + len(crcblob))    # batchLength
    out.i32(0)                           # partitionLeaderEpoch
    out.i8(2)                            # magic
    out.i32(crc - (1 << 32) if crc >= (1 << 31) else crc)
    out.raw(crcblob)
    return out.done()


_COMPRESSION_NAMES = {1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}


def _decompress_records(codec: int, payload: bytes) -> bytes:
    """Inflate a compressed RecordBatch records-section (real brokers —
    kafkad and this module's producer never compress).  gzip rides the
    stdlib; the other codecs raise loudly instead of mis-parsing."""
    if codec == 1:
        import gzip

        try:
            return gzip.decompress(payload)
        except Exception as exc:  # noqa: BLE001 — BadGzipFile/zlib.error/EOFError
            raise RecordBatchError(f"corrupt gzip RecordBatch: {exc}") from exc
    name = _COMPRESSION_NAMES.get(codec, f"codec-{codec}")
    raise RecordBatchError(
        f"compressed RecordBatch ({name}) unsupported by the native wire "
        f"client — configure the producing side for gzip or no compression"
    )


def decode_record_batches(
    blob: bytes,
) -> "list[tuple[int, int, bytes | None, bytes | None, list[tuple[str, bytes]]]]":
    """Fetch record_set → [(offset, timestamp_ms, key, value, headers)].

    A truncated TRAILING batch (broker max_bytes cut) is dropped silently
    per the Kafka contract; corruption anywhere else raises a typed
    :class:`RecordBatchError` instead of a raw struct/index error."""
    out = []
    r = _R(blob)
    n = len(blob)
    while r.pos + 61 <= n:  # minimal batch header size
        base_offset = r.i64()
        batch_len = r.i32()
        batch_end = r.pos + batch_len
        if batch_end > n:
            break  # truncated trailing batch (broker max_bytes cut)
        if batch_len < 9:  # can't even hold epoch+magic+crc in any format
            raise RecordBatchError(f"batchLength {batch_len} not plausible")
        try:
            r.i32()  # partitionLeaderEpoch
            magic = r.i8()
            if magic != 2:
                # legacy v0/v1 message-set entry (magic shares this offset
                # across all formats): skip cleanly, don't size-check it
                r.pos = batch_end
                continue
            if batch_len < 49:  # smaller than the v2 header that must follow
                raise RecordBatchError(
                    f"batchLength {batch_len} below header size"
                )
            crc = r.i32() & 0xFFFFFFFF
            # crc covers attrs..end; verified on EVERY batch (native crc32c
            # makes this memory-speed) so a corrupt frame raises typed
            # instead of decoding to garbage records
            if crc32c(r.buf[r.pos:batch_end]) != crc:
                raise RecordBatchError("RecordBatch crc32c mismatch")
            attrs = r.i16()
            r.i32()  # lastOffsetDelta
            first_ts = r.i64()
            r.i64()  # maxTimestamp
            r.i64()  # producerId
            r.i16()  # producerEpoch
            r.i32()  # baseSequence
            count = r.i32()
            codec = attrs & 0x07
            if codec:
                rr = _R(_decompress_records(codec, r.buf[r.pos:batch_end]))
            else:
                rr = r
            for _ in range(count):
                rec_len = rr.varlong()
                rec_end = rr.pos + rec_len
                if rec_len < 0 or rec_end > len(rr.buf):
                    raise RecordBatchError(f"record length {rec_len} overruns batch")
                rr.i8()  # attributes
                ts_delta = rr.varlong()
                off_delta = rr.varlong()
                klen = rr.varlong()
                key = None
                if klen >= 0:
                    key = rr.buf[rr.pos:rr.pos + klen]
                    rr.pos += klen
                vlen = rr.varlong()
                value = None
                if vlen >= 0:
                    value = rr.buf[rr.pos:rr.pos + vlen]
                    rr.pos += vlen
                headers = []
                hcount = rr.varlong()
                if hcount < 0:
                    raise RecordBatchError(f"negative header count {hcount}")
                for _ in range(hcount):
                    hklen = rr.varlong()
                    hk = rr.buf[rr.pos:rr.pos + hklen].decode("utf-8", "replace")
                    rr.pos += hklen
                    hvlen = rr.varlong()
                    hv = b""
                    if hvlen >= 0:
                        hv = rr.buf[rr.pos:rr.pos + hvlen]
                        rr.pos += hvlen
                    headers.append((hk, hv))
                if rr.pos > rec_end:
                    raise RecordBatchError("record fields overran record length")
                rr.pos = rec_end
                out.append(
                    (base_offset + off_delta, first_ts + ts_delta, key, value,
                     headers)
                )
        except (struct.error, IndexError) as exc:
            raise RecordBatchError(f"corrupt RecordBatch: {exc}") from exc
        r.pos = batch_end
    return out


def murmur2(data: bytes) -> int:
    """Kafka's default partitioner hash (murmur2, seed 0x9747b28c)."""
    length = len(data)
    seed = 0x9747B28C
    m = 0x5BD1E995
    mask = 0xFFFFFFFF
    h = (seed ^ length) & mask
    i = 0
    while length - i >= 4:
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * m) & mask
        k ^= k >> 24
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
        i += 4
    rem = length - i
    if rem == 3:
        h ^= data[i + 2] << 16
    if rem >= 2:
        h ^= data[i + 1] << 8
    if rem >= 1:
        h ^= data[i]
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    return h


def partition_for(key: bytes | None, n: int, counter: list[int]) -> int:
    if key is None:
        counter[0] = (counter[0] + 1) % n
        return counter[0]
    return (murmur2(key) & 0x7FFFFFFF) % n


# --------------------------------------------------------------- security
_SUPPORTED_PROTOCOLS = ("PLAINTEXT", "SSL", "SASL_PLAINTEXT", "SASL_SSL")
_SUPPORTED_MECHANISMS = ("PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512")
_SECURITY_KEYS = (
    "security_protocol", "ssl_context", "sasl_mechanism",
    "sasl_plain_username", "sasl_plain_password",
)


@dataclass(frozen=True)
class WireSecurity:
    """The wire client's security config, parsed from the same
    aiokafka-style ``security=`` mapping :class:`ConnectionProfile`
    carries (reference: calfkit/client/_connection.py:39-110 threads
    SSL/SASL through every client the same way).  Anything the native
    client cannot honor fails LOUDLY at construction — a secured cluster
    must never be contacted with security silently dropped."""

    protocol: str = "PLAINTEXT"
    ssl_context: "ssl_module.SSLContext | None" = None
    sasl_mechanism: str | None = None
    username: str | None = None
    password: str | None = None

    @property
    def uses_tls(self) -> bool:
        return self.protocol in ("SSL", "SASL_SSL")

    @property
    def uses_sasl(self) -> bool:
        return self.protocol in ("SASL_PLAINTEXT", "SASL_SSL")

    @classmethod
    def from_security_kwargs(cls, security: "Mapping[str, Any]") -> "WireSecurity":
        unknown = sorted(set(security) - set(_SECURITY_KEYS))
        if unknown:
            raise ValueError(
                f"security keys {unknown} are not supported by the native "
                f"kafka wire client (supported: {list(_SECURITY_KEYS)}); "
                "supply supported keys or terminate security out-of-process"
            )
        protocol = str(security.get("security_protocol", "PLAINTEXT")).upper()
        if protocol not in _SUPPORTED_PROTOCOLS:
            raise ValueError(
                f"security_protocol {protocol!r} unsupported by the native "
                f"wire client (supported: {list(_SUPPORTED_PROTOCOLS)})"
            )
        mechanism = security.get("sasl_mechanism")
        if mechanism is not None:
            mechanism = str(mechanism).upper()
            if mechanism not in _SUPPORTED_MECHANISMS:
                raise ValueError(
                    f"sasl_mechanism {mechanism!r} unsupported by the native "
                    f"wire client (supported: {list(_SUPPORTED_MECHANISMS)}); "
                    "GSSAPI/OAUTHBEARER need an out-of-process authenticator"
                )
        out = cls(
            protocol=protocol,
            ssl_context=security.get("ssl_context"),
            sasl_mechanism=mechanism,
            username=security.get("sasl_plain_username"),
            password=security.get("sasl_plain_password"),
        )
        if out.ssl_context is not None and not out.uses_tls:
            raise ValueError(
                f"ssl_context given but security_protocol is {protocol} — "
                "use SSL or SASL_SSL (refusing to connect in cleartext "
                "when TLS material was supplied)"
            )
        if out.uses_sasl:
            if not out.sasl_mechanism:
                raise ValueError(f"{protocol} requires sasl_mechanism")
            if out.username is None or out.password is None:
                raise ValueError(
                    f"{protocol} requires sasl_plain_username and "
                    "sasl_plain_password"
                )
        elif out.sasl_mechanism:
            raise ValueError(
                "sasl_mechanism given but security_protocol is "
                f"{protocol} (use SASL_PLAINTEXT or SASL_SSL)"
            )
        return out

    def resolved_ssl_context(self) -> "ssl_module.SSLContext | None":
        if not self.uses_tls:
            return None
        return self.ssl_context or ssl_module.create_default_context()


PLAINTEXT = WireSecurity()


class ScramClient:
    """RFC 5802 SCRAM client (SHA-256 / SHA-512), stdlib only.

    Three-message exchange: ``first()`` → server-first → ``final()`` →
    server-final → ``verify()`` (which authenticates the SERVER — a
    man-in-the-middle cannot forge the v= signature without the password).
    """

    def __init__(self, mechanism: str, username: str, password: str,
                 cnonce: str | None = None):
        self._hash = {
            "SCRAM-SHA-256": hashlib.sha256,
            "SCRAM-SHA-512": hashlib.sha512,
        }[mechanism]
        self._username = username
        self._password = password.encode("utf-8")
        self._cnonce = cnonce or base64.b64encode(os.urandom(24)).decode()
        self._first_bare = ""
        self._auth_message = b""
        self._salted = b""

    @staticmethod
    def _escape(name: str) -> str:
        return name.replace("=", "=3D").replace(",", "=2C")

    def first(self) -> bytes:
        self._first_bare = f"n={self._escape(self._username)},r={self._cnonce}"
        return ("n,," + self._first_bare).encode("utf-8")

    def final(self, server_first: bytes) -> bytes:
        text = server_first.decode("utf-8")
        fields = dict(f.split("=", 1) for f in text.split(","))
        snonce, salt_b64, iterations = fields["r"], fields["s"], int(fields["i"])
        if not snonce.startswith(self._cnonce):
            raise KafkaWireError("scram: server nonce does not extend ours", -1)
        self._salted = hashlib.pbkdf2_hmac(
            self._hash().name, self._password,
            base64.b64decode(salt_b64), iterations,
        )
        client_key = hmac.new(self._salted, b"Client Key", self._hash).digest()
        stored_key = self._hash(client_key).digest()
        without_proof = f"c=biws,r={snonce}"
        self._auth_message = ",".join(
            [self._first_bare, text, without_proof]
        ).encode("utf-8")
        client_sig = hmac.new(stored_key, self._auth_message, self._hash).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        return (
            without_proof + ",p=" + base64.b64encode(proof).decode()
        ).encode("utf-8")

    def verify(self, server_final: bytes) -> None:
        text = server_final.decode("utf-8")
        fields = dict(f.split("=", 1) for f in text.split(","))
        if "e" in fields:
            raise KafkaWireError(f"scram: server error {fields['e']}", -1)
        server_key = hmac.new(self._salted, b"Server Key", self._hash).digest()
        expected = hmac.new(server_key, self._auth_message, self._hash).digest()
        if base64.b64decode(fields["v"]) != expected:
            raise KafkaWireError("scram: server signature mismatch", -1)


# --------------------------------------------------------------- protocol
class KafkaWireError(Exception):
    def __init__(self, api: str, code: int):
        self.code = code
        super().__init__(f"{api} error_code={code}")


class RecordBatchError(KafkaWireError):
    """A RecordBatch that cannot be parsed safely (corrupt frame, crc
    mismatch, or a compression codec the native client does not speak)."""

    def __init__(self, message: str):
        self.code = -1
        Exception.__init__(self, message)


ERR_OFFSET_OUT_OF_RANGE = 1
ERR_REBALANCE_IN_PROGRESS = 27
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER = 25


class _Conn:
    """One broker connection; requests serialized (responses arrive in
    order per connection on every Kafka-compatible broker)."""

    def __init__(self, host: str, port: int, client_id: str = "calfkit",
                 security: WireSecurity = PLAINTEXT):
        self.host, self.port = host, port
        self.client_id = client_id
        self.security = security
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()
        self._correlation = 0

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port,
            ssl=self.security.resolved_ssl_context(),
        )
        self._correlation = 0
        if self.security.uses_sasl:
            try:
                await self._sasl_authenticate()
            except BaseException:
                # a half-authenticated connection must not stay installed:
                # the next request() would reuse it, skip connect(), and
                # surface an opaque read error instead of the auth failure
                self._drop()
                raise

    async def _sasl_authenticate(self) -> None:
        """SaslHandshake v1 + SaslAuthenticate v0 on the fresh connection
        (v1 handshake = tokens ride wrapped SaslAuthenticate frames)."""
        mechanism = self.security.sasl_mechanism or "PLAIN"
        w = _W()
        w.string(mechanism)
        r = await self._roundtrip(17, 1, w.done())
        err = r.i16()
        if err:
            raise KafkaWireError(f"sasl_handshake({mechanism})", err)

        async def auth_round(token: bytes) -> bytes:
            body = _W()
            body.bytes_(token)
            resp = await self._roundtrip(36, 0, body.done())
            code = resp.i16()
            message = resp.string()
            auth = resp.bytes_() or b""
            if code:
                raise KafkaWireError(
                    f"sasl_authenticate: {message or 'failed'}", code
                )
            return auth

        user = self.security.username or ""
        password = self.security.password or ""
        if mechanism == "PLAIN":
            await auth_round(
                b"\0" + user.encode("utf-8") + b"\0" + password.encode("utf-8")
            )
        else:
            scram = ScramClient(mechanism, user, password)
            server_first = await auth_round(scram.first())
            server_final = await auth_round(scram.final(server_first))
            scram.verify(server_final)

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None
            self._reader = None

    def _drop(self) -> None:
        """Abandon the connection WITHOUT awaiting (safe under
        cancellation): the next request() reconnects from a clean stream."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def request(self, api_key: int, version: int, body: bytes) -> _R:
        async with self._lock:
            if self._writer is None:
                await self.connect()
            return await self._roundtrip(api_key, version, body)

    async def _roundtrip(self, api_key: int, version: int, body: bytes) -> _R:
        """One request/response on the live connection.  Callers hold the
        lock (request) or own the fresh connection (connect's SASL)."""
        self._correlation += 1
        header = _W()
        header.i16(api_key)
        header.i16(version)
        header.i32(self._correlation)
        header.string(self.client_id)
        payload = header.done() + body
        try:
            self._writer.write(struct.pack(">i", len(payload)) + payload)
            await self._writer.drain()
            szbuf = await self._reader.readexactly(4)
            size = struct.unpack(">i", szbuf)[0]
            blob = await self._reader.readexactly(size)
        except BaseException:
            # a cancellation (the fetch long-poll is where stop() lands)
            # or transport error mid-exchange leaves an unread response
            # in the stream — every later request would read the stale
            # frame and mis-correlate.  Drop the connection so the next
            # call starts clean.
            self._drop()
            raise
        r = _R(blob)
        correlation = r.i32()
        if correlation != self._correlation:
            self._drop()
            raise KafkaWireError("correlation-mismatch", -1)
        return r


ERR_NOT_LEADER = 6
ERR_NOT_COORDINATOR = 16


class KafkaWireClient:
    """Typed API calls with metadata-driven per-partition leader routing.

    One ``_Conn`` per broker address: produce/fetch/list_offsets go to the
    partition leader learned from Metadata, group APIs go to the group
    coordinator learned from FindCoordinator, everything else rides the
    bootstrap connection.  Against single-node brokers (kafkad) every
    route resolves to the bootstrap address and behavior is unchanged;
    against a spread-leader cluster each request lands on the right
    broker, with NOT_LEADER / NOT_COORDINATOR triggering a refresh +
    single retry."""

    def __init__(self, host: str, port: int, client_id: str = "calfkit",
                 security: WireSecurity = PLAINTEXT):
        self._client_id = client_id
        self._security = security
        self._conns: dict[tuple[str, int], _Conn] = {}
        self.conn = self._get_conn(host, port)  # bootstrap/control
        # routing state, refreshed from Metadata / FindCoordinator
        self._leaders: dict[tuple[str, int], tuple[str, int]] = {}
        self._coordinator: tuple[str, int] | None = None

    def _get_conn(self, host: str, port: int) -> _Conn:
        conn = self._conns.get((host, port))
        if conn is None:
            conn = _Conn(host, port, self._client_id, security=self._security)
            self._conns[(host, port)] = conn
        return conn

    def _leader_conn(self, topic: str, part: int) -> _Conn:
        addr = self._leaders.get((topic, part))
        return self._get_conn(*addr) if addr else self.conn

    def _coord_conn(self) -> _Conn:
        return (
            self._get_conn(*self._coordinator) if self._coordinator
            else self.conn
        )

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()

    async def metadata(self, topics: list[str] | None) -> dict:
        w = _W()
        if topics is None:
            w.i32(-1)
        else:
            w.i32(len(topics))
            for t in topics:
                w.string(t)
        r = await self.conn.request(3, 1, w.done())
        nbrokers = r.i32()
        brokers = []
        nodes: dict[int, tuple[str, int]] = {}
        for _ in range(nbrokers):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            brokers.append((node, host, port))
            nodes[node] = (host, port)
        r.i32()  # controller
        out: dict = {"brokers": brokers, "topics": {}}
        for _ in range(r.i32()):
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            parts = []
            for _ in range(r.i32()):
                r.i16()  # partition error
                idx = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()
                for _ in range(r.i32()):
                    r.i32()
                parts.append(idx)
                if leader in nodes:
                    self._leaders[(name, idx)] = nodes[leader]
                else:
                    self._leaders.pop((name, idx), None)  # leaderless
            out["topics"][name] = {"error": err, "partitions": sorted(parts)}
        return out

    async def _refresh_leaders(self, topics: "list[str]") -> None:
        try:
            await self.metadata(sorted(set(topics)))
        except Exception:  # noqa: BLE001 — routing refresh is best-effort
            logger.warning("kafka-wire metadata refresh failed", exc_info=True)

    async def create_topics(
        self, topics: list[str], partitions: int, *, compacted: bool = False
    ) -> dict[str, int]:
        w = _W()
        w.i32(len(topics))
        for name in topics:
            w.string(name)
            w.i32(partitions)
            w.i16(1)   # replication
            w.i32(0)   # manual assignments
            if compacted:
                w.i32(1)
                w.string("cleanup.policy")
                w.string("compact")
            else:
                w.i32(0)
        w.i32(10000)  # timeout
        r = await self.conn.request(19, 0, w.done())
        out = {}
        for _ in range(r.i32()):
            name = r.string()
            out[name] = r.i16()
        return out

    async def produce(
        self, topic: str, partition: int, batch: bytes
    ) -> int:
        w = _W()
        w.string(None)  # transactional_id
        w.i16(-1)       # acks=all
        w.i32(10000)
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.bytes_(batch)
        body = w.done()
        for attempt in (0, 1):
            conn = self._leader_conn(topic, partition)
            try:
                r = await conn.request(0, 3, body)
            except (OSError, EOFError):
                # leader connection died (EOFError covers the clean-close
                # IncompleteReadError signature): re-learn topology once
                if attempt == 0 and conn is not self.conn:
                    await self._refresh_leaders([topic])
                    continue
                raise
            base = -1
            err = 0
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    r.i32()  # partition
                    err = r.i16()
                    base = r.i64()
                    r.i64()  # log_append_time
            if err == ERR_NOT_LEADER and attempt == 0:
                await self._refresh_leaders([topic])
                continue
            if err:
                raise KafkaWireError("produce", err)
            return base
        # unreachable: attempt 1 always returned or raised above
        raise AssertionError("produce retry loop exhausted")

    async def _fetch_on(
        self, conn: _Conn, wants: "list[tuple[str, int, int]]",
        max_wait_ms: int, max_bytes: int,
    ) -> "list[tuple[str, int, int, bytes]]":
        w = _W()
        w.i32(-1)            # replica
        w.i32(max_wait_ms)
        w.i32(1)             # min_bytes
        w.i32(max_bytes)
        w.i8(0)              # isolation
        by_topic: dict[str, list[tuple[int, int]]] = {}
        for topic, part, off in wants:
            by_topic.setdefault(topic, []).append((part, off))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic)
            w.i32(len(parts))
            for part, off in parts:
                w.i32(part)
                w.i64(off)
                w.i32(max_bytes)
        r = await conn.request(1, 4, w.done())
        r.i32()  # throttle
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                part = r.i32()
                err = r.i16()
                r.i64()  # high watermark
                r.i64()  # last stable
                naborted = r.i32()
                for _ in range(max(0, naborted)):
                    r.i64()
                    r.i64()
                blob = r.bytes_()
                out.append((topic, part, err, blob or b""))
        return out

    async def fetch(
        self,
        wants: "list[tuple[str, int, int]]",
        *,
        max_wait_ms: int = 300,
        max_bytes: int = 4 * 1024 * 1024,
    ) -> "list[tuple[str, int, int, bytes]]":
        """wants: [(topic, partition, offset)] →
        [(topic, partition, error, record_set)] — one request per leader
        broker, long-polled concurrently."""
        if not wants:
            return []
        by_conn: dict[_Conn, list[tuple[str, int, int]]] = {}
        for topic, part, off in wants:
            by_conn.setdefault(self._leader_conn(topic, part), []).append(
                (topic, part, off)
            )
        if len(by_conn) <= 1:
            conn, conn_wants = next(iter(by_conn.items()))
            out = await self._fetch_on(conn, conn_wants, max_wait_ms, max_bytes)
        else:
            chunks = await asyncio.gather(*(
                self._fetch_on(conn, conn_wants, max_wait_ms, max_bytes)
                for conn, conn_wants in by_conn.items()
            ), return_exceptions=True)
            out = []
            first_error: BaseException | None = None
            for chunk in chunks:
                if isinstance(chunk, BaseException):
                    first_error = first_error or chunk
                else:
                    out.extend(chunk)
            if first_error is not None:
                # a dead leader poisons only its chunk; re-learn topology
                # and surface the failure (the consume loop retries)
                await self._refresh_leaders(
                    sorted({t for t, *_x in wants})
                )
                if not out:
                    raise first_error
        stale = [
            (topic, part) for topic, part, err, _b in out
            if err == ERR_NOT_LEADER
        ]
        if stale:
            for tp in stale:
                self._leaders.pop(tp, None)
            await self._refresh_leaders(sorted({t for t, _p in stale}))
        return out

    async def list_offsets(
        self, wants: "list[tuple[str, int]]", *, earliest: bool = False
    ) -> dict:
        by_conn: dict[_Conn, list[tuple[str, int]]] = {}
        for topic, part in wants:
            by_conn.setdefault(self._leader_conn(topic, part), []).append(
                (topic, part)
            )

        async def one(conn: _Conn, conn_wants: "list[tuple[str, int]]") -> dict:
            w = _W()
            w.i32(-1)
            by_topic: dict[str, list[int]] = {}
            for topic, part in conn_wants:
                by_topic.setdefault(topic, []).append(part)
            w.i32(len(by_topic))
            for topic, parts in by_topic.items():
                w.string(topic)
                w.i32(len(parts))
                for part in parts:
                    w.i32(part)
                    w.i64(-2 if earliest else -1)
            r = await conn.request(2, 1, w.done())
            found: dict = {}
            for _ in range(r.i32()):
                topic = r.string()
                for _ in range(r.i32()):
                    part = r.i32()
                    err = r.i16()
                    r.i64()  # timestamp
                    off = r.i64()
                    if not err:
                        found[(topic, part)] = off
            return found

        out: dict = {}
        # concurrent like fetch(): barrier/position-resolve sits on the
        # worker-startup hot path — pay max(RTT), not sum(RTT)
        for found in await asyncio.gather(
            *(one(conn, ws) for conn, ws in by_conn.items())
        ):
            out.update(found)
        return out

    async def find_coordinator(self, group: str) -> tuple[str, int]:
        w = _W()
        w.string(group)
        r = await self.conn.request(10, 0, w.done())
        err = r.i16()
        if err:
            raise KafkaWireError("find_coordinator", err)
        r.i32()  # node
        host, port = r.string(), r.i32()
        self._coordinator = (host, port)
        return host, port

    async def ensure_coordinator(self, group: str) -> None:
        """Resolve + cache the group coordinator so group APIs route to
        it (real clusters host a group on ONE broker; kafkad reports
        itself)."""
        if self._coordinator is None:
            await self.find_coordinator(group)

    def forget_coordinator(self) -> None:
        self._coordinator = None

    async def join_group(
        self, group: str, member_id: str, topics: list[str],
        *, session_timeout_ms: int = 10000, rebalance_timeout_ms: int = 10000,
    ) -> dict:
        meta = _W()
        meta.i16(0)  # consumer-protocol version
        meta.i32(len(topics))
        for t in topics:
            meta.string(t)
        meta.bytes_(b"")  # userdata
        w = _W()
        w.string(group)
        w.i32(session_timeout_ms)
        w.i32(rebalance_timeout_ms)
        w.string(member_id)
        w.string("consumer")
        w.i32(1)
        w.string("range")
        w.bytes_(meta.done())
        r = await self._coord_conn().request(11, 2, w.done())
        r.i32()  # throttle
        err = r.i16()
        if err:
            raise KafkaWireError("join_group", err)
        generation = r.i32()
        protocol = r.string()
        leader = r.string()
        me = r.string()
        members = {}
        for _ in range(r.i32()):
            mid = r.string()
            blob = r.bytes_() or b""
            mr = _R(blob)
            mr.i16()
            mtopics = [mr.string() for _ in range(mr.i32())]
            members[mid] = mtopics
        return {
            "generation": generation, "protocol": protocol,
            "leader": leader, "member_id": me, "members": members,
        }

    async def sync_group(
        self, group: str, generation: int, member_id: str,
        assignments: "dict[str, dict[str, list[int]]] | None" = None,
    ) -> dict[str, list[int]]:
        w = _W()
        w.string(group)
        w.i32(generation)
        w.string(member_id)
        if assignments:
            w.i32(len(assignments))
            for mid, parts_by_topic in assignments.items():
                w.string(mid)
                blob = _W()
                blob.i16(0)
                blob.i32(len(parts_by_topic))
                for topic, parts in parts_by_topic.items():
                    blob.string(topic)
                    blob.i32(len(parts))
                    for p in parts:
                        blob.i32(p)
                blob.bytes_(b"")  # userdata
                w.bytes_(blob.done())
        else:
            w.i32(0)
        r = await self._coord_conn().request(14, 1, w.done())
        r.i32()  # throttle
        err = r.i16()
        if err:
            raise KafkaWireError("sync_group", err)
        blob = r.bytes_() or b""
        if not blob:
            return {}
        ar = _R(blob)
        ar.i16()
        out: dict[str, list[int]] = {}
        for _ in range(ar.i32()):
            topic = ar.string()
            out[topic] = [ar.i32() for _ in range(ar.i32())]
        return out

    async def heartbeat(self, group: str, generation: int, member_id: str) -> int:
        w = _W()
        w.string(group)
        w.i32(generation)
        w.string(member_id)
        r = await self._coord_conn().request(12, 1, w.done())
        r.i32()  # throttle
        return r.i16()

    async def leave_group(self, group: str, member_id: str) -> None:
        w = _W()
        w.string(group)
        w.string(member_id)
        r = await self._coord_conn().request(13, 1, w.done())
        r.i32()
        r.i16()

    async def offset_commit(
        self, group: str, generation: int, member_id: str,
        offsets: "dict[tuple[str, int], int]",
    ) -> None:
        w = _W()
        w.string(group)
        w.i32(generation)
        w.string(member_id)
        w.i64(-1)  # retention
        by_topic: dict[str, list[tuple[int, int]]] = {}
        for (topic, part), off in offsets.items():
            by_topic.setdefault(topic, []).append((part, off))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic)
            w.i32(len(parts))
            for part, off in parts:
                w.i32(part)
                w.i64(off)
                w.string(None)  # metadata
        r = await self._coord_conn().request(8, 2, w.done())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                if err:
                    # a silently-failed commit (rebalance in flight against
                    # a real broker) would rewind the group on restart
                    raise KafkaWireError("offset_commit", err)

    async def offset_fetch(
        self, group: str, wants: "list[tuple[str, int]]"
    ) -> "dict[tuple[str, int], int]":
        w = _W()
        w.string(group)
        by_topic: dict[str, list[int]] = {}
        for topic, part in wants:
            by_topic.setdefault(topic, []).append(part)
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic)
            w.i32(len(parts))
            for part in parts:
                w.i32(part)
        r = await self._coord_conn().request(9, 1, w.done())
        out = {}
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                part = r.i32()
                off = r.i64()
                r.string()  # metadata
                r.i16()
                if off >= 0:
                    out[(topic, part)] = off
        return out


# ------------------------------------------------------------- consumers
def range_assign(
    members: "dict[str, list[str]]", partitions: "dict[str, list[int]]"
) -> "dict[str, dict[str, list[int]]]":
    """The standard range assignor, computed CLIENT-side by the group
    leader (Kafka's embedded consumer protocol)."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    for topic, parts in sorted(partitions.items()):
        subscribed = sorted(m for m, ts in members.items() if topic in ts)
        if not subscribed:
            continue
        per = len(parts) // len(subscribed)
        extra = len(parts) % len(subscribed)
        idx = 0
        for i, member in enumerate(subscribed):
            take = per + (1 if i < extra else 0)
            if take:
                out[member][topic] = parts[idx:idx + take]
            idx += take
    return out


class _WireConsumer:
    """One subscription's consume loop: group-coordinated or groupless."""

    def __init__(
        self,
        host: str,
        port: int,
        topics: list[str],
        group_id: str | None,
        from_latest: bool,
        deliver: Callable[[Record], Awaitable[None]],
        *,
        session_timeout_ms: int = 10000,
        commit_interval_s: float = 1.0,
        security: WireSecurity = PLAINTEXT,
        max_message_bytes: int = DEFAULT_MAX_MESSAGE_BYTES,
        client_id: str = "calfkit-consumer",
    ):
        self._security = security
        # the coordinated-knob law (ConnectionProfile): the consumer fetch
        # budget must FLOOR at the producer message budget, or the biggest
        # legal message could never be fetched (brokers do return at least
        # one oversized message per fetch — KIP-74 — but honoring the
        # budget keeps multi-record batches flowing too)
        self._fetch_max_bytes = fetch_floor(max_message_bytes)
        self._client = KafkaWireClient(
            host, port, client_id=client_id, security=security
        )
        self._topics = topics
        self._group = group_id
        self._from_latest = from_latest
        self._deliver = deliver
        self._client_id = client_id
        self._session_ms = session_timeout_ms
        self._commit_interval = commit_interval_s
        self._positions: dict[tuple[str, int], int] = {}
        self._member_id = ""
        self._generation = -1
        self._group_had_no_partitions = False
        self._poison_logged: dict[tuple[str, int], float] = {}
        self._rejoin = asyncio.Event()
        self._stopped = False
        self._task: asyncio.Task[None] | None = None
        self._hb_task: asyncio.Task[None] | None = None
        self.started = asyncio.Event()  # first assignment ready

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name=f"kafka-wire-{self._group or 'tap'}"
        )

    async def stop(self) -> None:
        self._stopped = True
        if self._hb_task:
            self._hb_task.cancel()
        if self._task:
            self._task.cancel()
            for task in (self._hb_task, self._task):
                if task:
                    try:
                        await task
                    except (asyncio.CancelledError, Exception):  # noqa: BLE001
                        pass
        try:
            if self._group and self._positions:
                await self._client.offset_commit(
                    self._group, self._generation, self._member_id,
                    self._positions,
                )
            if self._group and self._member_id:
                await self._client.leave_group(self._group, self._member_id)
        except Exception:  # noqa: BLE001
            pass
        await self._client.close()

    async def _run(self) -> None:
        """Consume forever; transport errors (broker restart, idle reap)
        back off and retry instead of silently killing the subscription —
        the Subscription object stays live, so the loop must too."""
        while not self._stopped:
            try:
                if self._group is None:
                    await self._run_tap()
                else:
                    await self._run_group_cycle()
            except asyncio.CancelledError:
                raise
            except KafkaWireError as exc:
                if exc.code in (
                    ERR_REBALANCE_IN_PROGRESS,
                    ERR_ILLEGAL_GENERATION,
                    ERR_UNKNOWN_MEMBER,
                ):
                    continue  # rejoin immediately
                if exc.code == ERR_NOT_COORDINATOR:
                    # coordinator moved (real clusters): re-find + rejoin
                    self._client.forget_coordinator()
                    continue
                logger.warning(
                    "kafka-wire consumer error on %s: %s; retrying",
                    self._topics, exc,
                )
                await asyncio.sleep(1.0)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "kafka-wire consumer error on %s; retrying", self._topics
                )
                await asyncio.sleep(1.0)

    async def _assignment_all_partitions(self) -> dict[tuple[str, int], None]:
        meta = await self._client.metadata(self._topics)
        return {
            (topic, part): None
            for topic, info in meta["topics"].items()
            for part in info["partitions"]
        }

    async def _resolve_tap_positions(self) -> None:
        assigned = list(await self._assignment_all_partitions())
        if not assigned:
            return
        offsets = await self._client.list_offsets(
            assigned, earliest=not self._from_latest
        )
        self._positions = {tp: offsets.get(tp, 0) for tp in assigned}

    async def _run_tap(self) -> None:
        if not self._positions:  # first attach; a retry keeps its positions
            await self._resolve_tap_positions()
        self.started.set()
        while not self._stopped:
            if not self._positions:
                # zero partitions at attach (auto-create off, or the topic
                # is created later): keep re-resolving instead of leaving
                # the subscription permanently dead while looking started
                await asyncio.sleep(1.0)
                await self._resolve_tap_positions()
                continue
            await self._fetch_once()

    async def _run_group_cycle(self) -> None:
        await self._client.ensure_coordinator(self._group)
        join = await self._client.join_group(
            self._group, self._member_id, self._topics,
            session_timeout_ms=self._session_ms,
            rebalance_timeout_ms=self._session_ms,
        )
        self._member_id = join["member_id"]
        self._generation = join["generation"]
        if join["member_id"] == join["leader"]:
            meta = await self._client.metadata(
                sorted({t for ts in join["members"].values() for t in ts})
            )
            partitions = {
                name: info["partitions"]
                for name, info in meta["topics"].items()
            }
            assignment = await self._client.sync_group(
                self._group, self._generation, self._member_id,
                range_assign(join["members"], partitions),
            )
        else:
            assignment = await self._client.sync_group(
                self._group, self._generation, self._member_id
            )
        assigned = [
            (topic, part)
            for topic, parts in assignment.items()
            for part in parts
        ]
        # distinguish "topic has no partitions anywhere" (watch for them to
        # appear) from "peers hold them all" (stay idle, keep membership)
        self._group_had_no_partitions = (
            not assigned and not await self._assignment_all_partitions()
        )
        committed = await self._client.offset_fetch(self._group, assigned)
        missing = [tp for tp in assigned if tp not in committed]
        if missing:
            fresh = await self._client.list_offsets(
                missing, earliest=not self._from_latest
            )
            committed.update({tp: fresh.get(tp, 0) for tp in missing})
        self._positions = committed
        self._rejoin.clear()
        self.started.set()
        # heartbeat rides its own task; REBALANCE_IN_PROGRESS flags rejoin
        self._hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop(), name=f"kafka-wire-hb-{self._group}"
        )
        last_commit = time.monotonic()
        last_empty_check = time.monotonic()
        try:
            while not self._stopped and not self._rejoin.is_set():
                if not self._positions:
                    # empty assignment: either the topic has no partitions
                    # yet (created later / auto-create off) or other members
                    # hold them all.  Re-check metadata on a slow cadence and
                    # force a rebalance ONLY when partitions newly appear —
                    # rejoining because peers hold the partitions would
                    # thrash the whole group.
                    await asyncio.sleep(0.5)
                    if (
                        self._group_had_no_partitions
                        and time.monotonic() - last_empty_check >= 5.0
                    ):
                        last_empty_check = time.monotonic()
                        if await self._assignment_all_partitions():
                            break  # partitions appeared → rejoin cycle
                    continue
                await self._fetch_once()
                if time.monotonic() - last_commit >= self._commit_interval:
                    # ACK-first auto-commit: cadence independent of handler
                    # completion (transport contract)
                    await self._client.offset_commit(
                        self._group, self._generation, self._member_id,
                        self._positions,
                    )
                    last_commit = time.monotonic()
        finally:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._hb_task = None
            # commit-on-revoke: the NEXT generation's owner starts where
            # this one stopped
            if self._positions:
                try:
                    await self._client.offset_commit(
                        self._group, self._generation, self._member_id,
                        self._positions,
                    )
                except Exception:  # noqa: BLE001
                    pass

    async def _heartbeat_loop(self) -> None:
        interval = max(self._session_ms / 3000.0, 0.5)
        hb = KafkaWireClient(
            self._client.conn.host, self._client.conn.port,
            client_id=f"{self._client_id}-hb", security=self._security,
        )
        failures = 0
        try:
            while not self._stopped:
                await asyncio.sleep(interval)
                try:
                    await hb.ensure_coordinator(self._group)
                    code = await hb.heartbeat(
                        self._group, self._generation, self._member_id
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001
                    # transport error (broker restart, idle reap): retry
                    # with backoff; a persistent failure must force a rejoin
                    # instead of leaving the consumer fetching heartbeat-less
                    # until the session expires server-side
                    failures += 1
                    if failures >= 3:
                        logger.warning(
                            "kafka-wire heartbeat to group %s failing; "
                            "forcing rejoin", self._group,
                        )
                        self._rejoin.set()
                        return
                    await asyncio.sleep(min(0.25 * 2 ** failures, 2.0))
                    continue
                failures = 0
                if code == ERR_NOT_COORDINATOR:
                    hb.forget_coordinator()
                    continue
                if code in (
                    ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION,
                    ERR_UNKNOWN_MEMBER,
                ):
                    self._rejoin.set()
                    return
        finally:
            await hb.close()

    def _poison_warn(self, topic: str, part: int, exc: Exception) -> None:
        """Log a poison batch loudly but at most once per ~30s per
        partition — the fetch loop retries it forever."""
        now = time.monotonic()
        last = self._poison_logged.get((topic, part), 0.0)
        if now - last >= 30.0:
            self._poison_logged[(topic, part)] = now
            logger.error(
                "kafka-wire: undecodable RecordBatch on %s[%d] at offset "
                "%s — partition stalled (will retry): %s",
                topic, part, self._positions.get((topic, part)), exc,
            )

    async def _fetch_once(self) -> None:
        if not self._positions:
            await asyncio.sleep(0.2)
            return
        wants = [
            (topic, part, off)
            for (topic, part), off in self._positions.items()
        ]
        results = await self._client.fetch(
            wants, max_wait_ms=300, max_bytes=self._fetch_max_bytes
        )
        for topic, part, err, blob in results:
            if err == ERR_OFFSET_OUT_OF_RANGE:
                # retention moved log-start past our position, or the
                # broker restarted with a shorter log (kafkad is
                # memory-only): re-resolve LOUDLY instead of silently
                # stalling the partition forever
                fresh = await self._client.list_offsets(
                    [(topic, part)], earliest=not self._from_latest
                )
                new_off = fresh.get((topic, part), 0)
                logger.warning(
                    "kafka-wire: %s[%d] position %s out of range; broker "
                    "log truncated or restarted — resetting to %s",
                    topic, part, self._positions.get((topic, part)), new_off,
                )
                self._positions[(topic, part)] = new_off
                continue
            if err:
                logger.warning(
                    "kafka-wire fetch error %d on %s[%d]; retrying",
                    err, topic, part,
                )
                await asyncio.sleep(0.2)
                continue
            if not blob:
                continue
            try:
                batches = await _decode_off_loop(blob)
            except RecordBatchError as exc:
                # poison batch (crc mismatch / unsupported codec): stall
                # THIS partition loudly without advancing past data, and
                # without propagating — propagation would exit the group
                # cycle and rebalance-thrash every member at ~1 Hz
                self._poison_warn(topic, part, exc)
                await asyncio.sleep(1.0)
                continue
            for off, ts_ms, key, value, headers in batches:
                position = self._positions.get((topic, part), 0)
                if off < position:
                    continue  # batch includes pre-position records
                record = Record(
                    topic=topic,
                    key=key,
                    value=value or b"",
                    # the protocol.header_map contract: undecodable header
                    # values are DROPPED, not replacement-char'd — a
                    # garbage x-mesh-trace must degrade to untraced, not
                    # mint a bogus trace id shared by every corrupt record
                    headers=protocol_header_map(dict(headers)),
                    offset=off,
                    timestamp=ts_ms / 1000.0,
                )
                self._positions[(topic, part)] = off + 1
                try:
                    await self._deliver(record)
                except Exception:  # noqa: BLE001
                    logger.exception("kafka-wire delivery failed on %s", topic)


# ------------------------------------------------------------- transport
class KafkaWireMesh(MeshTransport):
    """MeshTransport over the native wire client — same contract mapping
    the reference's aiokafka transport defines, zero third-party
    dependencies.  Points at any
    Kafka-compatible broker (``native/bin/kafkad`` in-image; real
    Kafka/Redpanda in production).

    Security rides the same :class:`ConnectionProfile` as the aiokafka
    adapter: TLS (``security_protocol="SSL"``), SASL PLAIN and
    SCRAM-SHA-256/512 (``SASL_PLAINTEXT`` / ``SASL_SSL``) are spoken
    natively; anything else fails loudly at construction.

    Multi-node clusters: produce/fetch/list_offsets route to each
    partition's leader and group APIs to the group coordinator, both
    learned from metadata with refresh-and-retry on NOT_LEADER /
    NOT_COORDINATOR — one client, any Kafka-compatible topology."""

    def __init__(
        self,
        bootstrap_servers: str | None = None,
        *,
        profile: "ConnectionProfile | None" = None,
        security: "Mapping[str, Any] | None" = None,
        max_message_bytes: int | None = None,
        default_partitions: int = 8,
    ):
        from calfkit_tpu.mesh.connection import ConnectionProfile

        if profile is None:
            if not bootstrap_servers:
                raise ValueError("bootstrap_servers (or profile=) required")
            profile = ConnectionProfile(
                bootstrap_servers=bootstrap_servers,
                max_message_bytes=(
                    max_message_bytes if max_message_bytes is not None
                    else DEFAULT_MAX_MESSAGE_BYTES
                ),
                security=dict(security or {}),
            )
        else:
            # profile= owns every connection knob (same conflict rule as
            # the reference adapter): silently ignoring a kwarg would hide a config bug
            conflicts = [
                name for name, value in (
                    ("bootstrap_servers", bootstrap_servers),
                    ("security", security),
                    ("max_message_bytes", max_message_bytes),
                ) if value is not None
            ]
            if conflicts:
                raise ValueError(
                    f"profile= conflicts with {conflicts}: set these on the "
                    "ConnectionProfile instead"
                )
        self._profile = profile
        if profile.enable_idempotence:
            # retry-once produce (NOT_LEADER / dead-leader EOF) cannot
            # guarantee exactly-once sequencing; honoring the flag
            # silently as at-least-once would be a lie
            raise ValueError(
                "enable_idempotence=True is not supported by the native "
                "wire client (no idempotent-producer sequencing); unset it"
            )
        # parse EARLY so unsupported security fails at construction, not
        # first I/O
        self._security = WireSecurity.from_security_kwargs(profile.security)
        # "host:port[,host:port...]" — the FIRST entry seeds the bootstrap
        # connection; partition leaders and the group coordinator are then
        # learned from metadata and dialed directly.  A bare host defaults
        # to 9092.
        first = profile.bootstrap_servers.split(",")[0].strip()
        host, _, port = first.rpartition(":")
        if not host:
            host, port = first, ""
        self._host = host or "127.0.0.1"
        self._port = int(port) if port else 9092
        self._max_bytes = profile.max_message_bytes
        self._default_partitions = default_partitions
        self._producer: KafkaWireClient | None = None
        self._partition_counts: dict[str, int] = {}
        self._rr_counter = [0]
        self._consumers: list[_WireConsumer] = []
        self._dispatchers: list[KeyOrderedDispatcher] = []
        self._readers: list[_WireTableReader] = []
        self._started = False

    @property
    def max_message_bytes(self) -> int:
        return self._max_bytes

    @property
    def profile(self):
        return self._profile

    async def start(self) -> None:
        if self._started:
            return
        self._producer = KafkaWireClient(
            self._host, self._port,
            client_id=f"{self._profile.client_id}-producer",
            security=self._security,
        )
        await self._producer.conn.connect()
        # atomicity-ok: callers serialize start() (Client._ensure_started's
        # single-flight lock / worker boot); double start only re-dials the
        # producer conn
        self._started = True

    async def stop(self) -> None:
        self._started = False
        # swap-then-iterate (meshlint await-atomicity): detach before
        # the first await so a racing subscribe can't be silently dropped
        readers, self._readers = self._readers, []
        for reader in readers:
            try:
                await reader.stop()
            except Exception:  # noqa: BLE001
                logger.exception("table reader stop failed")
        consumers, self._consumers = self._consumers, []
        for consumer in consumers:
            try:
                await consumer.stop()
            except Exception:  # noqa: BLE001
                logger.exception("consumer stop failed")
        dispatchers, self._dispatchers = self._dispatchers, []
        for dispatcher in dispatchers:
            try:
                await dispatcher.stop()
            except Exception:  # noqa: BLE001
                logger.exception("dispatcher drain failed")
        if self._producer is not None:
            await self._producer.close()
            self._producer = None

    # ---------------------------------------------------------------- admin
    async def ensure_topics(
        self, names: list[str], *, compacted: bool = False
    ) -> None:
        if self._producer is None:
            raise RuntimeError("mesh not started")
        await self._producer.create_topics(
            names, self._default_partitions, compacted=compacted
        )

    async def _partitions_of(self, topic: str) -> int:
        count = self._partition_counts.get(topic)
        if count:
            return count
        meta = await self._producer.metadata([topic])
        count = max(1, len(meta["topics"].get(topic, {}).get("partitions", [])))
        self._partition_counts[topic] = count
        return count

    # -------------------------------------------------------------- produce
    async def publish(
        self,
        topic: str,
        value: bytes | None,
        *,
        key: bytes | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        if value is not None and len(value) > self._max_bytes:
            raise ValueError(
                f"message of {len(value)} bytes exceeds "
                f"max_message_bytes={self._max_bytes}"
            )
        header_bytes = sum(
            len(hk.encode()) + len(hv.encode())
            for hk, hv in (headers or {}).items()
        )
        if len(key or b"") + header_bytes > KEY_HEADERS_CAP:
            raise ValueError(
                f"key+headers of {len(key or b'') + header_bytes} bytes "
                f"exceed the {KEY_HEADERS_CAP}-byte budget"
            )
        if self._producer is None:
            raise RuntimeError("mesh not started")
        # no mesh-wide lock: partition choice is synchronous, the metadata
        # lookup caches after the first call per topic, and _Conn already
        # serializes the wire — holding a lock across the produce RTT
        # would cap the whole transport at one in-flight message
        n = await self._partitions_of(topic)
        part = partition_for(key, n, self._rr_counter)
        records = [(
            key, value,
            [(hk, hv.encode("utf-8")) for hk, hv in (headers or {}).items()],
        )]
        now_ms = int(time.time() * 1000)
        if value is not None and len(value) > 65536:
            # the pure-Python crc32c over a multi-MiB payload would stall
            # the event loop (heartbeats, fetch long-polls); encode big
            # batches on a worker thread
            batch = await asyncio.to_thread(
                encode_record_batch, records, now_ms
            )
        else:
            batch = encode_record_batch(records, now_ms)
        await self._producer.produce(topic, part, batch)

    # -------------------------------------------------------------- consume
    async def subscribe(
        self,
        topics: list[str],
        handler: RecordHandler,
        *,
        group_id: str | None,
        from_latest: bool | None = None,
        max_workers: int = 8,
        ordered: bool = True,
    ) -> Subscription:
        if from_latest is None:
            from_latest = group_id is None
        deliver = handler
        dispatcher: KeyOrderedDispatcher | None = None
        if ordered:
            dispatcher = KeyOrderedDispatcher(
                handler, max_workers=max_workers,
                name=f"kafka-wire-{group_id or 'tap'}",
            )
            dispatcher.start()
            self._dispatchers.append(dispatcher)

            async def deliver(record: Record) -> None:  # type: ignore[misc]
                await dispatcher.submit(record)

        if self._producer is not None:
            # topics must exist before a groupless tap resolves "latest"
            await self._producer.metadata(topics)
        consumer = _WireConsumer(
            self._host, self._port, topics, group_id, from_latest, deliver,
            security=self._security, max_message_bytes=self._max_bytes,
            client_id=f"{self._profile.client_id}-consumer",
        )
        consumer.start()
        self._consumers.append(consumer)
        try:
            await asyncio.wait_for(consumer.started.wait(), timeout=30)
        except BaseException:
            # a failed subscribe must not leak a live consumer task (still
            # rejoining, still a group member) + a running dispatcher
            self._consumers.remove(consumer)
            await consumer.stop()
            if dispatcher is not None:
                await dispatcher.stop()
                self._dispatchers.remove(dispatcher)
            raise

        async def stop_fn() -> None:
            await consumer.stop()
            if consumer in self._consumers:
                self._consumers.remove(consumer)
            if dispatcher is not None:
                await dispatcher.stop()
                if dispatcher in self._dispatchers:
                    self._dispatchers.remove(dispatcher)

        return CallbackSubscription(stop_fn)

    # --------------------------------------------------------------- tables
    def table_reader(self, topic: str) -> TableReader:
        reader = _WireTableReader(self, topic)
        self._readers.append(reader)
        return reader

    def table_writer(self, topic: str) -> TableWriter:
        return _WireTableWriter(self, topic)


class _WireTableReader(TableReader):
    """Compacted-topic view over the wire client: consume-all into a dict
    with catch-up (end-offsets gate) and barrier semantics."""

    def __init__(self, mesh: KafkaWireMesh, topic: str):
        self._mesh = mesh
        self._topic = topic
        self._view: dict[str, bytes] = {}
        self._client: KafkaWireClient | None = None
        self._fetch_positions: dict[int, int] = {}
        self._fetch_max_bytes = fetch_floor(mesh.max_message_bytes)
        self._task: asyncio.Task[None] | None = None
        self._stopped = False
        self._advanced = asyncio.Event()
        self._caught_up = False
        # view-mutation counter (TableReader.version): bumps per applied
        # record and at every rebuild swap — the no-change fast path for
        # per-call readers (the fleet registry)
        self._version = 0

    async def start(self, *, timeout: float = 30.0) -> None:
        self._client = KafkaWireClient(
            self._mesh._host, self._mesh._port,
            client_id=f"{self._mesh._profile.client_id}-table",
            security=self._mesh._security,
        )
        # own fetch loop (not _WireConsumer): the barrier needs each
        # record's PARTITION, which the transport Record doesn't carry
        meta = await self._client.metadata([self._topic])
        parts = meta["topics"].get(self._topic, {}).get("partitions", [])
        self._fetch_positions = {p: 0 for p in parts}
        self._task = asyncio.get_running_loop().create_task(
            self._pump(), name=f"kafka-wire-table-{self._topic}"
        )
        try:
            await self.barrier(timeout=timeout)
        except BaseException:
            await self.stop()
            raise
        self._caught_up = True

    async def _pump(self) -> None:
        while not self._stopped:
            try:
                await self._pump_once(self._view, self._fetch_positions)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                # transport failure: the broker may have restarted with a
                # fresh (shorter) log whose high watermark can even equal
                # our stale position — undetectable at the fetch level.
                # Rebuild into a SHADOW view and swap atomically when
                # caught up: the live view keeps serving reads meanwhile
                # (read-your-writes across transient drops), and ghosts
                # of a restarted broker's lost world vanish at the swap.
                logger.warning(
                    "kafka-wire table %s: transport error; rebuilding the "
                    "view from the log start", self._topic, exc_info=True,
                )
                await asyncio.sleep(0.5)
                await self._rebuild()
                continue
            self._advanced.set()

    async def _rebuild(self) -> None:
        try:
            meta = await self._client.metadata([self._topic])
            parts = meta["topics"].get(self._topic, {}).get("partitions", [])
            ends = await self._client.list_offsets(
                [(self._topic, p) for p in parts]
            )
            shadow: dict[str, bytes] = {}
            positions = {p: 0 for p in parts}
            while not self._stopped and any(
                positions[p] < ends.get((self._topic, p), 0) for p in parts
            ):
                await self._pump_once(shadow, positions)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — broker (still) down; the outer
            return  # loop fails its next fetch and retries the rebuild
        self._view = shadow
        self._fetch_positions = positions
        self._version += 1  # the whole view may have changed: one bump
        self._advanced.set()

    async def _pump_once(
        self, view: "dict[str, bytes]", positions: "dict[int, int]"
    ) -> None:
        """One fetch round applied to (view, positions); per-partition
        errors handled here, transport errors propagate to the caller."""
        wants = [
            (self._topic, part, off) for part, off in positions.items()
        ]
        if not wants:
            await asyncio.sleep(0.2)
            return
        results = await self._client.fetch(
            wants, max_wait_ms=300, max_bytes=self._fetch_max_bytes
        )
        for _topic, part, err, blob in results:
            if err == ERR_OFFSET_OUT_OF_RANGE:
                fresh = await self._client.list_offsets(
                    [(self._topic, part)], earliest=True
                )
                positions[part] = fresh.get((self._topic, part), 0)
                continue
            if err or not blob:
                continue
            try:
                batches = await _decode_off_loop(blob)
            except RecordBatchError:
                # poison batch: keep the pump task ALIVE (a dead pump
                # would turn start() timeouts opaque and freeze the
                # view silently after catch-up) and keep it loud
                logger.exception(
                    "kafka-wire table %s[%d]: undecodable RecordBatch; "
                    "view stalled at offset %s",
                    self._topic, part, positions.get(part),
                )
                await asyncio.sleep(1.0)
                continue
            for off, _ts, key, value, _headers in batches:
                if off < positions.get(part, 0):
                    continue
                text_key = (key or b"").decode("utf-8", errors="replace")
                if text_key:
                    if value:
                        view[text_key] = value
                    else:
                        view.pop(text_key, None)
                    if view is self._view:
                        # shadow rebuilds bump once at the swap instead
                        self._version += 1
                positions[part] = off + 1

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        if self._client is not None:
            await self._client.close()
            self._client = None
        if self in self._mesh._readers:
            self._mesh._readers.remove(self)

    async def barrier(self, *, timeout: float = 30.0) -> None:
        if self._client is None:
            raise RuntimeError("table reader not started")
        wants = [(self._topic, part) for part in self._fetch_positions]
        if not wants:
            return
        ends = await self._client.list_offsets(wants)

        def behind() -> bool:
            return any(
                self._fetch_positions.get(part, 0) < off
                for (_t, part), off in ends.items()
                if off > 0
            )

        async def gate() -> None:
            while behind():
                self._advanced.clear()
                if not behind():
                    return
                await self._advanced.wait()

        await asyncio.wait_for(gate(), timeout=timeout)

    def get(self, key: str) -> bytes | None:
        return self._view.get(key)

    def items(self) -> dict[str, bytes]:
        return dict(self._view)

    @property
    def is_caught_up(self) -> bool:
        return self._caught_up

    @property
    def version(self) -> "int | None":
        return self._version


class _WireTableWriter(TableWriter):
    def __init__(self, mesh: KafkaWireMesh, topic: str):
        self._mesh = mesh
        self._topic = topic

    async def put(self, key: str, value: bytes) -> None:
        await self._mesh.publish(self._topic, value, key=key.encode("utf-8"))

    async def tombstone(self, key: str) -> None:
        await self._mesh.publish(self._topic, None, key=key.encode("utf-8"))
