"""TcpMesh: MeshTransport over the native ``meshd`` dev broker.

``meshd`` (native/meshd.cpp) is the single-binary broker behind the
multi-process dev mesh — the analog of the reference's bundled Tansu binary
(reference cli/_dev_broker.py).  The protocol is newline-delimited text with
base64 fields; every publish is acked broker-side before the response
returns.  Table reads use a locally-cached fold with an end-offsets barrier
(see ``_TcpTableReader``).

Per-key ordering across processes holds because the broker assigns each
partition to exactly one live group member.
"""

from __future__ import annotations

import asyncio
import base64
import contextlib
import json
import logging
import os
import subprocess
import time
from pathlib import Path
from typing import Awaitable, Callable

from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.transport import (
    CallbackSubscription,
    MeshTransport,
    Record,
    RecordHandler,
    Subscription,
)

logger = logging.getLogger(__name__)

DEFAULT_PORT = 19092
# keys + rendered headers get their own budget (they ride every protocol
# line alongside the value; the stream limits are derived from BOTH)
KEY_HEADERS_CAP = 1024 * 1024


def _enc(data: bytes | None) -> str:
    if not data:
        return "-"
    return base64.b64encode(data).decode()


def _dec(field: str) -> bytes:
    if field == "-":
        return b""
    return base64.b64decode(field)


class _Conn:
    """One broker connection; the protocol is strict request→response."""

    def __init__(self, host: str, port: int,
                 limit: int = 32 * 1024 * 1024):
        self._host, self._port = host, port
        self._limit = limit
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()

    async def open(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self._host, self._port,
            # asyncio's default 64 KiB stream limit would break the
            # newline-delimited protocol on any record past ~48 KiB
            # (base64 inflates 4/3): budget for the biggest legal message
            limit=self._limit,
        )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            with contextlib.suppress(Exception):
                await self._writer.wait_closed()
            self._writer = None

    async def request(self, line: str) -> str:
        async with self._lock:
            assert self._writer is not None and self._reader is not None
            self._writer.write(line.encode() + b"\n")
            await self._writer.drain()
            response = await self._reader.readline()
            if not response:
                raise ConnectionError("meshd closed the connection")
            return response.decode().rstrip("\n")

    async def request_multi(self, line: str) -> list[str]:
        """For N-prefixed responses (POLL/TABLE)."""
        async with self._lock:
            assert self._writer is not None and self._reader is not None
            self._writer.write(line.encode() + b"\n")
            await self._writer.drain()
            head = (await self._reader.readline()).decode().rstrip("\n")
            if not head.startswith("N "):
                raise ConnectionError(f"unexpected meshd response: {head!r}")
            count = int(head.split()[1])
            return [
                (await self._reader.readline()).decode().rstrip("\n")
                for _ in range(count)
            ]


class TcpMesh(MeshTransport):
    def __init__(
        self,
        address: str = f"127.0.0.1:{DEFAULT_PORT}",
        *,
        max_message_bytes: int = 5 * 1024 * 1024,
        poll_timeout_ms: int = 500,
    ):
        host, _, port = address.partition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port or DEFAULT_PORT)
        self._max_bytes = max_message_bytes
        # stream budget for one protocol line: base64 (4/3 inflation) of
        # the biggest legal value PLUS the key/headers cap + frame
        # overhead — derived, so a bigger configured budget can't pass
        # the publish guard then die on read
        self._line_limit = max(
            32 * 1024 * 1024,
            (max_message_bytes + KEY_HEADERS_CAP) * 4 // 3 + 64 * 1024,
        )
        self._poll_timeout_ms = poll_timeout_ms
        self._control: _Conn | None = None
        self._pumps: list[asyncio.Task[None]] = []
        self._dispatchers: list[KeyOrderedDispatcher] = []
        self._sub_conns: list[_Conn] = []  # per-subscription connections
        self._readers: list["_TcpTableReader"] = []
        self._started = False

    @property
    def max_message_bytes(self) -> int:
        return self._max_bytes

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._started:
            return
        self._control = _Conn(self._host, self._port, limit=self._line_limit)
        await self._control.open()
        if await self._control.request("PING") != "PONG":
            raise ConnectionError("meshd did not answer PING")
        # atomicity-ok: callers serialize start() (Client._ensure_started's
        # single-flight lock / worker boot); a double start re-opens the
        # control conn, it never corrupts state
        self._started = True

    async def stop(self) -> None:
        self._started = False
        # table readers own their conn + pump; stopping the mesh must not
        # leak them (same discipline as KafkaWireMesh).  Swap-then-iterate:
        # the lists are detached BEFORE the first await, so a subscribe()
        # racing stop() can never append into a snapshot we already walked
        # (the meshlint await-atomicity rule pins this shape)
        readers, self._readers = self._readers, []
        for reader in readers:
            with contextlib.suppress(Exception):
                await reader.stop()
        pumps, self._pumps = self._pumps, []
        for pump in pumps:
            pump.cancel()
        for pump in pumps:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await pump
        dispatchers, self._dispatchers = self._dispatchers, []
        for d in dispatchers:
            with contextlib.suppress(Exception):
                await d.stop()
        # close subscription connections so the broker rebalances away from
        # this (now dead) member immediately
        sub_conns, self._sub_conns = self._sub_conns, []
        for conn in sub_conns:
            with contextlib.suppress(Exception):
                await conn.close()
        if self._control is not None:
            await self._control.close()
            self._control = None

    # ---------------------------------------------------------------- admin
    async def ensure_topics(self, names: list[str], *, compacted: bool = False) -> None:
        if not names:
            return
        assert self._control is not None
        await self._control.request("ENSURE " + ",".join(names))

    # -------------------------------------------------------------- produce
    async def publish(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        if len(value) > self._max_bytes:
            raise ValueError(
                f"message of {len(value)} bytes exceeds max_message_bytes={self._max_bytes}"
            )
        if self._control is None:
            raise RuntimeError("mesh not started")
        headers_json = json.dumps(headers or {}).encode()
        if len(key or b"") + len(headers_json) > KEY_HEADERS_CAP:
            raise ValueError(
                f"key+headers of {len(key or b'') + len(headers_json)} bytes "
                f"exceed the {KEY_HEADERS_CAP}-byte budget"
            )
        response = await self._control.request(
            f"PUB {topic} {_enc(key)} {_enc(value)} {_enc(headers_json)}"
        )
        if not response.startswith("OK"):
            raise ConnectionError(f"publish failed: {response!r}")

    # -------------------------------------------------------------- consume
    async def subscribe(
        self,
        topics: list[str],
        handler: RecordHandler,
        *,
        group_id: str | None,
        from_latest: bool | None = None,
        max_workers: int = 8,
        ordered: bool = True,
    ) -> Subscription:
        if not self._started:
            raise RuntimeError("mesh not started")
        if from_latest is None:
            from_latest = group_id is None

        deliver = handler
        dispatcher: KeyOrderedDispatcher | None = None
        if ordered:
            dispatcher = KeyOrderedDispatcher(
                handler, max_workers=max_workers, name=f"tcp-{group_id or 'tap'}"
            )
            dispatcher.start()
            self._dispatchers.append(dispatcher)

            async def deliver(record: Record) -> None:  # type: ignore[misc]
                await dispatcher.submit(record)

        conns: list[_Conn] = []
        tasks: list[asyncio.Task[None]] = []
        stopping = asyncio.Event()
        mode = "latest" if from_latest else "earliest"
        for name in topics:
            conn = _Conn(self._host, self._port, limit=self._line_limit)
            await conn.open()
            response = await conn.request(f"SUB {name} {group_id or '-'} {mode}")
            sub_id = response.split()[1]
            conns.append(conn)
            self._sub_conns.append(conn)
            tasks.append(
                asyncio.get_running_loop().create_task(
                    self._pump(conn, sub_id, name, group_id, mode, deliver,
                               stopping),
                    name=f"tcp-pump-{name}",
                )
            )
        self._pumps.extend(tasks)

        async def stop_fn() -> None:
            # GRACEFUL leave: let each pump finish its in-flight POLL and
            # deliver what the broker already ack-committed to us — a
            # mid-response cancel would turn a clean unsubscribe into
            # record loss (the crash path, which is documented at-most-once)
            stopping.set()
            # the window must cover the in-flight poll AND delivering its
            # whole batch through dispatcher backpressure — a mid-delivery
            # cancel drops broker-committed records; only a genuinely hung
            # handler forfeits that guarantee
            grace = self._poll_timeout_ms / 1000.0 + 30.0
            if tasks:
                done, pending = await asyncio.wait(tasks, timeout=grace)
                for task in pending:
                    task.cancel()
                for task in tasks:  # retrieve exceptions from done pumps too
                    with contextlib.suppress(asyncio.CancelledError, Exception):
                        await task
            for conn in conns:
                await conn.close()  # broker rebalances on disconnect
                if conn in self._sub_conns:
                    self._sub_conns.remove(conn)
            if dispatcher is not None:
                await dispatcher.stop()
                if dispatcher in self._dispatchers:
                    self._dispatchers.remove(dispatcher)

        return CallbackSubscription(stop_fn)

    async def _pump(
        self,
        conn: _Conn,
        sub_id: str,
        topic: str,
        group_id: str | None,
        mode: str,
        deliver: RecordHandler,
        stopping: asyncio.Event,
    ) -> None:
        while not stopping.is_set():
            try:
                lines = await conn.request_multi(
                    f"POLL {sub_id} 64 {self._poll_timeout_ms}"
                )
            except (ConnectionError, OSError):
                if not self._started:
                    return
                # broker restart: reconnect + re-subscribe (dev brokers are
                # memory-only, so a fresh broker means a fresh log)
                logger.warning(
                    "meshd connection lost for %s: reconnecting", topic
                )
                try:
                    await asyncio.sleep(1.0)
                    await conn.close()
                    await conn.open()
                    response = await conn.request(
                        f"SUB {topic} {group_id or '-'} {mode}"
                    )
                    sub_id = response.split()[1]
                except (ConnectionError, OSError):
                    continue  # keep trying while the mesh is running
                continue
            for line in lines:
                _, part, offset, key, value, headers_b64 = line.split(" ")
                try:
                    headers = json.loads(_dec(headers_b64) or b"{}")
                except ValueError:
                    headers = {}
                record = Record(
                    topic=topic,
                    key=_dec(key) or None,
                    value=_dec(value),
                    headers=headers,
                    offset=int(offset),
                )
                try:
                    await deliver(record)
                except Exception:  # noqa: BLE001
                    logger.exception("tcp delivery failed on %s", topic)

    # --------------------------------------------------------------- tables
    def table_reader(self, topic: str) -> TableReader:
        reader = _TcpTableReader(self, topic)
        self._readers.append(reader)
        return reader

    def table_writer(self, topic: str) -> TableWriter:
        return _TcpTableWriter(self, topic)


class _TcpTableReader(TableReader):
    """A locally-cached fold fed by a broadcast tap, with an offset-gate
    barrier (same shape as the Kafka reader): ``barrier()`` captures the
    broker's per-partition end offsets and waits until the local view has
    consumed past them."""

    def __init__(self, mesh: TcpMesh, topic: str):
        self._mesh = mesh
        self._topic = topic
        self._view: dict[str, bytes] = {}
        self._positions = [0] * 16  # consumed count per partition
        self._version = 0  # view-mutation counter (TableReader.version)
        self._advanced = asyncio.Event()
        self._conn: _Conn | None = None
        self._task: asyncio.Task[None] | None = None
        self._started = False

    async def start(self, *, timeout: float = 30.0) -> None:
        await self._mesh.ensure_topics([self._topic])
        self._conn = _Conn(self._mesh._host, self._mesh._port,
                          limit=self._mesh._line_limit)
        await self._conn.open()
        response = await self._conn.request(f"SUB {self._topic} - earliest")
        sub_id = response.split()[1]
        self._task = asyncio.get_running_loop().create_task(
            self._pump(sub_id), name=f"tcp-table-{self._topic}"
        )
        try:
            await asyncio.wait_for(self.barrier(), timeout=timeout)
        except BaseException:
            await self.stop()
            raise
        self._started = True

    async def _pump(self, sub_id: str) -> None:
        assert self._conn is not None
        while True:
            try:
                lines = await self._conn.request_multi(f"POLL {sub_id} 256 500")
            except (ConnectionError, OSError):
                return
            for line in lines:
                _, part, _offset, key, value, _headers = line.split(" ")
                k = _dec(key).decode("utf-8", errors="replace")
                v = _dec(value)
                if k:
                    if v:
                        self._view[k] = v
                    else:
                        self._view.pop(k, None)
                    self._version += 1
                self._positions[int(part)] += 1
            if lines:
                self._advanced.set()

    async def stop(self) -> None:
        self._started = False
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        if self._conn is not None:
            await self._conn.close()
            self._conn = None
        if self in self._mesh._readers:
            self._mesh._readers.remove(self)

    async def barrier(self, *, timeout: float = 30.0) -> None:
        assert self._mesh._control is not None
        response = await self._mesh._control.request(f"ENDS {self._topic}")
        ends = [int(x) for x in response.split()[1].split(",")]

        def behind() -> bool:
            return any(p < e for p, e in zip(self._positions, ends))

        async def gate() -> None:
            while behind():
                self._advanced.clear()
                if not behind():
                    return
                await self._advanced.wait()

        await asyncio.wait_for(gate(), timeout=timeout)

    def get(self, key: str) -> bytes | None:
        return self._view.get(key)

    def items(self) -> dict[str, bytes]:
        return dict(self._view)

    @property
    def is_caught_up(self) -> bool:
        return self._started

    @property
    def version(self) -> "int | None":
        return self._version


class _TcpTableWriter(TableWriter):
    def __init__(self, mesh: TcpMesh, topic: str):
        self._mesh = mesh
        self._topic = topic

    async def put(self, key: str, value: bytes) -> None:
        await self._mesh.publish(self._topic, value, key=key.encode())

    async def tombstone(self, key: str) -> None:
        await self._mesh.publish(self._topic, b"", key=key.encode())


# --------------------------------------------------------------------------- #
# spawning
# --------------------------------------------------------------------------- #


def find_meshd() -> str | None:
    from calfkit_tpu.mesh._native import find_native_binary

    return find_native_binary("meshd", "CALFKIT_MESHD")


def spawn_meshd(
    port: int = DEFAULT_PORT, *, start_new_session: bool = False
) -> subprocess.Popen:
    """Spawn the native broker and wait for readiness.

    ``port=0`` lets the broker bind an OS-assigned port (no
    probe-then-spawn TOCTOU race); the actual port is parsed from the
    broker's ``PORT <n>`` stdout line and exposed as ``proc.meshd_port``
    (set for every spawn).

    ``start_new_session=True`` detaches it from the caller's terminal
    (managed dev brokers must survive a ctrl-c aimed at the CLI).
    """
    from calfkit_tpu.mesh._native import spawn_port_reporting

    binary = find_meshd()
    if binary is None:
        raise FileNotFoundError(
            "meshd binary not found: run `make -C native` or set CALFKIT_MESHD"
        )
    proc, port = spawn_port_reporting(
        binary, port, name="meshd", start_new_session=start_new_session
    )
    proc.meshd_port = port  # type: ignore[attr-defined]
    deadline = time.time() + 10
    import socket

    while time.time() < deadline:
        if proc.poll() is not None:
            # a PONG from a pre-existing broker must not mask a bind failure
            raise RuntimeError(
                f"meshd exited immediately (code {proc.returncode}) — is "
                f"port {port} already in use?"
            )
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.5) as s:
                s.sendall(b"PING\n")
                if s.recv(16).startswith(b"PONG"):
                    return proc
        except OSError:
            time.sleep(0.05)
    proc.terminate()
    raise TimeoutError(f"meshd on port {port} did not become ready")
