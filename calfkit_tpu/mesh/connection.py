"""The frozen connection profile threaded to the Kafka wire client.

Reference: calfkit/client/_connection.py:39-110 — one validated object owns
bootstrap + security + message budget, and every client the transport
creates derives its behavior from it, so the coordinated knobs cannot
drift apart:

- ``max_message_bytes`` is BOTH the producer guard (``publish`` rejects
  bigger values) and the consumer fetch floor
  (``kafka_wire.fetch_floor``), so the biggest legal record can always
  be fetched — a producer-side-only budget would starve consumption of
  the largest legal message.
- ``security`` parses into :class:`calfkit_tpu.mesh.kafka_wire.WireSecurity`
  (TLS + SASL PLAIN/SCRAM); anything unsupported fails loudly at
  construction.
- ``enable_idempotence=True`` is REJECTED by the wire mesh (no
  idempotent-producer sequencing in the native client) — never silently
  honored as at-least-once.
- Raw kwargs that would bypass a coordinated knob are **rejected by name**
  (reference: caller.py:148-165) with a pointer at the right knob.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Mapping

DEFAULT_MAX_MESSAGE_BYTES = 5 * 1024 * 1024

# kwarg name -> the knob that owns it
REJECTED_SECURITY_KWARGS: dict[str, str] = {
    "max_request_size": "max_message_bytes",
    "max_partition_fetch_bytes": "max_message_bytes",
    "fetch_max_bytes": "max_message_bytes",
    "enable_idempotence": "enable_idempotence",
    "acks": "the framework (acks=all is load-bearing for the fault rail)",
    "bootstrap_servers": "the positional bootstrap argument",
    "client_id": "client_id",
    "group_id": "subscribe(group_id=...)",
    "auto_offset_reset": "subscribe(from_latest=...)",
    "enable_auto_commit": "the framework (commit cadence is load-bearing)",
}


@dataclass(frozen=True)
class ConnectionProfile:
    """Validated once; one object owns every coordinated connection knob."""

    bootstrap_servers: str
    max_message_bytes: int = DEFAULT_MAX_MESSAGE_BYTES
    enable_idempotence: bool | None = None
    client_id: str = field(
        default_factory=lambda: f"calfkit-{uuid.uuid4().hex[:8]}"
    )
    security: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        # own copy: a caller mutating its dict after construction must not
        # bypass the reject-by-name validation below
        object.__setattr__(self, "security", dict(self.security))
        if self.max_message_bytes <= 0:
            raise ValueError("max_message_bytes must be positive")
        bad = sorted(set(self.security) & set(REJECTED_SECURITY_KWARGS))
        if bad:
            hints = "; ".join(
                f"{name!r} is owned by {REJECTED_SECURITY_KWARGS[name]}"
                for name in bad
            )
            raise ValueError(
                f"security= must not carry coordinated kwargs: {hints}"
            )

