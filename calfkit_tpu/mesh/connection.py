"""The frozen connection profile threaded to every Kafka client.

Reference: calfkit/client/_connection.py:39-110 — one validated object owns
bootstrap + security + message budget, and every producer/consumer/admin
derives its kwargs from it, so the coordinated knobs cannot drift apart:

- ``max_message_bytes`` is BOTH the producer guard (``max_request_size``)
  and the consumer fetch floor (``max_partition_fetch_bytes`` and
  ``fetch_max_bytes`` are raised to at least the budget, so a max-size
  message can always be fetched — a producer-side-only budget deadlocks
  consumption of the biggest legal message).
- ``enable_idempotence`` is tri-state (None = broker default) and reaches
  every producer.
- Raw kwargs that would bypass a coordinated knob are **rejected by name**
  (reference: caller.py:148-165) with a pointer at the right knob.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Mapping

DEFAULT_MAX_MESSAGE_BYTES = 5 * 1024 * 1024
_AIOKAFKA_DEFAULT_FETCH_MAX = 50 * 1024 * 1024

# kwarg name -> the knob that owns it
REJECTED_SECURITY_KWARGS: dict[str, str] = {
    "max_request_size": "max_message_bytes",
    "max_partition_fetch_bytes": "max_message_bytes",
    "fetch_max_bytes": "max_message_bytes",
    "enable_idempotence": "enable_idempotence",
    "acks": "the framework (acks=all is load-bearing for the fault rail)",
    "bootstrap_servers": "the positional bootstrap argument",
    "client_id": "client_id",
    "group_id": "subscribe(group_id=...)",
    "auto_offset_reset": "subscribe(from_latest=...)",
    "enable_auto_commit": "the framework (commit cadence is load-bearing)",
}


@dataclass(frozen=True)
class ConnectionProfile:
    """Validated once; derives kwargs for every client kind."""

    bootstrap_servers: str
    max_message_bytes: int = DEFAULT_MAX_MESSAGE_BYTES
    enable_idempotence: bool | None = None
    client_id: str = field(
        default_factory=lambda: f"calfkit-{uuid.uuid4().hex[:8]}"
    )
    security: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        # own copy: a caller mutating its dict after construction must not
        # bypass the reject-by-name validation below
        object.__setattr__(self, "security", dict(self.security))
        if self.max_message_bytes <= 0:
            raise ValueError("max_message_bytes must be positive")
        bad = sorted(set(self.security) & set(REJECTED_SECURITY_KWARGS))
        if bad:
            hints = "; ".join(
                f"{name!r} is owned by {REJECTED_SECURITY_KWARGS[name]}"
                for name in bad
            )
            raise ValueError(
                f"security= must not carry coordinated kwargs: {hints}"
            )

    # ------------------------------------------------------------- kwargs
    def common_kwargs(self) -> dict[str, Any]:
        return {"bootstrap_servers": self.bootstrap_servers, **self.security}

    def producer_kwargs(self) -> dict[str, Any]:
        kwargs = dict(
            self.common_kwargs(),
            client_id=self.client_id,
            max_request_size=self.max_message_bytes,  # producer guard
            acks="all",
        )
        if self.enable_idempotence is not None:
            kwargs["enable_idempotence"] = self.enable_idempotence
        return kwargs

    def consumer_kwargs(
        self, *, group_id: str | None, from_latest: bool
    ) -> dict[str, Any]:
        return dict(
            self.common_kwargs(),
            group_id=group_id,
            auto_offset_reset="latest" if from_latest else "earliest",
            enable_auto_commit=group_id is not None,
            # consumer fetch FLOOR: both bounds at least the budget
            max_partition_fetch_bytes=self.max_message_bytes,
            fetch_max_bytes=max(
                self.max_message_bytes, _AIOKAFKA_DEFAULT_FETCH_MAX
            ),
        )

    def admin_kwargs(self) -> dict[str, Any]:
        return self.common_kwargs()
