"""The transport interface every mesh implementation provides.

Semantics contract (what nodes/clients may rely on, independent of backend):

- **At-least-once under redelivery, at-most-once under crash**: commits are
  ACK-first (cadence independent of handler completion), so records
  abandoned in flight by a crashed consumer are not redelivered — the
  reference's documented stance (_faststream_ext/_subscriber.py:214-221).
  Durable state (fan-out batches) makes workflows survive crashes anyway.
- Per-key ordering within a topic (keys map to partitions; one partition is
  consumed serially per group).
- ``group_id=None`` subscriptions are *broadcast taps from latest*: every
  such subscriber sees every record published after it attached (the client
  inbox / firehose pattern).
- Named-group subscriptions share work: each record goes to exactly one live
  member of the group (horizontal scaling — the reference's DP analog,
  SURVEY.md §2.4).
- Compacted-table topics retain the latest value per key; ``None`` value is
  a tombstone.
"""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from calfkit_tpu.mesh.tables import TableReader, TableWriter


@dataclass(frozen=True)
class Record:
    """One consumed record."""

    topic: str
    value: bytes
    key: bytes | None = None
    headers: dict[str, str] = field(default_factory=dict)
    offset: int = 0
    timestamp: float = field(default_factory=time.time)


RecordHandler = Callable[[Record], Awaitable[None]]


class Subscription(abc.ABC):
    """A live subscription; ``stop()`` drains in-flight handlers."""

    @abc.abstractmethod
    async def stop(self) -> None: ...


class CallbackSubscription(Subscription):
    """The standard stop_fn-wrapping subscription every transport uses."""

    def __init__(self, stop_fn: Callable[[], Awaitable[None]]):
        self._stop_fn = stop_fn
        self._stopped = False

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        await self._stop_fn()


class MeshTransport(abc.ABC):
    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def stop(self) -> None: ...

    @abc.abstractmethod
    async def publish(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        headers: dict[str, str] | None = None,
    ) -> None: ...

    @abc.abstractmethod
    async def subscribe(
        self,
        topics: list[str],
        handler: RecordHandler,
        *,
        group_id: str | None,
        from_latest: bool | None = None,
        max_workers: int = 8,
        ordered: bool = True,
    ) -> Subscription:
        """Attach a consumer.

        ``ordered=True`` routes records through a key-ordered dispatcher
        (parallel across keys, serial per key, bounded in-flight);
        ``ordered=False`` runs the handler serially in subscription order
        (broadcast taps).

        ``from_latest=None`` (default) resolves per the contract: broadcast
        taps (``group_id=None``) start from latest, named groups from
        earliest uncommitted.
        """

    @abc.abstractmethod
    async def ensure_topics(
        self, names: list[str], *, compacted: bool = False
    ) -> None: ...

    @abc.abstractmethod
    def table_reader(self, topic: str) -> TableReader: ...

    @abc.abstractmethod
    def table_writer(self, topic: str) -> TableWriter: ...

    # ------------------------------------------------------------------ misc
    @property
    def max_message_bytes(self) -> int:
        """Producer guard / consumer fetch floor (reference default 5 MiB,
        calfkit/client/_connection.py:31)."""
        return 5 * 1024 * 1024
