"""Mesh transport: the Kafka-compatible substrate abstraction.

Three tiers ride one interface (reference: SURVEY.md §5 "distributed
communication backend"):

1. pub/sub of envelopes + steps (:class:`MeshTransport.publish` /
   ``subscribe`` with key-ordered dispatch),
2. compacted tables for control-plane and fan-out state
   (:class:`TableReader` / :class:`TableWriter`),
3. topic admin (``ensure_topics``).

``InMemoryMesh`` is a full single-process implementation — it is both the
offline test substrate and the ``ck dev`` zero-setup mesh.
``KafkaWireMesh`` — the dependency-free native wire-protocol client with
leader/coordinator routing, TLS and SASL — is the production adapter; it
pairs with the in-repo ``native/bin/kafkad`` broker or any real
Kafka/Redpanda cluster.
"""

from calfkit_tpu.mesh.transport import MeshTransport, Record, Subscription
from calfkit_tpu.mesh.connection import ConnectionProfile
from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh, WireSecurity
from calfkit_tpu.mesh.memory import InMemoryMesh
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.tcp import TcpMesh

__all__ = [
    "ConnectionProfile",
    "InMemoryMesh",
    "KafkaWireMesh",
    "KeyOrderedDispatcher",
    "MeshTransport",
    "Record",
    "Subscription",
    "TableReader",
    "TableWriter",
    "TcpMesh",
    "WireSecurity",
]
