"""Kafka adapter: the production MeshTransport over aiokafka.

Import-gated: aiokafka is an optional extra (``pip install calfkit-tpu[kafka]``).
The adapter maps the transport contract onto real Kafka:

- ``subscribe(group_id=...)`` → an ``AIOKafkaConsumer`` in that group with
  auto-commit ("ACK-first": commit cadence is independent of handler
  completion — at-most-once for crash-abandoned in-flight records, matching
  the reference's documented stance, _faststream_ext/_subscriber.py:214-221),
  feeding the same :class:`KeyOrderedDispatcher` used by the in-memory mesh.
- ``subscribe(group_id=None)`` → a groupless consumer from latest offsets.
- tables → a compacted-topic consumer maintaining a local dict view with
  catch-up (end-offsets gate) and barrier (produce-stamp + wait) semantics.

Untested in the offline lane; exercised by ``-m kafka`` integration tests
against a real broker.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable

from calfkit_tpu.exceptions import MeshUnavailableError
from calfkit_tpu.mesh.connection import (
    DEFAULT_MAX_MESSAGE_BYTES,
    ConnectionProfile,
)
from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.transport import (
    CallbackSubscription,
    MeshTransport,
    Record,
    RecordHandler,
    Subscription,
)

logger = logging.getLogger(__name__)


def _aiokafka():
    try:
        import aiokafka  # type: ignore

        return aiokafka
    except ImportError as exc:  # pragma: no cover - depends on environment
        raise MeshUnavailableError(
            "KafkaMesh requires aiokafka (install the 'kafka' extra); "
            "use InMemoryMesh for local development",
            reason="missing-dependency",
        ) from exc


class KafkaMesh(MeshTransport):
    """MeshTransport over a Kafka-compatible cluster."""

    def __init__(
        self,
        bootstrap_servers: str | None = None,
        *,
        profile: "ConnectionProfile | None" = None,
        # None = "not passed" for every legacy kwarg, so the profile=
        # conflict check can't false-positive on a value that happens to
        # equal a default (security={} is benign; 5 MiB is the default)
        max_message_bytes: int | None = None,
        enable_idempotence: bool | None = None,
        security: dict | None = None,
        client_id: str | None = None,
    ):
        _aiokafka()
        if profile is None:
            if bootstrap_servers is None:
                raise ValueError("bootstrap_servers (or profile=) required")
            kwargs: dict = dict(
                bootstrap_servers=bootstrap_servers,
                max_message_bytes=(
                    max_message_bytes
                    if max_message_bytes is not None
                    else DEFAULT_MAX_MESSAGE_BYTES
                ),
                enable_idempotence=enable_idempotence,
                security=dict(security or {}),
            )
            if client_id is not None:
                kwargs["client_id"] = client_id
            profile = ConnectionProfile(**kwargs)
        else:
            # profile= owns every connection knob; silently ignoring a
            # conflicting legacy kwarg would contradict reject-by-name
            conflicts = [
                name
                for name, value in (
                    ("bootstrap_servers", bootstrap_servers),
                    ("max_message_bytes", max_message_bytes),
                    ("enable_idempotence", enable_idempotence),
                    ("security", security),
                    ("client_id", client_id),
                )
                if value is not None
            ]
            if conflicts:
                raise ValueError(
                    f"profile= conflicts with {conflicts}: set these on the "
                    "ConnectionProfile instead"
                )
        self._profile = profile
        self._max_bytes = profile.max_message_bytes
        self._producer = None
        self._tasks: list[asyncio.Task[None]] = []
        self._consumers: list = []
        self._dispatchers: list[KeyOrderedDispatcher] = []
        self._readers: list["_KafkaTableReader"] = []
        self._started = False

    @property
    def max_message_bytes(self) -> int:
        return self._max_bytes

    @property
    def profile(self) -> "ConnectionProfile":
        return self._profile

    def _common_kwargs(self) -> dict:
        return self._profile.common_kwargs()

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._started:
            return
        aiokafka = _aiokafka()
        self._producer = aiokafka.AIOKafkaProducer(
            **self._profile.producer_kwargs()
        )
        await self._producer.start()
        self._started = True

    async def stop(self) -> None:
        self._started = False
        # table readers own consumers + pump tasks the lists below don't
        # cover; stopping the mesh must not leak them
        for reader in list(self._readers):
            try:
                await reader.stop()
            except Exception:  # noqa: BLE001
                logger.exception("table reader stop failed")
        self._readers = []
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        for consumer in self._consumers:
            try:
                await consumer.stop()
            except Exception:  # noqa: BLE001
                logger.exception("consumer stop failed")
        self._consumers = []
        for d in self._dispatchers:
            try:
                await d.stop()
            except Exception:  # noqa: BLE001
                logger.exception("dispatcher drain failed")
        self._dispatchers = []
        if self._producer is not None:
            await self._producer.stop()
            self._producer = None

    # ---------------------------------------------------------------- admin
    async def ensure_topics(self, names: list[str], *, compacted: bool = False) -> None:
        from aiokafka.admin import AIOKafkaAdminClient, NewTopic  # type: ignore

        admin = AIOKafkaAdminClient(**self._profile.admin_kwargs())
        await admin.start()
        try:
            configs = {"cleanup.policy": "compact"} if compacted else {}

            def new_topic(name: str) -> "NewTopic":
                return NewTopic(
                    name=name, num_partitions=16, replication_factor=-1,
                    topic_configs=configs,
                )

            def is_exists(exc: BaseException) -> bool:
                return (
                    "TopicAlreadyExists" in type(exc).__name__
                    or "exists" in str(exc).lower()
                )

            try:
                # the happy path is ONE admin round trip for the whole set
                await admin.create_topics(
                    [new_topic(n) for n in names], validate_only=False
                )
            except Exception as batch_exc:  # noqa: BLE001
                if not is_exists(batch_exc):
                    raise
                # a pre-existing topic aborted the batch: create the rest
                # individually so it can't mask genuinely-missing siblings
                for name in names:
                    try:
                        await admin.create_topics(
                            [new_topic(name)], validate_only=False
                        )
                    except Exception as exc:  # noqa: BLE001
                        if not is_exists(exc):
                            raise
        finally:
            await admin.close()

    # -------------------------------------------------------------- produce
    async def publish(
        self,
        topic: str,
        value: bytes | None,
        *,
        key: bytes | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        # value=None is a real null-value record — REQUIRED for tombstones:
        # Kafka log compaction only purges null values, an empty byte value
        # would be retained (and replayed to every table reader) forever
        if value is not None and len(value) > self._max_bytes:
            raise ValueError(
                f"message of {len(value)} bytes exceeds max_message_bytes={self._max_bytes}"
            )
        if self._producer is None:
            raise RuntimeError("mesh not started")
        hdrs = [(k, v.encode("utf-8")) for k, v in (headers or {}).items()]
        await self._producer.send_and_wait(topic, value=value, key=key, headers=hdrs)

    # -------------------------------------------------------------- consume
    async def subscribe(
        self,
        topics: list[str],
        handler: RecordHandler,
        *,
        group_id: str | None,
        from_latest: bool | None = None,
        max_workers: int = 8,
        ordered: bool = True,
    ) -> Subscription:
        aiokafka = _aiokafka()
        if from_latest is None:
            from_latest = group_id is None
        consumer = aiokafka.AIOKafkaConsumer(
            *topics,
            **self._profile.consumer_kwargs(
                group_id=group_id, from_latest=from_latest
            ),
        )
        await consumer.start()
        self._consumers.append(consumer)

        deliver = handler
        dispatcher: KeyOrderedDispatcher | None = None
        if ordered:
            dispatcher = KeyOrderedDispatcher(
                handler, max_workers=max_workers, name=f"kafka-{group_id or 'tap'}"
            )
            dispatcher.start()
            self._dispatchers.append(dispatcher)

            async def deliver(record: Record) -> None:  # type: ignore[misc]
                await dispatcher.submit(record)

        async def pump() -> None:
            async for msg in consumer:
                record = Record(
                    topic=msg.topic,
                    key=msg.key,
                    value=msg.value or b"",
                    headers={
                        k: v.decode("utf-8", errors="replace") for k, v in (msg.headers or [])
                    },
                    offset=msg.offset,
                    timestamp=msg.timestamp / 1000.0,
                )
                try:
                    await deliver(record)
                except Exception:  # noqa: BLE001
                    logger.exception("kafka delivery failed on %s", msg.topic)

        task = asyncio.get_running_loop().create_task(pump(), name=f"kafka-pump-{topics}")
        self._tasks.append(task)

        async def stop_fn() -> None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            await consumer.stop()
            if consumer in self._consumers:
                self._consumers.remove(consumer)
            if dispatcher is not None:
                await dispatcher.stop()
                if dispatcher in self._dispatchers:
                    self._dispatchers.remove(dispatcher)

        return CallbackSubscription(stop_fn)

    # --------------------------------------------------------------- tables
    def table_reader(self, topic: str) -> TableReader:
        reader = _KafkaTableReader(self, topic)
        self._readers.append(reader)
        return reader

    def table_writer(self, topic: str) -> TableWriter:
        return _KafkaTableWriter(self, topic)


class _KafkaTableReader(TableReader):
    """Compacted-topic view: consume-all into a dict, catch-up + barrier."""

    def __init__(self, mesh: KafkaMesh, topic: str):
        self._mesh = mesh
        self._topic = topic
        self._view: dict[str, bytes] = {}
        self._consumer = None
        self._task: asyncio.Task[None] | None = None
        self._caught_up = False
        self._positions: dict[int, int] = {}
        self._advanced = asyncio.Event()

    async def start(self, *, timeout: float = 30.0) -> None:
        aiokafka = _aiokafka()
        self._consumer = aiokafka.AIOKafkaConsumer(
            self._topic,
            **self._mesh._common_kwargs(),
            group_id=None,
            auto_offset_reset="earliest",
            enable_auto_commit=False,
        )
        await self._consumer.start()
        try:
            # groupless consumers get their assignment lazily; wait for it so
            # the catch-up gate sees real end offsets
            deadline = asyncio.get_running_loop().time() + timeout
            while not self._consumer.assignment():
                if asyncio.get_running_loop().time() > deadline:
                    raise TimeoutError(f"no partition assignment for {self._topic}")
                await asyncio.sleep(0.05)
            self._task = asyncio.get_running_loop().create_task(self._pump())
            # catch-up gate: consume to attach-time end offsets before serving
            await self.barrier(
                timeout=max(deadline - asyncio.get_running_loop().time(), 1.0)
            )
        except BaseException:
            # failed start must not leak the consumer/pump (callers won't
            # stop() a reader that never started)
            await self.stop()
            raise
        self._caught_up = True

    async def _pump(self) -> None:
        async for msg in self._consumer:
            key = (msg.key or b"").decode("utf-8", errors="replace")
            if key:
                if msg.value:
                    self._view[key] = msg.value
                else:
                    self._view.pop(key, None)
            self._positions[msg.partition] = msg.offset + 1
            self._advanced.set()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        if self._consumer:
            await self._consumer.stop()
            self._consumer = None
        if self in self._mesh._readers:
            self._mesh._readers.remove(self)

    async def barrier(self, *, timeout: float = 30.0) -> None:
        """Freshness barrier across ALL partitions: capture end offsets at
        call time and wait until consumption reaches every one of them.

        A sentinel write would only prove visibility for the sentinel's own
        partition — Kafka gives no cross-partition ordering — so the gate is
        offset-based instead."""
        if self._consumer is None:
            raise RuntimeError("table reader not started")
        partitions = list(self._consumer.assignment())
        if not partitions:
            return
        end_offsets = await self._consumer.end_offsets(partitions)

        def behind() -> bool:
            return any(
                self._positions.get(tp.partition, 0) < off
                for tp, off in end_offsets.items()
                if off > 0
            )

        async def gate() -> None:
            while behind():
                self._advanced.clear()
                if not behind():  # re-check after clear: lost-wakeup guard
                    return
                await self._advanced.wait()

        await asyncio.wait_for(gate(), timeout=timeout)

    def get(self, key: str) -> bytes | None:
        return self._view.get(key)

    def items(self) -> dict[str, bytes]:
        return dict(self._view)

    @property
    def is_caught_up(self) -> bool:
        return self._caught_up


class _KafkaTableWriter(TableWriter):
    def __init__(self, mesh: KafkaMesh, topic: str):
        self._mesh = mesh
        self._topic = topic

    async def put(self, key: str, value: bytes) -> None:
        await self._mesh.publish(self._topic, value, key=key.encode("utf-8"))

    async def tombstone(self, key: str) -> None:
        await self._mesh.publish(self._topic, None, key=key.encode("utf-8"))
