"""Mesh URL resolution — one grammar for the CLI, Client, and Worker.

Reference: the mesh-url handling at calfkit/client/_mesh_url.py (env
``CALFKIT_MESH_URL``, scheme-dispatched transports).
"""

from __future__ import annotations

import os

from calfkit_tpu.mesh.transport import MeshTransport

MESH_URL_ENV = "CALFKIT_MESH_URL"


def mesh_from_url(url: str) -> MeshTransport:
    """``memory://`` | ``tcp://host:port`` | ``kafka://host:port[,...]``
    (``kafka+wire://`` is an accepted alias).

    ``kafka://`` resolves to the native wire-protocol client
    (:class:`KafkaWireMesh`) — the framework's only Kafka transport:
    leader/coordinator routing, TLS and SASL are spoken natively, so no
    third-party adapter exists to prefer (the aiokafka adapter was
    removed in r5: it could never execute in-image and its fake was
    self-certified — VERDICT r4 item 3).  Secured clusters need an
    ssl_context/credentials a URL cannot carry: construct
    ``KafkaWireMesh(profile=ConnectionProfile(...))`` directly."""
    if url.startswith("memory://"):
        from calfkit_tpu.mesh.memory import InMemoryMesh

        return InMemoryMesh()
    if url.startswith("tcp://"):
        from calfkit_tpu.mesh.tcp import TcpMesh

        return TcpMesh(url.removeprefix("tcp://"))
    if url.startswith("kafka+wire://") or url.startswith("kafka://"):
        from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh

        bootstrap = url.removeprefix("kafka+wire://").removeprefix("kafka://")
        return KafkaWireMesh(bootstrap)
    raise ValueError(
        f"unsupported mesh url {url!r} "
        "(use memory://, tcp://host:port, or kafka://host:port)"
    )


def resolve_mesh(
    mesh: "MeshTransport | str | None",
    *,
    allow_memory: bool = True,
    default: str | None = None,
) -> tuple[MeshTransport, bool]:
    """Accept a transport, a URL string, or None (→ $CALFKIT_MESH_URL,
    then ``default`` when given).

    → (transport, owned): ``owned`` is True when THIS call constructed the
    transport from a url — the caller is then responsible for stopping it.

    ``allow_memory=False`` rejects ``memory://`` urls: a fresh in-process
    mesh resolved from a URL can by construction reach no worker, so a
    client connecting that way would only ever time out (the CLI allows it
    because the CLI also hosts the worker in the same process).
    """
    if isinstance(mesh, MeshTransport):
        return mesh, False
    if isinstance(mesh, str):
        url = mesh
    elif mesh is None:
        url = os.environ.get(MESH_URL_ENV) or default or ""
        if not url:
            raise ValueError(
                "no mesh given and CALFKIT_MESH_URL is unset — pass a "
                "transport, a url (tcp://host:port, kafka://host:port), "
                "or export CALFKIT_MESH_URL"
            )
    else:
        raise TypeError(
            f"mesh must be a MeshTransport, url string, or None, got "
            f"{type(mesh).__name__}"
        )
    if not allow_memory and url.startswith("memory://"):
        raise ValueError(
            "memory:// resolved from a url is a brand-new isolated mesh — "
            "no worker can share it; pass the worker's InMemoryMesh object "
            "instead (or use tcp://, kafka://)"
        )
    return mesh_from_url(url), True
