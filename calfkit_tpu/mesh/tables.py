"""Compacted-table readers/writers — the slim ktables equivalent.

A *table topic* is a compacted topic read as a key→value map.  Readers expose
a **catch-up gate** (``start()`` returns only once the view has consumed to
the end-of-topic as of attach time) and a **barrier** (``await barrier()``
guarantees the view reflects every record published before the call) — the
read-your-own-writes primitive the durable fan-out store depends on
(reference: ktables usage at calfkit/nodes/_fanout_store.py:258-337 and
controlplane/view.py catch-up gates).
"""

from __future__ import annotations

import abc


class TableReader(abc.ABC):
    @abc.abstractmethod
    async def start(self, *, timeout: float = 30.0) -> None:
        """Attach and catch up; raises ``TimeoutError`` if the gate fails."""

    @abc.abstractmethod
    async def stop(self) -> None: ...

    @abc.abstractmethod
    async def barrier(self, *, timeout: float = 30.0) -> None:
        """Block until the view reflects all records published before now."""

    @abc.abstractmethod
    def get(self, key: str) -> bytes | None: ...

    @abc.abstractmethod
    def items(self) -> dict[str, bytes]:
        """Snapshot of the compacted view (tombstoned keys absent)."""

    @property
    @abc.abstractmethod
    def is_caught_up(self) -> bool: ...

    @property
    def version(self) -> "int | None":
        """Monotonic view-mutation counter: bumps at least once whenever
        the folded view changes (put, tombstone, rebuild swap), never
        otherwise.  Lets per-call readers (the fleet registry's parsed-
        replica cache, ISSUE 9) make the no-change case a single int
        compare instead of re-scanning the table's bytes.  ``None`` (the
        default, for third-party readers) means "no counter — fall back
        to content fingerprinting"; all in-repo transports implement it.
        """
        return None


class TableWriter(abc.ABC):
    @abc.abstractmethod
    async def put(self, key: str, value: bytes) -> None:
        """Publish and wait for the broker ack."""

    @abc.abstractmethod
    async def tombstone(self, key: str) -> None: ...
