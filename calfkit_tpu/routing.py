"""Route grammar: ``.``-delimited patterns with optional trailing ``*``.

A *route* is the logical address inside a node's handler table (distinct from
the Kafka topic, which addresses the node itself).  Routes are dot-delimited
identifier segments; a handler may register a *pattern* whose final segment is
``*``, matching any suffix.  More-specific patterns win.

Reference: calfkit/_routing.py:14-80 (same grammar and specificity ordering).
"""

from __future__ import annotations

import re

_SEGMENT = re.compile(r"^[a-zA-Z0-9_-]+$")


class RouteError(ValueError):
    pass


def validate_route(route: str) -> str:
    """Validate a concrete (wildcard-free) route."""
    if not route:
        raise RouteError("route must be non-empty")
    for seg in route.split("."):
        if not _SEGMENT.match(seg):
            raise RouteError(f"invalid route segment {seg!r} in {route!r}")
    return route


def validate_route_pattern(pattern: str) -> str:
    """Validate a handler pattern: a route whose final segment may be ``*``."""
    if not pattern:
        raise RouteError("route pattern must be non-empty")
    segments = pattern.split(".")
    for i, seg in enumerate(segments):
        if seg == "*":
            if i != len(segments) - 1:
                raise RouteError(
                    f"wildcard only allowed as the final segment: {pattern!r}"
                )
        elif not _SEGMENT.match(seg):
            raise RouteError(f"invalid segment {seg!r} in pattern {pattern!r}")
    return pattern


def route_matches(pattern: str, route: str) -> bool:
    """Does ``pattern`` match the concrete ``route``?

    ``a.b`` matches only ``a.b``; ``a.*`` matches ``a``, ``a.b``, ``a.b.c``;
    a bare ``*`` matches everything.
    """
    if pattern == route:
        return True
    if pattern == "*":
        return True
    if pattern.endswith(".*"):
        prefix = pattern[:-2]
        return route == prefix or route.startswith(prefix + ".")
    return False


def specificity(pattern: str) -> tuple[int, int]:
    """Sort key: exact patterns before wildcards, longer prefixes first."""
    if pattern == "*":
        return (1, 0)
    if pattern.endswith(".*"):
        return (1, -len(pattern.split(".")))
    return (0, -len(pattern.split(".")))


def match_chain(patterns: list[str], route: str) -> list[str]:
    """All patterns matching ``route``, most-specific first.

    This is the chain-of-responsibility order for routed dispatch
    (reference: calfkit/_routing.py:72).
    """
    return sorted((p for p in patterns if route_matches(p, route)), key=specificity)
