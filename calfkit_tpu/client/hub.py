"""The client hub: one groupless inbox subscriber demuxing replies and steps
by correlation id into weakly-held run channels.

Reference: calfkit/client/hub.py:89-426.  Invariants preserved:

- a handle is registered BEFORE the call publishes (race-free: the reply
  cannot beat the registration);
- channels are weakly held — an abandoned handle stops consuming memory;
- cancel-safe: ``result()``/``stream()`` can be cancelled without corrupting
  the channel; a late reply to a dead handle goes to the firehose only.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import weakref
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Generic, TypeVar

from calfkit_tpu import protocol
from calfkit_tpu.exceptions import ClientTimeoutError, NodeFaultError
from calfkit_tpu.mesh.transport import Record
from calfkit_tpu.models.error_report import ErrorReport, FaultTypes
from calfkit_tpu.models.node_result import InvocationResult
from calfkit_tpu.models.reply import FaultMessage, ReturnMessage
from calfkit_tpu.models.session_context import Envelope
from calfkit_tpu.models.step import StepEvent, StepMessage

logger = logging.getLogger(__name__)

OutputT = TypeVar("OutputT")


@dataclass
class RunCompleted:
    envelope: Envelope
    headers: dict[str, str]


@dataclass
class RunFailed:
    report: ErrorReport
    envelope: Envelope | None = None


Terminal = RunCompleted | RunFailed


@dataclass
class _RunChannel:
    correlation_id: str
    task_id: str
    steps: asyncio.Queue[StepEvent] = field(
        default_factory=lambda: asyncio.Queue(maxsize=1024)
    )
    terminal: asyncio.Future[Terminal] = field(
        default_factory=lambda: asyncio.get_running_loop().create_future()
    )

    def push_step(self, event: StepEvent) -> None:
        try:
            self.steps.put_nowait(event)
        except asyncio.QueueFull:
            # drop-oldest: the terminal result matters more than telemetry
            with contextlib.suppress(asyncio.QueueEmpty, asyncio.QueueFull):
                self.steps.get_nowait()
                self.steps.put_nowait(event)

    def complete(self, terminal: Terminal) -> None:
        if not self.terminal.done():
            self.terminal.set_result(terminal)


class InvocationHandle(Generic[OutputT]):
    """The caller's grip on one in-flight run."""

    # fleet routing (ISSUE 7): the replica instance id this run was
    # placed on, set by AgentGateway.start; None = shared-topic placement
    routed_replica: "str | None" = None
    # the FULL control-plane replica key ("<node_id>@<instance>") of the
    # placement — what the failover supervisor's dead-placement probe
    # looks up in the registry (ISSUE 9); None = shared-topic placement
    routed_replica_key: "str | None" = None
    # run-scoped observability (ISSUE 17): the run id this placement
    # serves under — every retry/failover/hedge/resume placement of one
    # logical call shares it — and the client's ledger, so
    # ``run_report()`` answers from the handle
    run_id: "str | None" = None
    _run_ledger: Any = None

    def run_report(self) -> Any:
        """The run-level report (:class:`~calfkit_tpu.models.records.RunRecord`)
        for this handle's run: every attempt with its placement, marker
        kind, and typed outcome (ISSUE 17).  None when the client's
        ledger no longer holds the run (LRU aged out)."""
        if self._run_ledger is None or self.run_id is None:
            return None
        return self._run_ledger.run_report(self.run_id)

    def __init__(
        self,
        channel: _RunChannel,
        output_type: type[OutputT],
        *,
        default_timeout: float | None = None,
        on_abandon: Any = None,  # async callable: publish the mesh cancel
        task_registry: "set | None" = None,  # client-owned: close() drains it
    ):
        self._channel = channel
        self._output_type = output_type
        self._default_timeout = default_timeout
        self._on_abandon = on_abandon
        self._cancelled = False
        self._cancel_task: "asyncio.Task | None" = None
        self._task_registry = task_registry

    @property
    def correlation_id(self) -> str:
        return self._channel.correlation_id

    @property
    def task_id(self) -> str:
        return self._channel.task_id

    # the cancel publish runs as a background task off the timeout rail
    # (_cancel_soon) but still must not linger forever: an unreachable
    # broker is the LIKELY state when a timeout fires — the publish could
    # otherwise block on reconnection indefinitely
    _CANCEL_PUBLISH_TIMEOUT = 5.0

    async def cancel(self) -> None:
        """Publish the run's mesh ``cancel`` record (idempotent,
        best-effort, time-bounded): downstream engines abandon in-flight
        work for this correlation id instead of decoding for a caller
        that left.  Called automatically when ``result()``/``stream()``
        time out; call it yourself when abandoning a run for any other
        reason."""
        if self._cancelled or self._on_abandon is None:
            return
        self._cancelled = True
        try:
            await asyncio.wait_for(
                self._on_abandon(), self._CANCEL_PUBLISH_TIMEOUT
            )
        except Exception:  # noqa: BLE001 - cancel is advisory, never masks
            logger.debug(
                "cancel publish failed for %s", self.correlation_id[:8],
                exc_info=True,
            )

    def _cancel_soon(self) -> None:
        """Queue the advisory cancel publish OFF the timeout rail: the
        ``ClientTimeoutError`` must surface the moment the caller's
        budget expires, not up to ``_CANCEL_PUBLISH_TIMEOUT`` later when
        the broker is unreachable (the likely state when a timeout
        fires).  The task is retained on the handle — and registered with
        the client so ``Client.close()`` gives it a bounded window to
        land before the mesh stops; ``cancel()`` stays awaitable for
        callers who want publish confirmation."""
        if self._cancelled or self._on_abandon is None:
            return
        task = asyncio.get_running_loop().create_task(self.cancel())
        self._cancel_task = task
        if self._task_registry is not None:
            self._task_registry.add(task)
            task.add_done_callback(self._task_registry.discard)

    @property
    def terminal_arrived(self) -> bool:
        """True once the run's terminal reply (return OR fault) landed."""
        return self._channel.terminal.done()

    async def wait(self, timeout: "float | None") -> bool:
        """Await the terminal for up to ``timeout`` seconds WITHOUT
        consuming it or publishing a cancel on expiry — the failover
        supervisor's probe primitive (ISSUE 9): returns True once the
        terminal landed, False on a quiet timeout (the run is still
        in flight; call :meth:`result` to consume, :meth:`cancel` to
        abandon)."""
        try:
            await asyncio.wait_for(
                asyncio.shield(self._channel.terminal), timeout
            )
            return True
        except asyncio.TimeoutError:
            return False

    async def result(self, timeout: float | None = None) -> InvocationResult[OutputT]:
        """Await the terminal reply; faults raise :class:`NodeFaultError`.
        A timeout publishes the run's mesh cancel before raising — the
        timeout is no longer purely local (ISSUE 5)."""
        timeout = timeout if timeout is not None else self._default_timeout
        try:
            terminal = await asyncio.wait_for(
                asyncio.shield(self._channel.terminal), timeout
            )
        except asyncio.TimeoutError:
            self._cancel_soon()
            raise ClientTimeoutError(
                f"run {self.correlation_id[:8]} produced no terminal reply "
                f"within {timeout}s"
            ) from None
        if isinstance(terminal, RunFailed):
            raise NodeFaultError(terminal.report, terminal.envelope)
        return InvocationResult.from_envelope(
            terminal.envelope,
            self._output_type,
            correlation_id=self.correlation_id,
            task_id=self.task_id,
        )

    async def stream(
        self, timeout: float | None = None
    ) -> AsyncIterator[StepEvent | InvocationResult[OutputT]]:
        """Yield step events live, ending with the typed result."""
        timeout = timeout if timeout is not None else self._default_timeout
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout if timeout is not None else None
        while True:
            remaining: float | None = None
            if deadline is not None:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    self._cancel_soon()
                    raise ClientTimeoutError(
                        f"run {self.correlation_id[:8]} stream timed out"
                    )
            step_task = asyncio.ensure_future(self._channel.steps.get())
            try:
                done, _ = await asyncio.wait(
                    [step_task, self._channel.terminal],
                    timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            except asyncio.CancelledError:
                step_task.cancel()
                raise
            if not done:
                step_task.cancel()
                self._cancel_soon()
                raise ClientTimeoutError(
                    f"run {self.correlation_id[:8]} stream timed out"
                )
            if step_task in done:
                yield step_task.result()
                continue
            step_task.cancel()
            # drain any steps that raced the terminal
            while not self._channel.steps.empty():
                yield self._channel.steps.get_nowait()
            terminal = self._channel.terminal.result()
            if isinstance(terminal, RunFailed):
                raise NodeFaultError(terminal.report, terminal.envelope)
            yield InvocationResult.from_envelope(
                terminal.envelope,
                self._output_type,
                correlation_id=self.correlation_id,
                task_id=self.task_id,
            )
            return


class Hub:
    """Demuxes the client inbox into run channels + the firehose tee."""

    def __init__(self) -> None:
        self._channels: weakref.WeakValueDictionary[str, _RunChannel] = (
            weakref.WeakValueDictionary()
        )
        self._firehose_taps: list[Any] = []  # EventStream instances
        # terminal-arrival hook (ISSUE 10): called with the correlation
        # id of EVERY terminal reply this inbox observes — including
        # replies to abandoned/fire-and-forget runs whose channel is
        # gone.  The client's lease heartbeat uses it to stop counting a
        # run as outstanding the moment its terminal lands, which no
        # handle-side callback can do for a dropped handle.
        self.on_terminal: "Any | None" = None

    def track(self, correlation_id: str, task_id: str) -> _RunChannel:
        channel = _RunChannel(correlation_id=correlation_id, task_id=task_id)
        self._channels[correlation_id] = channel
        return channel

    def add_tap(self, tap: Any) -> None:
        self._firehose_taps.append(tap)

    def remove_tap(self, tap: Any) -> None:
        if tap in self._firehose_taps:
            self._firehose_taps.remove(tap)

    # ----------------------------------------------------------- dispatch
    async def on_record(self, record: Record) -> None:
        headers = record.headers
        correlation_id = headers.get(protocol.HDR_CORRELATION)
        if headers.get(protocol.HDR_WIRE) == "step":
            self._on_step(record, correlation_id)
            return
        self._on_reply(record, correlation_id, headers)

    def _on_step(self, record: Record, correlation_id: str | None) -> None:
        try:
            message = StepMessage.from_wire(record.value)
        except ValueError:
            logger.debug("undecodable step message dropped")
            return
        for step in message.steps:
            event = StepEvent(
                correlation_id=correlation_id or "",
                task_id=record.headers.get(protocol.HDR_TASK),
                node=message.emitter or None,
                step=step,
            )
            channel = self._channels.get(correlation_id or "")
            if channel is not None:
                channel.push_step(event)
            for tap in self._firehose_taps:
                tap.push(event)

    def _on_reply(
        self, record: Record, correlation_id: str | None, headers: dict[str, str]
    ) -> None:
        try:
            envelope = Envelope.from_wire(record.value)
        except ValueError:
            logger.warning("undecodable reply on client inbox dropped")
            return
        if self.on_terminal is not None and correlation_id:
            try:
                self.on_terminal(correlation_id)
            except Exception:  # noqa: BLE001 - the hook never blocks replies
                logger.debug("on_terminal hook failed", exc_info=True)
        channel = self._channels.get(correlation_id or "")
        if channel is None:
            logger.debug(
                "reply for unknown/abandoned run %s", (correlation_id or "?")[:8]
            )
            return
        reply = envelope.reply
        if isinstance(reply, ReturnMessage):
            channel.complete(RunCompleted(envelope=envelope, headers=headers))
        elif isinstance(reply, FaultMessage):
            channel.complete(RunFailed(report=reply.report, envelope=envelope))
        else:
            channel.complete(
                RunFailed(
                    report=ErrorReport.build_safe(
                        FaultTypes.DESERIALIZATION_ERROR,
                        "terminal record carried no reply",
                    ),
                    envelope=envelope,
                )
            )
