"""The caller surface (SURVEY.md §1 layer 8)."""

from calfkit_tpu.client.caller import AgentGateway, Client
from calfkit_tpu.client.events import EventStream
from calfkit_tpu.client.hub import Hub, InvocationHandle, RunCompleted, RunFailed
from calfkit_tpu.client.mesh import Mesh
from calfkit_tpu.models.node_result import InvocationResult

__all__ = [
    "AgentGateway",
    "Client",
    "EventStream",
    "Hub",
    "InvocationHandle",
    "InvocationResult",
    "Mesh",
    "RunCompleted",
    "RunFailed",
]
