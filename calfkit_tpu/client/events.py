"""The client firehose: every step event from every run this client observes.

Bounded drop-oldest per observer with a ``dropped`` counter (reference:
calfkit/client/events.py:26-157).
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import AsyncIterator, Callable

from calfkit_tpu.models.step import StepEvent

DEFAULT_BUFFER = 1024

_CLOSED = object()  # queue sentinel: wakes consumers parked on get()


class EventStream:
    def __init__(
        self,
        *,
        buffer: int = DEFAULT_BUFFER,
        on_close: Callable[["EventStream"], None] | None = None,
    ):
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=buffer + 1)
        self.dropped = 0
        self._closed = False
        self._on_close = on_close

    def push(self, event: StepEvent) -> None:
        if self._closed:
            return
        try:
            self._queue.put_nowait(event)
        except asyncio.QueueFull:
            self.dropped += 1
            with contextlib.suppress(asyncio.QueueEmpty, asyncio.QueueFull):
                self._queue.get_nowait()
                self._queue.put_nowait(event)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._on_close is not None:
            self._on_close(self)
        with contextlib.suppress(asyncio.QueueFull):
            self._queue.put_nowait(_CLOSED)  # wake any parked consumer

    def __aiter__(self) -> AsyncIterator[StepEvent]:
        return self

    async def __anext__(self) -> StepEvent:
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is _CLOSED:
            raise StopAsyncIteration
        return item
