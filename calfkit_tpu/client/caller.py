"""The Client: the caller surface onto the mesh.

Reference: calfkit/client/caller.py:46-437 + gateway.py.  Semantics kept:

- ``Client.connect(...)`` is **lazy sync** — no I/O until first use;
- the inbox subscriber is consuming before the first call publishes;
- three verbs per agent: ``send`` (fire token), ``start`` (handle),
  ``execute`` (await result);
- handles register before publish (race-free);
- ``client.events()`` is the bounded drop-oldest firehose.
"""

from __future__ import annotations

import asyncio
import contextlib
import random
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Generic, TypeVar

from calfkit_tpu import cancellation, protocol
from calfkit_tpu.exceptions import (
    RETRIABLE_FAULT_TYPES,
    ClientClosedError,
    NodeFaultError,
)
from calfkit_tpu.keying import partition_key
from calfkit_tpu.mesh.transport import MeshTransport, Subscription
from calfkit_tpu.models.messages import ModelMessage
from calfkit_tpu.models.node_result import InvocationResult
from calfkit_tpu.models.payload import ContentPart, TextPart
from calfkit_tpu.models.session_context import (
    CallFrame,
    Envelope,
    SessionContext,
    WorkflowState,
    new_id,
)
from calfkit_tpu.models.state import State
from calfkit_tpu.client.events import EventStream
from calfkit_tpu.client.hub import Hub, InvocationHandle

OutputT = TypeVar("OutputT")

DEFAULT_TIMEOUT = 60.0


@dataclass(frozen=True)
class RetryPolicy:
    """Caller-side bounded retry with jittered exponential backoff
    (ISSUE 5) — applied by :meth:`AgentGateway.execute` to faults whose
    ``error_type`` is in :data:`RETRIABLE_FAULT_TYPES` (overload, drain,
    transient capability loss) and NOTHING else: a deadline fault means
    the budget is spent, a node error means the same call would fail the
    same way.

    Delays follow ``base_delay * multiplier**attempt`` capped at
    ``max_delay``, each multiplied by a jitter factor drawn uniformly
    from ``[1 - jitter, 1]``.  ``rng`` is a zero-arg callable returning
    a float in ``[0, 1)`` (default :func:`random.random`); pass e.g.
    ``random.Random(0).random`` for fully deterministic backoff (the
    chaos harness does)."""

    attempts: int = 3  # total tries (1 = no retry)
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # fraction of the delay the jitter may remove
    rng: "Callable[[], float] | None" = None

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        raw = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        draw = (self.rng or random.random)()
        return raw * (1.0 - self.jitter * draw)

    @staticmethod
    def retriable(exc: BaseException) -> bool:
        return (
            isinstance(exc, NodeFaultError)
            and exc.report.error_type in RETRIABLE_FAULT_TYPES
        )


class Client:
    def __init__(
        self,
        mesh: MeshTransport,
        *,
        client_id: str | None = None,
        default_timeout: float = DEFAULT_TIMEOUT,
        retry: "RetryPolicy | None" = None,
        router: Any = None,  # FleetRouter | policy name | None
    ):
        self.mesh = mesh
        self.client_id = client_id or uuid.uuid4().hex[:12]
        self.inbox_topic = protocol.client_inbox_topic(self.client_id)
        self.default_timeout = default_timeout
        # opt-in bounded retry for execute(): None = single attempt (the
        # pre-ISSUE-5 behavior; retries change at-most-once semantics for
        # non-idempotent agents, so the caller must choose them)
        self.retry = retry
        # opt-in fleet routing (ISSUE 7): a FleetRouter (or a policy name
        # — "least-loaded" / "p2c" / "prefix-affinity" — that builds one
        # over this client's transport) replaces the hardcoded shared
        # agent topic with a per-call replica placement; None = the
        # pre-fleet behavior (shared topic, consumer-group balancing).
        # The router's lifecycle is owned here: close() stops it.
        if isinstance(router, str):
            from calfkit_tpu.fleet import FleetRouter

            router = FleetRouter(mesh, router)
        self.router = router
        self._hub = Hub()
        self._subscription: Subscription | None = None
        self._started = False
        self._closed = False
        self._owns_mesh = False  # connect() sets it for url-built transports
        self._start_lock: asyncio.Lock | None = None
        self._mesh_view: Any = None
        self._span_tasks: set[asyncio.Task] = set()  # in-flight span exports
        # in-flight fire-and-forget cancel publishes (hub._cancel_soon):
        # close() drains these too, or a caller exiting right after a
        # ClientTimeoutError would silently drop the mesh cancel
        self._cancel_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------------------- connect
    @classmethod
    def connect(
        cls,
        mesh: "MeshTransport | str | None" = None,
        *,
        client_id: str | None = None,
        default_timeout: float = DEFAULT_TIMEOUT,
        retry: "RetryPolicy | None" = None,
        router: Any = None,
    ) -> "Client":
        """Lazy constructor: performs no I/O (reference: caller.py:102).

        ``mesh`` may be a transport object, a url string
        (``tcp://host:port`` / ``kafka://host:port``), or None to read
        ``$CALFKIT_MESH_URL``.  A transport built here from a url is OWNED
        by the client: ``close()`` stops it.
        """
        from calfkit_tpu.mesh.urls import resolve_mesh

        transport, owned = resolve_mesh(mesh, allow_memory=False)
        client = cls(
            transport, client_id=client_id, default_timeout=default_timeout,
            retry=retry, router=router,
        )
        client._owns_mesh = owned
        return client

    async def _ensure_started(self) -> None:
        if self._closed:
            raise ClientClosedError("client is closed")
        if self._started:
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if self._started:
                return
            await self.mesh.start()
            await self.mesh.ensure_topics([self.inbox_topic])
            # inbox must be consuming BEFORE any call publishes
            self._subscription = await self.mesh.subscribe(
                [self.inbox_topic],
                self._hub.on_record,
                group_id=None,
                from_latest=False,
                ordered=False,
            )
            self._started = True

    async def close(self) -> None:
        self._closed = True
        pending = {
            t
            for t in (*self._span_tasks, *self._cancel_tasks)
            if not t.done()
        }
        if pending:
            # give in-flight fire-and-forget span exports and cancel
            # publishes a brief window to land before the mesh stops (the
            # root span has no ring-to-topic fallback; a dropped cancel
            # leaves downstream engines decoding for a dead caller);
            # stragglers are dropped, not awaited
            with contextlib.suppress(Exception):
                await asyncio.wait(pending, timeout=2.0)
        if self._subscription is not None:
            with contextlib.suppress(Exception):
                await self._subscription.stop()
            self._subscription = None
        if self.router is not None:
            # the router's registry holds a table reader on this client's
            # transport: stop it before the transport goes away
            with contextlib.suppress(Exception):
                await self.router.stop()
        if self._owns_mesh:
            # connect() built this transport from a url: stop it too, or a
            # per-job client would leak sockets and reader tasks
            with contextlib.suppress(Exception):
                await self.mesh.stop()

    async def __aenter__(self) -> "Client":
        await self._ensure_started()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    # ------------------------------------------------------------- agents
    def agent(
        self, name: str, *, output_type: type[OutputT] = str
    ) -> "AgentGateway[OutputT]":
        return AgentGateway(self, name, output_type)

    # ---------------------------------------------------------------- mesh
    @property
    def mesh_directory(self) -> Any:
        """The read-only directory of live agents/capabilities
        (``client.mesh`` in the reference; named ``mesh_directory`` here
        because ``.mesh`` is the transport)."""
        if self._mesh_view is None:
            from calfkit_tpu.client.mesh import Mesh

            self._mesh_view = Mesh(self)
        return self._mesh_view

    # ------------------------------------------------------------ firehose
    def events(self, *, buffer: int = 1024) -> EventStream:
        """Every step event this client observes, across all runs.

        ``stream.close()`` detaches the tap from the hub."""
        stream = EventStream(buffer=buffer, on_close=self._hub.remove_tap)
        self._hub.add_tap(stream)
        return stream

    # ------------------------------------------------------------ internal
    async def _publish_cancel(
        self, target_topic: str, correlation_id: str, task_id: str
    ) -> None:
        """Publish the run's ``cancel`` record (ISSUE 5): pure headers, no
        body, keyed like the call so it rides the same ordered lane.  Any
        node on the target topic fans it out to in-process cancellation
        targets (engines) — a timed-out caller stops burning TPU
        dispatches instead of merely stopping to listen."""
        headers = {
            protocol.HDR_EMITTER: protocol.emitter_header(
                "client", self.client_id
            ),
            protocol.HDR_KIND: "cancel",
            protocol.HDR_TASK: task_id,
            protocol.HDR_CORRELATION: correlation_id,
        }
        await self.mesh.publish(
            target_topic, b"", key=partition_key(task_id), headers=headers
        )

    async def _publish_call(
        self,
        target_topic: str,
        parts: list[ContentPart],
        *,
        route: str,
        correlation_id: str,
        task_id: str,
        state: State,
        deps: dict[str, Any],
        deadline: float | None = None,
    ) -> None:
        from calfkit_tpu.observability.trace import TRACER

        envelope = Envelope(
            context=SessionContext(state=state, deps=deps),
            workflow=WorkflowState(
                frames=[
                    CallFrame(
                        target_topic=target_topic,
                        callback_topic=self.inbox_topic,
                        route=route,
                        payload=parts,
                        caller_kind="client",
                        caller_name=self.client_id,
                    )
                ]
            ),
        )
        # the trace root: trace_id == correlation_id by convention, so
        # `ck trace <correlation-id>` needs no id mapping
        span = TRACER.start_span(
            "client.dispatch",
            trace_id=correlation_id,
            kind="client",
            emitter=protocol.emitter_header("client", self.client_id),
            attrs={"target_topic": target_topic, "route": route},
        )
        headers = {
            protocol.HDR_EMITTER: protocol.emitter_header("client", self.client_id),
            protocol.HDR_KIND: "call",
            protocol.HDR_WIRE: "envelope",
            protocol.HDR_ROUTE: route,
            protocol.HDR_TASK: task_id,
            protocol.HDR_CORRELATION: correlation_id,
            **span.context.headers(),
        }
        if deadline is not None:
            # the mesh deadline: minted once from the caller's timeout,
            # forwarded absolute by every hop (protocol.HDR_DEADLINE)
            headers[protocol.HDR_DEADLINE] = protocol.format_deadline(deadline)
        try:
            await self.mesh.publish(
                target_topic,
                envelope.to_wire(),
                key=partition_key(task_id),
                headers=headers,
            )
        except BaseException as exc:
            span.end(
                status="cancelled"
                if isinstance(exc, asyncio.CancelledError)
                else "error"
            )
            raise
        record = span.end()
        if record is not None:
            # best-effort span export, FIRE-AND-FORGET (shared helper):
            # an awaited publish here would add a full broker round-trip
            # to every client call; close() drains stragglers briefly
            from calfkit_tpu.observability.trace import publish_spans_soon

            publish_spans_soon(self.mesh.publish, [record], self._span_tasks)


class AgentGateway(Generic[OutputT]):
    """Typed per-agent verbs (reference: client/gateway.py:32-120)."""

    def __init__(self, client: Client, name: str, output_type: type[OutputT]):
        self._client = client
        self.name = name
        self.output_type = output_type
        self.input_topic = protocol.agent_input_topic(name)

    def _build_state(
        self, message_history: list[ModelMessage] | None
    ) -> State:
        return State(message_history=list(message_history or []))

    @staticmethod
    def _as_parts(prompt: str | list[ContentPart]) -> list[ContentPart]:
        if isinstance(prompt, str):
            return [TextPart(text=prompt)]
        return list(prompt)

    # the affinity key only ever reads the page-aligned head (64-char
    # pages × 4 max pages — see fleet/policy.py); collecting more would
    # copy a whole long-history prompt per routed call for nothing
    _AFFINITY_TEXT_CAP = 256

    @classmethod
    def _prompt_text(cls, parts: list[ContentPart]) -> str:
        """The prompt's text-projection HEAD, for affinity hashing only."""
        out: list[str] = []
        length = 0
        for p in parts:
            text = getattr(p, "text", "") or ""
            if not text:
                continue
            out.append(text[: cls._AFFINITY_TEXT_CAP - length])
            length += len(out[-1])
            if length >= cls._AFFINITY_TEXT_CAP:
                break
        return "".join(out)

    async def _route_topic(
        self,
        parts: list[ContentPart],
        correlation_id: str,
        exclude_replicas: "frozenset[str]",
    ) -> "tuple[str, Any]":
        """The engine/topic-selection seam (ISSUE 7): with a fleet
        router on the client, each call is placed on a specific
        replica's addressed topic; without one (or with no eligible
        replica) the shared agent topic load-balances as before.
        Returns ``(topic, Replica | None)``."""
        router = self._client.router
        if router is None:
            return self.input_topic, None
        route = await router.route(
            self.name,
            prompt_text=self._prompt_text(parts),
            correlation_id=correlation_id,
            exclude=exclude_replicas,
        )
        return route.topic, route.replica

    async def start(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        timeout: float | None = None,
        exclude_replicas: "frozenset[str]" = frozenset(),
    ) -> InvocationHandle[OutputT]:
        """Begin a run; returns a handle (reference: gateway.py:70).

        The effective timeout also mints the run's ``x-mesh-deadline``
        (absolute epoch), and the handle carries a cancel hook: a timeout
        (or an explicit ``handle.cancel()``) publishes a mesh ``cancel``
        record so downstream engines abandon the run's work.

        ``exclude_replicas`` (fleet-routed clients only) bars specific
        replica instances from this placement — the shed-retry loop in
        :meth:`execute` passes the instances that already refused.  The
        placement lands on ``handle.routed_replica`` (None = shared
        topic)."""
        client = self._client
        await client._ensure_started()
        correlation_id = new_id()
        task_id = new_id()
        effective_timeout = (
            timeout if timeout is not None else client.default_timeout
        )
        parts = self._as_parts(prompt)
        # place BEFORE minting the deadline: the first routed call may
        # pay the registry's table catch-up (seconds on a slow broker),
        # and that setup cost must not be charged against the caller's
        # serving budget — an expired-at-publish call would fault
        # non-retriable DeadlineExceeded for work that never started
        target_topic, routed = await self._route_topic(
            parts, correlation_id, exclude_replicas
        )
        routed_replica = routed.instance_id if routed is not None else None
        deadline = (
            cancellation.wall_clock() + effective_timeout
            if effective_timeout is not None
            else None
        )

        async def publish_cancel() -> None:
            # the cancel follows the CALL's placement: a replica-routed
            # run is abandoned on the replica's topic
            await client._publish_cancel(
                target_topic, correlation_id, task_id
            )

        # register BEFORE publish: the reply cannot beat the handle
        channel = client._hub.track(correlation_id, task_id)
        handle: InvocationHandle[OutputT] = InvocationHandle(
            channel,
            self.output_type,
            default_timeout=effective_timeout,
            on_abandon=publish_cancel,
            task_registry=client._cancel_tasks,
        )
        handle.routed_replica = routed_replica
        router = client.router if routed is not None else None
        if router is not None:
            # least-request accounting, keyed by the FULL replica key
            # (instance ids may be operator-pinned and collide across
            # agents): the router counts this run against the replica
            # until its terminal reply lands (TTL sweep covers terminals
            # that never arrive)
            replica_key = routed.key
            router.note_dispatch(replica_key, correlation_id)
            channel.terminal.add_done_callback(
                lambda _f, r=router, k=replica_key, c=correlation_id: (
                    r.note_done(k, c)
                )
            )
        try:
            await client._publish_call(
                target_topic,
                parts,
                route=route,
                correlation_id=correlation_id,
                task_id=task_id,
                state=self._build_state(message_history),
                deps=deps or {},
                deadline=deadline,
            )
        except BaseException:
            # the call never reached the mesh: no terminal will resolve,
            # so uncharge the replica NOW — a phantom in-flight entry
            # would bias placement away from a healthy replica for the
            # whole TTL
            if router is not None:
                router.note_done(routed.key, correlation_id)
            raise
        return handle

    async def send(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
    ) -> str:
        """Fire-and-forget; returns the correlation id (reference:
        gateway.py 'send' — the fire token)."""
        handle = await self.start(
            prompt, message_history=message_history, deps=deps, route=route
        )
        return handle.correlation_id

    async def execute(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        timeout: float | None = None,
        retry: "RetryPolicy | None" = None,
    ) -> InvocationResult[OutputT]:
        """Run to a typed result.  With a :class:`RetryPolicy` (here or on
        the client), faults typed retriable — overload sheds, draining
        workers — are retried with jittered exponential backoff; each
        retry is a FRESH run (new correlation id, new deadline).  Timeouts
        and non-retriable faults surface immediately.

        Fleet-routed clients retry ``mesh.overloaded`` sheds against a
        DIFFERENT replica: the shed source's instance id is excluded from
        every subsequent attempt's placement (ISSUE 7), so a retry storm
        spreads across the fleet instead of hammering the replica that
        just refused."""
        policy = retry if retry is not None else self._client.retry
        attempts = policy.attempts if policy is not None else 1
        last: BaseException | None = None
        shed_sources: set[str] = set()
        for attempt in range(max(1, attempts)):
            if attempt:
                await asyncio.sleep(policy.delay(attempt - 1))
            handle = await self.start(
                prompt,
                message_history=message_history,
                deps=deps,
                route=route,
                timeout=timeout,
                exclude_replicas=frozenset(shed_sources),
            )
            try:
                return await handle.result()
            except NodeFaultError as exc:
                if policy is None or not RetryPolicy.retriable(exc):
                    raise
                last = exc
                if handle.routed_replica is not None:
                    # EVERY retriable fault excludes the replica that
                    # produced it, not just sheds: a hung replica
                    # faulting mesh.timeout would otherwise be re-picked
                    # deterministically (affinity re-homes there;
                    # fail-fast keeps it the least-loaded minimum) while
                    # a healthy replica sits idle
                    shed_sources.add(handle.routed_replica)
        assert last is not None
        raise last
