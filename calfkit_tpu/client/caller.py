"""The Client: the caller surface onto the mesh.

Reference: calfkit/client/caller.py:46-437 + gateway.py.  Semantics kept:

- ``Client.connect(...)`` is **lazy sync** — no I/O until first use;
- the inbox subscriber is consuming before the first call publishes;
- three verbs per agent: ``send`` (fire token), ``start`` (handle),
  ``execute`` (await result);
- handles register before publish (race-free);
- ``client.events()`` is the bounded drop-oldest firehose.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import random
import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, AsyncIterator, Callable, Generic, TypeVar

from calfkit_tpu import cancellation, protocol
from calfkit_tpu.exceptions import (
    RETRIABLE_FAULT_TYPES,
    ClientClosedError,
    ClientTimeoutError,
    NodeFaultError,
)
from calfkit_tpu.models.error_report import ErrorReport, FaultTypes

if TYPE_CHECKING:  # pragma: no cover
    from calfkit_tpu.fleet.failover import FailoverPolicy, StreamLedger
    from calfkit_tpu.models.step import StepEvent
from calfkit_tpu.keying import partition_key
from calfkit_tpu.mesh.transport import MeshTransport, Subscription
from calfkit_tpu.models.messages import ModelMessage
from calfkit_tpu.models.node_result import InvocationResult
from calfkit_tpu.models.payload import ContentPart, TextPart
from calfkit_tpu.models.session_context import (
    CallFrame,
    Envelope,
    SessionContext,
    WorkflowState,
    new_id,
)
from calfkit_tpu.models.state import State
from calfkit_tpu.client.events import EventStream
from calfkit_tpu.client.hub import Hub, InvocationHandle, RunCompleted
from calfkit_tpu.observability.runledger import RunLedger, publish_runs_soon

logger = logging.getLogger(__name__)

OutputT = TypeVar("OutputT")

DEFAULT_TIMEOUT = 60.0
# a leased run with no deadline still leaves the outstanding set
# eventually: the beat loop prunes it after this many seconds, so a
# dropped fire-and-forget terminal cannot pin heartbeats forever
_LEASE_RUN_FALLBACK_S = 3600.0


@dataclass(frozen=True)
class RetryPolicy:
    """Caller-side bounded retry with jittered exponential backoff
    (ISSUE 5) — applied by :meth:`AgentGateway.execute` to faults whose
    ``error_type`` is in :data:`RETRIABLE_FAULT_TYPES` (overload, drain,
    transient capability loss) and NOTHING else: a deadline fault means
    the budget is spent, a node error means the same call would fail the
    same way.

    Delays follow ``base_delay * multiplier**attempt`` capped at
    ``max_delay``, each multiplied by a jitter factor drawn uniformly
    from ``[1 - jitter, 1]``.  ``rng`` is a zero-arg callable returning
    a float in ``[0, 1)`` (default :func:`random.random`); pass e.g.
    ``random.Random(0).random`` for fully deterministic backoff (the
    chaos harness does)."""

    attempts: int = 3  # total tries (1 = no retry)
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # fraction of the delay the jitter may remove
    rng: "Callable[[], float] | None" = None

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        raw = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        draw = (self.rng or random.random)()
        return raw * (1.0 - self.jitter * draw)

    @staticmethod
    def retriable(exc: BaseException) -> bool:
        return (
            isinstance(exc, NodeFaultError)
            and exc.report.error_type in RETRIABLE_FAULT_TYPES
        )


class Client:
    def __init__(
        self,
        mesh: MeshTransport,
        *,
        client_id: str | None = None,
        default_timeout: float = DEFAULT_TIMEOUT,
        retry: "RetryPolicy | None" = None,
        router: Any = None,  # FleetRouter | policy name | None
        failover: "FailoverPolicy | None" = None,
        lease_ttl: "float | None" = None,
        priority: "str | None" = None,
    ):
        self.mesh = mesh
        self.client_id = client_id or uuid.uuid4().hex[:12]
        # multi-tenant QoS (ISSUE 20): this client's default priority
        # class ("interactive" | "batch"), stamped on every call as
        # x-mesh-priority unless a per-call class overrides it.  None =
        # no header — receivers resolve the absent class to the mesh
        # DEFAULT (interactive); "batch" is the explicit opt-in to
        # shed/reap/rate-limit FIRST under overload.
        self.priority = priority if priority in protocol.PRIORITY_CLASSES else None
        self.inbox_topic = protocol.client_inbox_topic(self.client_id)
        self.default_timeout = default_timeout
        # opt-in bounded retry for execute(): None = single attempt (the
        # pre-ISSUE-5 behavior; retries change at-most-once semantics for
        # non-idempotent agents, so the caller must choose them)
        self.retry = retry
        # opt-in fleet routing (ISSUE 7): a FleetRouter (or a policy name
        # — "least-loaded" / "p2c" / "prefix-affinity" — that builds one
        # over this client's transport) replaces the hardcoded shared
        # agent topic with a per-call replica placement; None = the
        # pre-fleet behavior (shared topic, consumer-group balancing).
        # The router's lifecycle is owned here: close() stops it.
        if isinstance(router, str):
            from calfkit_tpu.fleet import FleetRouter

            router = FleetRouter(mesh, router)
        self.router = router
        # opt-in in-flight failure recovery (ISSUE 9): with a router AND a
        # FailoverPolicy, execute()/stream() supervise each outstanding
        # placement against the dead-placement law and re-dispatch (fresh
        # correlation id, remaining deadline, dead replica excluded, old
        # correlation cancel-tombstoned) when the placed replica dies
        # mid-run.  None = calls ride their placement to the caller's
        # timeout, the pre-ISSUE-9 behavior.
        self.failover = failover
        # opt-in caller liveness lease (ISSUE 10): with a TTL set, every
        # call carries an ``x-mesh-lease`` header and — while any run is
        # outstanding — this client heartbeats the compacted
        # ``mesh.caller_liveness`` table at ttl/3.  Engines whose run's
        # lease lapses reap it server-side (typed ``mesh.orphaned``): the
        # recovery path that covers fire-and-forget ``send()``, which no
        # client-side supervisor can.  None = un-leased (pre-ISSUE-10):
        # a dead caller's runs burn until their deadline.
        self.lease_ttl = lease_ttl
        self._lease_id = uuid.uuid4().hex[:12] if lease_ttl else None
        self._lease_runs: dict[str, float] = {}  # corr -> prune-after epoch
        self._lease_task: "asyncio.Task | None" = None
        self._lease_writer: Any = None
        self._hub = Hub()
        if self._lease_id is not None:
            self._hub.on_terminal = self._note_run_terminal
        self._subscription: Subscription | None = None
        self._started = False
        self._closed = False
        self._owns_mesh = False  # connect() sets it for url-built transports
        self._start_lock: asyncio.Lock | None = None
        self._mesh_view: Any = None
        self._span_tasks: set[asyncio.Task] = set()  # in-flight span exports
        # run-scoped observability (ISSUE 17): the per-run attempt ledger
        # — every start() placement records here under its run id, the
        # execute()/stream() supervisors close runs with caller-visible
        # outcomes, and closed runs export fire-and-forget to the
        # compacted ``mesh.runs`` table (key = run_id)
        self.run_ledger = RunLedger()
        self._run_tasks: set[asyncio.Task] = set()  # in-flight run exports
        # in-flight fire-and-forget cancel publishes (hub._cancel_soon):
        # close() drains these too, or a caller exiting right after a
        # ClientTimeoutError would silently drop the mesh cancel
        self._cancel_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------------------- connect
    @classmethod
    def connect(
        cls,
        mesh: "MeshTransport | str | None" = None,
        *,
        client_id: str | None = None,
        default_timeout: float = DEFAULT_TIMEOUT,
        retry: "RetryPolicy | None" = None,
        router: Any = None,
        failover: "FailoverPolicy | None" = None,
        lease_ttl: "float | None" = None,
        priority: "str | None" = None,
    ) -> "Client":
        """Lazy constructor: performs no I/O (reference: caller.py:102).

        ``mesh`` may be a transport object, a url string
        (``tcp://host:port`` / ``kafka://host:port``), or None to read
        ``$CALFKIT_MESH_URL``.  A transport built here from a url is OWNED
        by the client: ``close()`` stops it.
        """
        from calfkit_tpu.mesh.urls import resolve_mesh

        transport, owned = resolve_mesh(mesh, allow_memory=False)
        client = cls(
            transport, client_id=client_id, default_timeout=default_timeout,
            retry=retry, router=router, failover=failover,
            lease_ttl=lease_ttl, priority=priority,
        )
        client._owns_mesh = owned
        return client

    async def _ensure_started(self) -> None:
        if self._closed:
            raise ClientClosedError("client is closed")
        if self._started:
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if self._started:
                return
            await self.mesh.start()
            await self.mesh.ensure_topics([self.inbox_topic])
            # run-record export (ISSUE 17): compacted by run id so the
            # latest (finished) record per run survives for `ck run` /
            # the worker-side SLO fold
            await self.mesh.ensure_topics(
                [protocol.RUNS_TOPIC], compacted=True
            )
            if self._lease_id is not None:
                await self.mesh.ensure_topics(
                    [protocol.CALLER_LIVENESS_TOPIC], compacted=True
                )
                self._lease_writer = self.mesh.table_writer(
                    protocol.CALLER_LIVENESS_TOPIC
                )
            # inbox must be consuming BEFORE any call publishes
            self._subscription = await self.mesh.subscribe(
                [self.inbox_topic],
                self._hub.on_record,
                group_id=None,
                from_latest=False,
                ordered=False,
            )
            # atomicity-ok: double-checked under _start_lock — the flag is
            # re-read inside the lock, so the stale outer read only costs
            # a lock acquire, never a double start
            self._started = True

    # ------------------------------------------------- caller liveness
    # (ISSUE 10) One lease per CLIENT process, not per run: the beat loop
    # publishes a compact record keyed by the lease id while any leased
    # run is outstanding, and close() releases the lease (tombstone) so
    # a clean departure orphans its leftovers immediately instead of
    # after a TTL of silence.

    @property
    def lease_id(self) -> "str | None":
        return self._lease_id

    def _lease_header(self) -> "str | None":
        if self._lease_id is None or self.lease_ttl is None:
            return None
        return protocol.format_lease(self._lease_id, self.lease_ttl)

    def _note_run_started(
        self, correlation_id: str, deadline: "float | None"
    ) -> None:
        """Count a leased run as outstanding (and start beating).  The
        prune horizon bounds fire-and-forget runs whose terminal nobody
        awaits: the run's own deadline when it has one, else a fallback
        — a dropped terminal must not pin heartbeats forever."""
        if self._lease_id is None:
            return
        prune_at = (
            deadline
            if deadline is not None
            else cancellation.wall_clock() + _LEASE_RUN_FALLBACK_S
        )
        self._lease_runs[correlation_id] = prune_at
        if self._lease_task is None or self._lease_task.done():
            self._lease_task = asyncio.get_running_loop().create_task(
                self._beat_lease(), name="caller-lease-heartbeat"
            )

    def _note_run_terminal(self, correlation_id: str) -> None:
        """Hub hook: ANY terminal reply (including one for a dropped
        fire-and-forget handle) retires the run from the outstanding
        set — the beat loop goes quiet once the set empties."""
        self._lease_runs.pop(correlation_id, None)

    # ------------------------------------------- run ledger (ISSUE 17)
    # One run id per logical execute()/stream() call, minted once and
    # carried verbatim across every retry/failover/hedge/resume
    # placement.  The ledger is telemetry: every fold here is fail-open
    # and first-signal-wins, and a lost export degrades to client-local
    # ``handle.run_report()`` visibility only.

    def _record_attempt_terminal(
        self,
        run_id: str,
        correlation_id: str,
        fut: "asyncio.Future",
        *,
        finish: bool = False,
    ) -> None:
        """Terminal-future hook: fold one attempt's terminal into the
        ledger.  Typed mapping: return → ok; ``mesh.overloaded`` → shed;
        ``mesh.cancelled``/``mesh.orphaned`` → cancelled; any other
        fault → fault (with its error type).

        ``finish=True`` means no supervisor owns this run (a bare
        ``start()``/``send()`` minted the id itself): the attempt's
        terminal IS the run's terminal, so close the run and export it
        — otherwise an un-supervised run would sit ``pending`` forever
        and never reach ``mesh.runs``."""
        if fut.cancelled():
            return
        terminal = fut.result()
        now = cancellation.wall_clock()
        if isinstance(terminal, RunCompleted):
            self.run_ledger.note_outcome(
                run_id, correlation_id, outcome="ok", finished_at=now
            )
            if finish:
                self._finish_run_soon(run_id, outcome="ok")
            return
        error_type = str(getattr(terminal.report, "error_type", "") or "")
        if error_type == FaultTypes.OVERLOADED:
            outcome = "shed"
        elif error_type in (FaultTypes.CANCELLED, FaultTypes.ORPHANED):
            outcome = "cancelled"
        else:
            outcome = "fault"
        self.run_ledger.note_outcome(
            run_id,
            correlation_id,
            outcome=outcome,
            error_type=error_type,
            finished_at=now,
        )
        if finish:
            self._finish_run_soon(
                run_id, outcome=outcome, error_type=error_type
            )

    def _note_attempt_superseded(
        self, run_id: "str | None", handle: Any, reason: str
    ) -> None:
        """Supervisor verdict: this placement was abandoned (dead
        replica, losing hedge) — its terminal may never arrive, so the
        supervisor records the outcome itself.  First-signal-wins in the
        ledger: if a real terminal already landed, this drops."""
        if not run_id:
            return
        self.run_ledger.note_outcome(
            run_id,
            handle.correlation_id,
            outcome="superseded",
            error_type=reason,
            finished_at=cancellation.wall_clock(),
        )

    def _finish_run_soon(
        self, run_id: "str | None", *, outcome: str, error_type: str = ""
    ) -> None:
        """Close the run with its CALLER-visible outcome and export the
        record to ``mesh.runs`` fire-and-forget (the span-export
        pattern: close() drains stragglers briefly)."""
        if not run_id:
            return
        self.run_ledger.finish_run(
            run_id,
            outcome=outcome,
            error_type=error_type,
            finished_at=cancellation.wall_clock(),
        )
        record = self.run_ledger.export_record(run_id)
        if record is not None:
            publish_runs_soon(self.mesh.publish, [record], self._run_tasks)

    def _finish_run_exc(
        self, run_id: "str | None", exc: BaseException
    ) -> None:
        """Close the run from the exception surfacing to the caller."""
        if isinstance(exc, ClientTimeoutError):
            self._finish_run_soon(run_id, outcome="timeout")
        elif isinstance(exc, (asyncio.CancelledError, GeneratorExit)):
            self._finish_run_soon(run_id, outcome="cancelled")
        elif isinstance(exc, NodeFaultError):
            self._finish_run_soon(
                run_id,
                outcome="fault",
                error_type=str(exc.report.error_type or ""),
            )
        else:
            self._finish_run_soon(
                run_id, outcome="fault", error_type=type(exc).__name__
            )

    def _prune_lease_runs(self) -> None:
        """Drop runs past their prune horizon — UNLESS the caller still
        holds a live handle (the hub's weak channel map answers that):
        the fallback horizon exists for dropped fire-and-forget
        terminals, and silently stopping heartbeats under an
        un-deadlined run somebody is actively awaiting would make the
        engine orphan a LIVE caller's run.  Awaited runs re-arm."""
        now = cancellation.wall_clock()
        for corr, at in list(self._lease_runs.items()):
            if at > now:
                continue
            if self._hub._channels.get(corr) is not None:
                # handle still alive: the caller is awaiting — keep
                # beating and push the horizon out another window
                self._lease_runs[corr] = now + _LEASE_RUN_FALLBACK_S
            else:
                del self._lease_runs[corr]

    async def _beat_lease(self) -> None:
        """Publish caller heartbeats at ttl/3 while runs are outstanding.
        Per-beat resilient (a flaky broker logs and retries next tick —
        the engine grants a full TTL of grace); exits when the
        outstanding set drains, restarted by the next leased start()."""
        assert self.lease_ttl is not None and self._lease_id is not None
        from calfkit_tpu import leases

        interval = max(0.02, self.lease_ttl / 3.0)
        while not self._closed:
            self._prune_lease_runs()
            if not self._lease_runs:
                return
            try:
                await self._lease_writer.put(
                    self._lease_id,
                    leases.beat_payload(self._lease_id, self.lease_ttl),
                )
            except Exception:  # noqa: BLE001 - per-beat resilience
                logger.warning(
                    "caller lease beat failed (retrying next tick)",
                    exc_info=True,
                )
            await asyncio.sleep(interval)

    async def _release_lease(self) -> None:
        """Clean departure: stop beating and tombstone the lease —
        outstanding leased runs become orphans NOW (the server-side
        reaper grants no TTL grace to a deliberate close)."""
        if self._lease_task is not None:
            self._lease_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._lease_task
            self._lease_task = None
        if self._lease_writer is not None and self._lease_id is not None:
            with contextlib.suppress(Exception):
                await asyncio.wait_for(
                    self._lease_writer.tombstone(self._lease_id), 2.0
                )

    async def close(self) -> None:
        self._closed = True
        await self._release_lease()
        pending = {
            t
            for t in (*self._span_tasks, *self._run_tasks, *self._cancel_tasks)
            if not t.done()
        }
        if pending:
            # give in-flight fire-and-forget span exports and cancel
            # publishes a brief window to land before the mesh stops (the
            # root span has no ring-to-topic fallback; a dropped cancel
            # leaves downstream engines decoding for a dead caller);
            # stragglers are dropped, not awaited
            with contextlib.suppress(Exception):
                await asyncio.wait(pending, timeout=2.0)
        if self._subscription is not None:
            with contextlib.suppress(Exception):
                await self._subscription.stop()
            self._subscription = None
        if self.router is not None:
            # the router's registry holds a table reader on this client's
            # transport: stop it before the transport goes away
            with contextlib.suppress(Exception):
                await self.router.stop()
        if self._owns_mesh:
            # connect() built this transport from a url: stop it too, or a
            # per-job client would leak sockets and reader tasks
            with contextlib.suppress(Exception):
                await self.mesh.stop()

    async def __aenter__(self) -> "Client":
        await self._ensure_started()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    # ------------------------------------------------------------- agents
    def agent(
        self, name: str, *, output_type: type[OutputT] = str
    ) -> "AgentGateway[OutputT]":
        return AgentGateway(self, name, output_type)

    # ---------------------------------------------------------------- mesh
    @property
    def mesh_directory(self) -> Any:
        """The read-only directory of live agents/capabilities
        (``client.mesh`` in the reference; named ``mesh_directory`` here
        because ``.mesh`` is the transport)."""
        if self._mesh_view is None:
            from calfkit_tpu.client.mesh import Mesh

            self._mesh_view = Mesh(self)
        return self._mesh_view

    # ------------------------------------------------------------ firehose
    def events(self, *, buffer: int = 1024) -> EventStream:
        """Every step event this client observes, across all runs.

        ``stream.close()`` detaches the tap from the hub."""
        stream = EventStream(buffer=buffer, on_close=self._hub.remove_tap)
        self._hub.add_tap(stream)
        return stream

    # ------------------------------------------------------------ internal
    async def _publish_cancel(
        self, target_topic: str, correlation_id: str, task_id: str
    ) -> None:
        """Publish the run's ``cancel`` record (ISSUE 5): pure headers, no
        body, keyed like the call so it rides the same ordered lane.  Any
        node on the target topic fans it out to in-process cancellation
        targets (engines) — a timed-out caller stops burning TPU
        dispatches instead of merely stopping to listen."""
        headers = {
            protocol.HDR_EMITTER: protocol.emitter_header(
                "client", self.client_id
            ),
            protocol.HDR_KIND: "cancel",
            protocol.HDR_TASK: task_id,
            protocol.HDR_CORRELATION: correlation_id,
        }
        await self.mesh.publish(
            target_topic, b"", key=partition_key(task_id), headers=headers
        )

    async def _publish_call(
        self,
        target_topic: str,
        parts: list[ContentPart],
        *,
        route: str,
        correlation_id: str,
        task_id: str,
        state: State,
        deps: dict[str, Any],
        deadline: float | None = None,
        attempt: str | None = None,
        run: str | None = None,
        priority: str | None = None,
    ) -> None:
        from calfkit_tpu.observability.trace import TRACER

        envelope = Envelope(
            context=SessionContext(state=state, deps=deps),
            workflow=WorkflowState(
                frames=[
                    CallFrame(
                        target_topic=target_topic,
                        callback_topic=self.inbox_topic,
                        route=route,
                        payload=parts,
                        caller_kind="client",
                        caller_name=self.client_id,
                    )
                ]
            ),
        )
        # the trace root: trace_id == correlation_id by convention, so
        # `ck trace <correlation-id>` needs no id mapping
        span = TRACER.start_span(
            "client.dispatch",
            trace_id=correlation_id,
            kind="client",
            emitter=protocol.emitter_header("client", self.client_id),
            attrs={"target_topic": target_topic, "route": route},
        )
        headers = {
            protocol.HDR_EMITTER: protocol.emitter_header("client", self.client_id),
            protocol.HDR_KIND: "call",
            protocol.HDR_WIRE: "envelope",
            protocol.HDR_ROUTE: route,
            protocol.HDR_TASK: task_id,
            protocol.HDR_CORRELATION: correlation_id,
            **span.context.headers(),
        }
        if deadline is not None:
            # the mesh deadline: minted once from the caller's timeout,
            # forwarded absolute by every hop (protocol.HDR_DEADLINE)
            headers[protocol.HDR_DEADLINE] = protocol.format_deadline(deadline)
        lease = self._lease_header()
        if lease is not None:
            # the caller liveness lease (ISSUE 10): forwarded by every
            # hop like the deadline — downstream work runs on the
            # ORIGINAL caller's behalf and dies with its lease
            headers[protocol.HDR_LEASE] = lease
        if attempt:
            # failure recovery (ISSUE 9): "failover" | "hedge" — this
            # placement only, counted by the serving agent's advert
            headers[protocol.HDR_ATTEMPT] = attempt
        if run is not None:
            # run identity (ISSUE 17): "<run_id>:<attempt_no>", minted
            # once per logical execute()/stream() call and carried
            # VERBATIM across retry/failover/hedge/resume re-dispatches;
            # forwarded by every downstream hop (unlike x-mesh-attempt).
            # A corrupt value degrades to an un-linked run — never
            # faults delivery (the PR 5 law)
            headers[protocol.HDR_RUN] = run
        if priority in protocol.PRIORITY_CLASSES:
            # priority class (ISSUE 20): forwarded by every hop like the
            # deadline/lease — downstream work degrades as THIS caller's
            # class.  Absent = the mesh default; corrupt parses degrade,
            # never fault (the PR 5 law)
            headers[protocol.HDR_PRIORITY] = protocol.format_priority(priority)
        try:
            await self.mesh.publish(
                target_topic,
                envelope.to_wire(),
                key=partition_key(task_id),
                headers=headers,
            )
        except BaseException as exc:
            span.end(
                status="cancelled"
                if isinstance(exc, asyncio.CancelledError)
                else "error"
            )
            raise
        record = span.end()
        if record is not None:
            # best-effort span export, FIRE-AND-FORGET (shared helper):
            # an awaited publish here would add a full broker round-trip
            # to every client call; close() drains stragglers briefly
            from calfkit_tpu.observability.trace import publish_spans_soon

            publish_spans_soon(self.mesh.publish, [record], self._span_tasks)


class AgentGateway(Generic[OutputT]):
    """Typed per-agent verbs (reference: client/gateway.py:32-120)."""

    def __init__(self, client: Client, name: str, output_type: type[OutputT]):
        self._client = client
        self.name = name
        self.output_type = output_type
        self.input_topic = protocol.agent_input_topic(name)

    def _build_state(
        self, message_history: list[ModelMessage] | None
    ) -> State:
        return State(message_history=list(message_history or []))

    @staticmethod
    def _as_parts(prompt: str | list[ContentPart]) -> list[ContentPart]:
        if isinstance(prompt, str):
            return [TextPart(text=prompt)]
        return list(prompt)

    # the affinity key only ever reads the page-aligned head (64-char
    # pages × 4 max pages — see fleet/policy.py); collecting more would
    # copy a whole long-history prompt per routed call for nothing
    _AFFINITY_TEXT_CAP = 256

    @classmethod
    def _prompt_text(cls, parts: list[ContentPart]) -> str:
        """The prompt's text-projection HEAD, for affinity hashing only."""
        out: list[str] = []
        length = 0
        for p in parts:
            text = getattr(p, "text", "") or ""
            if not text:
                continue
            out.append(text[: cls._AFFINITY_TEXT_CAP - length])
            length += len(out[-1])
            if length >= cls._AFFINITY_TEXT_CAP:
                break
        return "".join(out)

    async def _route_topic(
        self,
        parts: list[ContentPart],
        correlation_id: str,
        exclude_replicas: "frozenset[str]",
    ) -> "tuple[str, Any]":
        """The engine/topic-selection seam (ISSUE 7): with a fleet
        router on the client, each call is placed on a specific
        replica's addressed topic; without one (or with no eligible
        replica) the shared agent topic load-balances as before.
        Returns ``(topic, Replica | None)``."""
        router = self._client.router
        if router is None:
            return self.input_topic, None
        route = await router.route(
            self.name,
            prompt_text=self._prompt_text(parts),
            correlation_id=correlation_id,
            exclude=exclude_replicas,
        )
        return route.topic, route.replica

    async def start(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        timeout: float | None = None,
        exclude_replicas: "frozenset[str]" = frozenset(),
        mark: "str | None" = None,
        run_id: "str | None" = None,
        attempt_no: int = 0,
        attempt_kind: str = "first",
        priority: "str | None" = None,
    ) -> InvocationHandle[OutputT]:
        """Begin a run; returns a handle (reference: gateway.py:70).

        The effective timeout also mints the run's ``x-mesh-deadline``
        (absolute epoch), and the handle carries a cancel hook: a timeout
        (or an explicit ``handle.cancel()``) publishes a mesh ``cancel``
        record so downstream engines abandon the run's work.

        ``exclude_replicas`` (fleet-routed clients only) bars specific
        replica instances from this placement — the shed-retry loop in
        :meth:`execute` passes the instances that already refused.  The
        placement lands on ``handle.routed_replica`` /
        ``handle.routed_replica_key`` (None = shared topic).  ``mark``
        stamps the call's ``x-mesh-attempt`` header ("failover" |
        "hedge", ISSUE 9) so the serving replica's advert counts
        recovery arrivals.

        ``run_id``/``attempt_no``/``attempt_kind`` (ISSUE 17) are the
        run identity: minted here for a bare ``start()``/``send()``,
        passed in by the execute()/stream() supervisors so every
        retry/failover/hedge/resume placement lands in ONE ledger entry
        and carries the same ``x-mesh-run`` header."""
        client = self._client
        await client._ensure_started()
        correlation_id = new_id()
        task_id = new_id()
        effective_timeout = (
            timeout if timeout is not None else client.default_timeout
        )
        parts = self._as_parts(prompt)
        # place BEFORE minting the deadline: the first routed call may
        # pay the registry's table catch-up (seconds on a slow broker),
        # and that setup cost must not be charged against the caller's
        # serving budget — an expired-at-publish call would fault
        # non-retriable DeadlineExceeded for work that never started
        target_topic, routed = await self._route_topic(
            parts, correlation_id, exclude_replicas
        )
        routed_replica = routed.instance_id if routed is not None else None
        now = cancellation.wall_clock()
        deadline = (
            now + effective_timeout if effective_timeout is not None else None
        )

        async def publish_cancel() -> None:
            # the cancel follows the CALL's placement: a replica-routed
            # run is abandoned on the replica's topic
            await client._publish_cancel(
                target_topic, correlation_id, task_id
            )

        # register BEFORE publish: the reply cannot beat the handle
        channel = client._hub.track(correlation_id, task_id)
        # caller liveness (ISSUE 10): the run joins the lease's
        # outstanding set BEFORE publish (heartbeats must be flowing by
        # the time the engine registers the run); the hub's terminal
        # hook retires it — even for a dropped fire-and-forget handle
        client._note_run_started(correlation_id, deadline)
        handle: InvocationHandle[OutputT] = InvocationHandle(
            channel,
            self.output_type,
            default_timeout=effective_timeout,
            on_abandon=publish_cancel,
            task_registry=client._cancel_tasks,
        )
        handle.routed_replica = routed_replica
        handle.routed_replica_key = routed.key if routed is not None else None
        # run ledger (ISSUE 17): record the attempt BEFORE publish (the
        # terminal callback below may fire the moment the reply lands),
        # and fold its terminal in when it does — first signal wins, so
        # a supervisor's later "superseded" verdict never clobbers a
        # real outcome (or vice versa)
        # a bare start()/send() owns the run it mints; execute()/stream()
        # supervisors pass run_id in and close the run themselves
        owns_run = run_id is None
        run_id = run_id or new_id()
        # the run's EFFECTIVE class (per-call override, else the client
        # default, else the default class): stamped on the wire header
        # below AND on the run record, so `ck slo` can fold per class
        effective_priority = (
            priority
            if priority in protocol.PRIORITY_CLASSES
            else client.priority
        )
        client.run_ledger.begin_run(
            run_id,
            agent=self.name,
            client_id=client.client_id,
            started_at=now,
            priority=effective_priority or protocol.DEFAULT_PRIORITY,
        )
        client.run_ledger.note_attempt(
            run_id,
            attempt_no=attempt_no,
            correlation_id=correlation_id,
            kind=attempt_kind,
            placement=routed.key if routed is not None else "",
            agent=self.name,
            started_at=now,
        )
        handle.run_id = run_id
        handle._run_ledger = client.run_ledger
        channel.terminal.add_done_callback(
            lambda f, r=run_id, c=correlation_id, fin=owns_run: (
                client._record_attempt_terminal(r, c, f, finish=fin)
            )
        )
        router = client.router if routed is not None else None
        if router is not None:
            # least-request accounting, keyed by the FULL replica key
            # (instance ids may be operator-pinned and collide across
            # agents): the router counts this run against the replica
            # until its terminal reply lands (TTL sweep covers terminals
            # that never arrive)
            replica_key = routed.key
            router.note_dispatch(replica_key, correlation_id)
            channel.terminal.add_done_callback(
                lambda _f, r=router, k=replica_key, c=correlation_id: (
                    r.note_done(k, c)
                )
            )
        try:
            await client._publish_call(
                target_topic,
                parts,
                route=route,
                correlation_id=correlation_id,
                task_id=task_id,
                state=self._build_state(message_history),
                deps=deps or {},
                deadline=deadline,
                attempt=mark,
                run=protocol.format_run(run_id, attempt_no),
                priority=effective_priority,
            )
        except BaseException:
            # the call never reached the mesh: no terminal will resolve,
            # so uncharge the replica NOW — a phantom in-flight entry
            # would bias placement away from a healthy replica for the
            # whole TTL — and retire the run from the lease's
            # outstanding set (its terminal can never arrive)
            if router is not None:
                router.note_done(routed.key, correlation_id)
            client._note_run_terminal(correlation_id)
            raise
        return handle

    async def send(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        priority: "str | None" = None,
    ) -> str:
        """Fire-and-forget; returns the correlation id (reference:
        gateway.py 'send' — the fire token)."""
        handle = await self.start(
            prompt, message_history=message_history, deps=deps, route=route,
            priority=priority,
        )
        return handle.correlation_id

    async def execute(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        timeout: float | None = None,
        retry: "RetryPolicy | None" = None,
        failover: "FailoverPolicy | None" = None,
        priority: "str | None" = None,
    ) -> InvocationResult[OutputT]:
        """Run to a typed result.  With a :class:`RetryPolicy` (here or on
        the client), faults typed retriable — overload sheds, draining
        workers — are retried with jittered exponential backoff; each
        retry is a FRESH run (new correlation id, new deadline).  Timeouts
        and non-retriable faults surface immediately.

        Fleet-routed clients retry ``mesh.overloaded`` sheds against a
        DIFFERENT replica: the shed source's instance id is excluded from
        every subsequent attempt's placement (ISSUE 7), so a retry storm
        spreads across the fleet instead of hammering the replica that
        just refused.

        With a :class:`~calfkit_tpu.fleet.failover.FailoverPolicy` (here
        or on the client) on a fleet-routed client, the call is
        additionally SUPERVISED in flight (ISSUE 9): the placed replica's
        health is probed while awaiting the terminal, a dead placement
        (heartbeat lapsed, advert gone, unready without drain) is
        re-dispatched to a surviving replica under the REMAINING deadline
        with the old correlation cancel-tombstoned, and an optional
        ``hedge_after`` races a duplicate on a second replica — first
        terminal wins, the loser is cancelled."""
        policy = retry if retry is not None else self._client.retry
        fo = failover if failover is not None else self._client.failover
        client = self._client
        # run identity (ISSUE 17): ONE run id for the whole logical call
        # — every retry/failover/hedge placement below records into the
        # same ledger entry and carries the same x-mesh-run header
        run_id = new_id()
        if fo is not None and client.router is not None:
            try:
                result = await self._execute_failover(
                    prompt,
                    message_history=message_history,
                    deps=deps,
                    route=route,
                    timeout=timeout,
                    policy=policy,
                    failover=fo,
                    run_id=run_id,
                    priority=priority,
                )
            except BaseException as exc:
                client._finish_run_exc(run_id, exc)
                raise
            client._finish_run_soon(run_id, outcome="ok")
            return result
        attempts = policy.attempts if policy is not None else 1
        last: BaseException | None = None
        shed_sources: set[str] = set()
        try:
            for attempt in range(max(1, attempts)):
                if attempt:
                    await asyncio.sleep(policy.delay(attempt - 1))
                handle = await self.start(
                    prompt,
                    message_history=message_history,
                    deps=deps,
                    route=route,
                    timeout=timeout,
                    exclude_replicas=frozenset(shed_sources),
                    run_id=run_id,
                    attempt_no=attempt,
                    attempt_kind="first" if attempt == 0 else "retry",
                    priority=priority,
                )
                try:
                    result = await handle.result()
                except NodeFaultError as exc:
                    if policy is None or not RetryPolicy.retriable(exc):
                        raise
                    last = exc
                    if handle.routed_replica is not None:
                        # EVERY retriable fault excludes the replica that
                        # produced it, not just sheds: a hung replica
                        # faulting mesh.timeout would otherwise be re-picked
                        # deterministically (affinity re-homes there;
                        # fail-fast keeps it the least-loaded minimum) while
                        # a healthy replica sits idle
                        shed_sources.add(handle.routed_replica)
                    continue
                client._finish_run_soon(run_id, outcome="ok")
                return result
            assert last is not None
            raise last
        except BaseException as exc:
            client._finish_run_exc(run_id, exc)
            raise

    # ================================================== failure recovery
    # (ISSUE 9; laws in calfkit_tpu/fleet/failover.py, docs/robustness.md
    # "Failure recovery")

    @staticmethod
    async def _first_terminal(
        handles: "list[InvocationHandle]", timeout: float
    ) -> "InvocationHandle | None":
        """Park until the FIRST of ``handles`` lands a terminal, or
        ``timeout`` (one probe tick) elapses — whichever is sooner.
        Returns the finished handle, or None on a quiet tick.

        ``timeout <= 0`` is the busy-poll mode (the fleet simulator's
        deterministic probing: a yield, not a timer) — one bare
        event-loop yield instead of waiter-task churn, because hundreds
        of outstanding supervised calls each allocating tasks per tick
        is the difference between a simulation step and a stall."""
        for handle in handles:
            if handle.terminal_arrived:
                return handle
        if timeout is not None and timeout <= 0:
            await asyncio.sleep(0)
            for handle in handles:
                if handle.terminal_arrived:
                    return handle
            return None
        waiters = [
            asyncio.ensure_future(h.wait(timeout)) for h in handles
        ]
        try:
            await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for w in waiters:
                w.cancel()
        for handle in handles:
            if handle.terminal_arrived:
                return handle
        return None

    async def _await_placement(
        self,
        exclude: "frozenset[str]",
        *,
        probe_interval: float,
        remaining: "Callable[[], float | None]",
    ) -> None:
        """Park until the router can place a call on SOME eligible
        replica outside ``exclude`` (a dead fleet usually means one
        heartbeat interval of waiting — a replica re-advertises or a
        fresh one boots), bounded by the remaining budget."""
        router = self._client.router
        while router.select(self.name, exclude=exclude) is None:
            rem = remaining()
            if rem is not None and rem <= 0:
                raise ClientTimeoutError(
                    "no eligible replica for the failover re-dispatch "
                    "within the remaining budget"
                )
            await asyncio.sleep(
                probe_interval if rem is None else min(probe_interval, rem)
            )

    def _no_placement_fault(self, reason: str) -> NodeFaultError:
        """The typed, RETRIABLE fault raised when a run keeps losing its
        placements past the failover budget: the fleet cannot currently
        hold this call — the caller may back off and try again."""
        return NodeFaultError(
            ErrorReport.build_safe(
                FaultTypes.CAPABILITY_UNAVAILABLE,
                f"run lost its placement ({reason}) and the failover "
                "budget is spent; the fleet cannot hold this call "
                "right now",
            )
        )

    async def _execute_failover(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None,
        deps: dict[str, Any] | None,
        route: str,
        timeout: float | None,
        policy: "RetryPolicy | None",
        failover: "FailoverPolicy",
        run_id: "str | None" = None,
        priority: "str | None" = None,
    ) -> InvocationResult[OutputT]:
        """The supervised execute: one absolute budget, N placements.

        The loop holds one PRIMARY handle (plus at most one HEDGE) and
        alternates between waiting for a terminal and probing each
        outstanding placement against the dead-placement law.  Every
        re-dispatch runs under the REMAINING budget (the mesh deadline is
        absolute), a fresh correlation id, and the accumulated exclusion
        set (shed sources AND dead replicas — one set, so a failover
        never re-picks a replica that already refused, and a shed retry
        never lands on a corpse)."""
        client = self._client
        router = client.router
        effective = timeout if timeout is not None else client.default_timeout
        deadline = (
            cancellation.wall_clock() + effective
            if effective is not None else None
        )
        exclude: set[str] = set()
        failovers = 0
        fault_attempts = 1  # terminals consumed (the original counts)
        max_fault_attempts = max(1, policy.attempts) if policy else 1
        run_id = run_id or new_id()
        attempt_no = 0  # ledger attempt counter (every placement)

        def remaining() -> "float | None":
            if deadline is None:
                return None
            return deadline - cancellation.wall_clock()

        async def dispatch(
            mark: "str | None",
            extra_exclude: "frozenset[str]" = frozenset(),
        ) -> InvocationHandle[OutputT]:
            rem = remaining()
            if rem is not None and rem <= 0:
                raise ClientTimeoutError(
                    f"budget spent after {failovers} failover(s); "
                    "no terminal reply"
                )
            if mark is not None:
                # a failover/hedge re-dispatch must NOT fail open to the
                # shared topic: the shared consumer group may still count
                # the corpse as a member (a dead consumer holds its
                # partitions until the broker's session timeout), which is
                # exactly the blackhole failover exists to escape.  Wait —
                # within the remaining budget — for an eligible replica.
                await self._await_placement(
                    frozenset(exclude | set(extra_exclude)),
                    probe_interval=failover.probe_interval,
                    remaining=remaining,
                )
            nonlocal attempt_no
            # the ledger marker: the wire mark where one exists
            # ("failover"/"hedge"), else first vs plain-retry
            kind = (
                mark
                if mark is not None
                else ("first" if attempt_no == 0 else "retry")
            )
            handle = await self.start(
                prompt,
                message_history=message_history,
                deps=deps,
                route=route,
                timeout=remaining(),
                exclude_replicas=frozenset(exclude | set(extra_exclude)),
                mark=mark,
                run_id=run_id,
                attempt_no=attempt_no,
                attempt_kind=kind,
                priority=priority,
            )
            attempt_no += 1
            return handle

        primary = await dispatch(None)
        dispatched_at = cancellation.wall_clock()
        hedge: "InvocationHandle[OutputT] | None" = None
        hedged = False  # at most one hedge per call

        while True:
            live = [h for h in (primary, hedge) if h is not None]
            winner = await self._first_terminal(live, failover.probe_interval)

            if winner is not None:
                loser = hedge if winner is primary else primary
                try:
                    result = await winner.result()
                except NodeFaultError as exc:
                    if policy is None or not RetryPolicy.retriable(exc):
                        if loser is not None and loser is not winner:
                            await loser.cancel()
                            client._note_attempt_superseded(
                                run_id, loser, "hedge_lost"
                            )
                        raise
                    if winner.routed_replica is not None:
                        exclude.add(winner.routed_replica)
                    if loser is not None and loser is not winner:
                        # the duplicate may still answer: promote it and
                        # keep supervising instead of burning a retry
                        primary, hedge = loser, None
                        continue
                    fault_attempts += 1
                    if fault_attempts > max_fault_attempts:
                        raise
                    await asyncio.sleep(policy.delay(fault_attempts - 2))
                    primary = await dispatch(None)
                    dispatched_at = cancellation.wall_clock()
                    hedge = None
                    continue
                if loser is not None and loser is not winner:
                    # first terminal wins: cancel the duplicate through
                    # the ordinary cancel propagation (tombstone included
                    # — a zombie cannot execute the losing correlation)
                    await loser.cancel()
                    client._note_attempt_superseded(
                        run_id, loser, "hedge_lost"
                    )
                return result

            # ---- quiet probe tick: budget, then placement health
            rem = remaining()
            if rem is not None and rem <= 0:
                for h in live:
                    h._cancel_soon()
                raise ClientTimeoutError(
                    f"run produced no terminal reply within {effective}s "
                    f"({failovers} failover(s) attempted)"
                )
            if hedge is not None and hedge.routed_replica_key is not None:
                hedge_verdict = router.placement_verdict(
                    hedge.routed_replica_key
                )
                if hedge_verdict != "alive":
                    # a dead hedge is simply dropped (and its correlation
                    # tombstoned) — the primary is still supervised
                    if hedge.routed_replica is not None:
                        exclude.add(hedge.routed_replica)
                    await hedge.cancel()
                    client._note_attempt_superseded(
                        run_id, hedge, hedge_verdict
                    )
                    # uncharge the corpse NOW: its terminal can never
                    # arrive, so the done-callback that normally clears
                    # the router's least-request entry never fires — the
                    # phantom in-flight would bias placement away from
                    # the replica for the whole TTL after it heals
                    router.note_done(
                        hedge.routed_replica_key, hedge.correlation_id
                    )
                    hedge = None
            if primary.routed_replica_key is not None:
                verdict = router.placement_verdict(primary.routed_replica_key)
                if verdict != "alive":
                    # dead placement: tombstone the orphaned correlation
                    # FIRST (a zombie that resumes consuming must fault
                    # the old call at its admission gate), then exclude
                    # the corpse and re-dispatch under what's left
                    if primary.routed_replica is not None:
                        exclude.add(primary.routed_replica)
                    await primary.cancel()
                    client._note_attempt_superseded(
                        run_id, primary, verdict
                    )
                    # uncharge the corpse (see the dead-hedge branch):
                    # no terminal will ever clear this entry, and a
                    # healed replica must not carry phantom load.
                    # note_done is pop-idempotent, so a zombie that DOES
                    # later publish a terminal double-clears harmlessly.
                    router.note_done(
                        primary.routed_replica_key, primary.correlation_id
                    )
                    if hedge is not None:
                        # the duplicate is already running elsewhere:
                        # promote it instead of spending a failover
                        primary, hedge = hedge, None
                        dispatched_at = cancellation.wall_clock()
                        continue
                    failovers += 1
                    if failovers > failover.max_failovers:
                        raise self._no_placement_fault(verdict)
                    primary = await dispatch("failover")
                    dispatched_at = cancellation.wall_clock()
                    continue
            # ---- tail-latency hedge (execute() only): race a duplicate
            if (
                not hedged
                and failover.hedge_after is not None
                and cancellation.wall_clock() - dispatched_at
                >= failover.hedge_after
                and primary.routed_replica is not None
                and router.select(
                    self.name,
                    exclude=frozenset(exclude | {primary.routed_replica}),
                ) is not None
            ):
                hedged = True
                hedge = await dispatch(
                    "hedge",
                    extra_exclude=frozenset({primary.routed_replica}),
                )

    def _filter_step(
        self, event: "StepEvent", ledger: "StreamLedger"
    ) -> "StepEvent | None":
        """Apply the stream-resume dedupe law to one step event: token
        steps pass through the ledger (suppressing the replayed prefix
        after a failover); None = fully-replayed, drop it.  Offset-
        stamped steps (ISSUE 10) align the ledger exactly — a resumed
        attempt's first chunk arrives at the delivered-prefix offset and
        passes through whole.  Non-token steps pass through unchanged —
        they carry no offsets to dedupe on, so a failover may repeat
        them (documented)."""
        step = event.step
        if getattr(step, "kind", "") != "token":
            return event
        text = ledger.filter(step.text, getattr(step, "offset", None))
        if not text:
            return None
        if text != step.text:
            return event.model_copy(
                update={"step": step.model_copy(update={"text": text})}
            )
        return event

    async def stream(
        self,
        prompt: str | list[ContentPart],
        *,
        message_history: list[ModelMessage] | None = None,
        deps: dict[str, Any] | None = None,
        route: str = "run",
        timeout: float | None = None,
        failover: "FailoverPolicy | None" = None,
        priority: "str | None" = None,
    ) -> "AsyncIterator[Any]":
        """Stream a run's step events live, ending with the typed result
        — ``handle.stream()`` with in-flight failure recovery (ISSUE 9).

        On a fleet-routed client with a FailoverPolicy, the placement is
        supervised while streaming: when the placed replica dies
        mid-stream (or faults typed-retriable), the call is re-issued as
        a continuation on a surviving replica — same prompt (it rides
        the prefix cache there), remaining deadline, old correlation
        cancel-tombstoned, ``deps["calfkit.resume_text"]`` carrying the
        already-delivered text — and the replayed token prefix is
        suppressed so the caller observes ONE contiguous stream (the
        :class:`~calfkit_tpu.fleet.failover.StreamLedger` law).  Without
        a policy (or a router) this is plain ``start()+stream()``."""
        client = self._client
        fo = failover if failover is not None else client.failover
        if fo is None or client.router is None:
            run_id = new_id()
            handle = await self.start(
                prompt, message_history=message_history, deps=deps,
                route=route, timeout=timeout, run_id=run_id,
                priority=priority,
            )
            try:
                async for item in handle.stream():
                    step = getattr(item, "step", None)
                    if step is not None and getattr(step, "kind", "") == "token":
                        client.run_ledger.add_tokens(
                            run_id, handle.correlation_id, 1
                        )
                    yield item
            except BaseException as exc:
                client._finish_run_exc(run_id, exc)
                raise
            client._finish_run_soon(run_id, outcome="ok")
            return
        from calfkit_tpu.fleet.failover import StreamLedger

        router = client.router
        ledger = StreamLedger()
        effective = timeout if timeout is not None else client.default_timeout
        deadline = (
            cancellation.wall_clock() + effective
            if effective is not None else None
        )

        def remaining() -> "float | None":
            if deadline is None:
                return None
            return deadline - cancellation.wall_clock()

        exclude: set[str] = set()
        failovers = 0
        # run identity (ISSUE 17): ONE run id across the original
        # placement and every failover/resume re-dispatch below — the
        # whole try/except boundary closes the run with the outcome the
        # CALLER observed (ok / timeout / fault / cancelled)
        run_id = new_id()
        attempt_no = 0
        # decode-from-offset resume is a SINGLE-TURN contract: the hint
        # seeds the re-attempt's first model turn, so a run that already
        # dispatched tool calls (its delivered text spans turns) must
        # replay wholly instead — the ledger's cumulative law keeps the
        # stream contiguous either way
        multi_turn = False
        try:
            handle = await self.start(
                prompt, message_history=message_history, deps=deps,
                route=route, timeout=effective,
                run_id=run_id, attempt_no=attempt_no, attempt_kind="first",
                priority=priority,
            )
            attempt_no += 1
            while True:
                dead_reason: "str | None" = None
                pending_exc: "NodeFaultError | None" = None
                channel = handle._channel
                step_task: asyncio.Task = asyncio.ensure_future(
                    channel.steps.get()
                )
                try:
                    while dead_reason is None:
                        rem = remaining()
                        if rem is not None and rem <= 0:
                            handle._cancel_soon()
                            raise ClientTimeoutError(
                                f"stream produced no terminal within "
                                f"{effective}s ({failovers} failover(s))"
                            )
                        tick = (
                            fo.probe_interval if rem is None
                            else min(fo.probe_interval, rem)
                        )
                        done, _ = await asyncio.wait(
                            [step_task, channel.terminal],
                            timeout=tick,
                            return_when=asyncio.FIRST_COMPLETED,
                        )
                        if step_task in done:
                            raw = step_task.result()
                            if getattr(raw.step, "kind", "") in (
                                "tool_call", "tool_result", "handoff"
                            ):
                                multi_turn = True
                            event = self._filter_step(raw, ledger)
                            if event is not None:
                                if getattr(event.step, "kind", "") == "token":
                                    # delivered (post-dedupe) tokens only:
                                    # a replayed prefix never double-counts
                                    client.run_ledger.add_tokens(
                                        run_id, handle.correlation_id, 1
                                    )
                                yield event
                            step_task = asyncio.ensure_future(
                                channel.steps.get()
                            )
                            continue
                        if channel.terminal.done():
                            while not channel.steps.empty():
                                event = self._filter_step(
                                    channel.steps.get_nowait(), ledger
                                )
                                if event is not None:
                                    if getattr(event.step, "kind", "") == "token":
                                        client.run_ledger.add_tokens(
                                            run_id, handle.correlation_id, 1
                                        )
                                    yield event
                            try:
                                final = await handle.result()
                            except NodeFaultError as exc:
                                if not RetryPolicy.retriable(exc):
                                    raise
                                # a retriable fault ends THIS attempt, not
                                # the stream: re-dispatch and resume
                                dead_reason = (
                                    f"fault:{exc.report.error_type}"
                                )
                                pending_exc = exc
                                continue
                            yield final
                            client._finish_run_soon(run_id, outcome="ok")
                            return
                        # quiet tick: probe the placement
                        if handle.routed_replica_key is not None:
                            verdict = router.placement_verdict(
                                handle.routed_replica_key
                            )
                            if verdict != "alive":
                                dead_reason = verdict
                finally:
                    step_task.cancel()
                # ---- failover re-dispatch (dead placement / retriable fault)
                failovers += 1
                if failovers > fo.max_failovers:
                    if pending_exc is not None:
                        raise pending_exc
                    raise self._no_placement_fault(dead_reason or "unknown")
                if handle.routed_replica is not None:
                    exclude.add(handle.routed_replica)
                # tombstone the orphan BEFORE the replacement publishes: a
                # zombie that resumes consuming faults the old correlation
                # at its admission gate instead of executing it
                await handle.cancel()
                # ledger verdict for the abandoned attempt (first signal
                # wins: a fault that already landed keeps its outcome)
                client._note_attempt_superseded(
                    run_id, handle, dead_reason or "superseded"
                )
                ledger.begin_attempt()
                rem = remaining()
                if rem is not None and rem <= 0:
                    if pending_exc is not None:
                        raise pending_exc
                    raise ClientTimeoutError(
                        f"stream placement died ({dead_reason}) with no "
                        "budget left to re-dispatch"
                    )
                if pending_exc is None:
                    # DEATH re-dispatch: never fail open to the shared topic
                    # — the shared group may still count the corpse as a
                    # member — wait for an eligible replica instead
                    await self._await_placement(
                        frozenset(exclude),
                        probe_interval=fo.probe_interval,
                        remaining=remaining,
                    )
                else:
                    # FAULT re-dispatch: the replica is alive and answering
                    # (it shed/wedged us, typed) — a brief backoff, then
                    # fail-open placement is SAFE and required: on a fleet
                    # with no alternative replica, waiting on the exclusion
                    # set would burn the whole deadline for a transient shed
                    # that the shared topic (or the same replica, recovered)
                    # can absorb in milliseconds
                    rem = remaining()
                    await asyncio.sleep(
                        fo.probe_interval if rem is None
                        else min(fo.probe_interval, max(rem, 0.0))
                    )
                resume_deps = dict(deps or {})
                if ledger.text and not multi_turn:
                    # the continuation hint: prompt + already-delivered text.
                    # The agent's first model turn CONSUMES it (decode-from-
                    # offset, ISSUE 10); multi-turn runs omit it — delivered
                    # text spanning tool-call turns would corrupt the first
                    # turn's continuation — and replay wholly instead (the
                    # dedupe ledger guarantees contiguity either way)
                    resume_deps["calfkit.resume_text"] = ledger.text
                handle = await self.start(
                    prompt,
                    message_history=message_history,
                    deps=resume_deps,
                    route=route,
                    timeout=remaining(),
                    exclude_replicas=frozenset(exclude),
                    mark="failover",
                    run_id=run_id,
                    attempt_no=attempt_no,
                    # the ledger distinguishes a decode-from-offset
                    # resume from a whole-replay failover; the wire mark
                    # stays "failover" (x-mesh-attempt vocabulary)
                    attempt_kind=(
                        "resume"
                        if "calfkit.resume_text" in resume_deps
                        else "failover"
                    ),
                    priority=priority,
                )
                attempt_no += 1
        except BaseException as exc:
            client._finish_run_exc(run_id, exc)
            raise
