"""client.mesh — the read-only mesh directory.

Reference: calfkit/client/mesh.py:241-354.  Per-kind views are created
lazily, started once (single-flight), and surface typed
:class:`MeshUnavailableError` with a reason instead of hanging when the
control plane can't be read.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import TYPE_CHECKING

from calfkit_tpu import protocol
from calfkit_tpu.controlplane.view import ControlPlaneView
from calfkit_tpu.exceptions import MeshUnavailableError
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.capability import CapabilityRecord
from calfkit_tpu.models.records import EngineStatsRecord

if TYPE_CHECKING:
    from calfkit_tpu.client.caller import Client


class Mesh:
    def __init__(self, client: "Client", *, catchup_timeout: float = 30.0):
        self._client = client
        self._catchup_timeout = catchup_timeout
        self._views: dict[str, ControlPlaneView] = {}
        self._locks: dict[str, asyncio.Lock] = {}

    async def _view(self, kind: str) -> ControlPlaneView:
        view = self._views.get(kind)
        if view is not None and view.is_caught_up:
            return view
        lock = self._locks.setdefault(kind, asyncio.Lock())
        async with lock:  # single-flight per kind
            view = self._views.get(kind)
            if view is not None and view.is_caught_up:
                return view
            if view is not None:
                # lagging/failed view: stop it before replacing (a replaced
                # reader would otherwise consume forever)
                self._views.pop(kind, None)
                try:
                    await view.stop()
                except Exception:  # noqa: BLE001
                    pass
            await self._client._ensure_started()
            topic, record_type = {
                "agents": (protocol.AGENTS_TOPIC, AgentCard),
                "capabilities": (protocol.CAPABILITIES_TOPIC, CapabilityRecord),
                "engine_stats": (
                    protocol.ENGINE_STATS_TOPIC, EngineStatsRecord
                ),
            }[kind]
            view = ControlPlaneView(
                self._client.mesh,
                topic,
                record_type,
                catchup_timeout=self._catchup_timeout,
            )
            try:
                await view.start()
            except Exception as exc:  # noqa: BLE001
                with contextlib.suppress(Exception):
                    await view.stop()  # failed start must not leak a reader
                raise MeshUnavailableError(
                    f"mesh {kind} directory unavailable: {exc}",
                    reason="catchup-failed",
                ) from exc
            self._views[kind] = view
            return view

    async def get_agents(self) -> list[AgentCard]:
        return (await self._view("agents")).records()

    async def get_capabilities(self) -> list[CapabilityRecord]:
        return (await self._view("capabilities")).records()

    async def get_engine_stats(self) -> "list[EngineStatsRecord]":
        """Live serving metrics from every worker whose agents run a local
        inference engine (tok/s, occupancy, free slots/pages)."""
        return (await self._view("engine_stats")).records()

    async def get_agent(self, name: str) -> AgentCard:
        for card in await self.get_agents():
            if card.name == name:
                return card
        raise MeshUnavailableError(
            f"no live agent named {name!r}", reason="not-found"
        )

    async def close(self) -> None:
        for view in self._views.values():
            await view.stop()
        self._views.clear()
