"""Wire-protocol constants: header names, kind vocabularies, header decode.

This module is the single authority for what travels in Kafka record *headers*
(bodies are defined in :mod:`calfkit_tpu.models`).  It depends on nothing else
in the package by design, mirroring the reference's dependency-free protocol
module (reference: calfkit/_protocol.py:1-118).

Header model
------------
Every envelope-bearing record carries:

- ``x-mesh-emitter``   — ``<node_kind>/<node_name>`` of the publishing node
- ``x-mesh-kind``      — :data:`MessageKind`: ``call`` | ``return`` | ``fault``
- ``x-mesh-wire``      — :data:`WireKind`: body schema discriminator
                         (``envelope`` | ``step``)
- ``x-mesh-route``     — the route string the publisher addressed
- ``x-mesh-task``      — task id (uuid); equals the partition key's source
- ``x-mesh-correlation`` — correlation id of the whole run (client-minted)
- ``x-mesh-error-type`` — fault records only: the typed fault code
- ``x-mesh-trace``     — distributed-trace id (client-minted, equals the
                         correlation id by convention)
- ``x-mesh-span``      — the EMITTING hop's span id; the receiving hop
                         parents its own span to it
- ``x-mesh-deadline``  — absolute wall-clock deadline (epoch seconds,
                         decimal string), minted by the client from its
                         timeout and forwarded by every hop.  A hop that
                         receives an already-expired call records a typed
                         ``mesh.deadline_exceeded`` fault instead of
                         executing — work for a dead caller is the mesh's
                         most expensive no-op.

Headers are advisory routing/telemetry metadata; the envelope body is always
authoritative.  Consumers must tolerate missing headers (a ``None`` decode).

The ``cancel`` message kind carries no envelope body: it is a pure header
record (correlation id + task key) that asks every in-process cancellation
target along the run's path — engines, long-running handlers — to abandon
work for that correlation id.  Each hop re-publishes the cancel to the
topics it sent the run's calls to, so it follows the run across process
boundaries; a tombstone guards work the targets cannot see yet (see
:mod:`calfkit_tpu.cancellation`).
"""

from __future__ import annotations

from typing import Final, Literal

# --------------------------------------------------------------------------- #
# header names
# --------------------------------------------------------------------------- #

HDR_EMITTER: Final = "x-mesh-emitter"
HDR_KIND: Final = "x-mesh-kind"
HDR_WIRE: Final = "x-mesh-wire"
HDR_ROUTE: Final = "x-mesh-route"
HDR_TASK: Final = "x-mesh-task"
HDR_CORRELATION: Final = "x-mesh-correlation"
HDR_ERROR_TYPE: Final = "x-mesh-error-type"
HDR_TRACE: Final = "x-mesh-trace"
HDR_SPAN: Final = "x-mesh-span"
HDR_DEADLINE: Final = "x-mesh-deadline"
# failure recovery (ISSUE 9): marks a call record as a failover
# re-dispatch or a hedge duplicate ("failover" | "hedge").  Describes
# THIS placement only — hops do not forward it downstream; the serving
# agent counts arrivals into its engine-stats advert (FAILOVER/HEDGE in
# ``ck stats``).
HDR_ATTEMPT: Final = "x-mesh-attempt"
# caller liveness lease (ISSUE 10): "<lease_id>:<ttl_s>" — the caller's
# process-level lease, minted once per client and forwarded by every hop
# (like the deadline: downstream tool calls run on the original caller's
# behalf).  While any leased run is outstanding the caller heartbeats the
# compacted CALLER_LIVENESS_TOPIC; an engine whose run's lease lapses
# past its TTL reaps the run as an orphan (typed ``mesh.orphaned``) —
# the server-side half of failure recovery, covering fire-and-forget
# ``send()`` that no client-side supervisor can.
HDR_LEASE: Final = "x-mesh-lease"
# priority class (ISSUE 20): "interactive" | "batch" — the caller's QoS
# class, minted by the client and forwarded by every hop (downstream
# tool calls run on the original caller's behalf, so they inherit its
# class).  Under overload the mesh degrades SELECTIVELY: batch-class
# work sheds first, reaps first, and rate-limits first.  A corrupt or
# missing header degrades to the DEFAULT class (interactive — batch is
# an explicit opt-in to lower priority; legacy callers must not be
# demoted) and never faults delivery (the PR 5 law).
HDR_PRIORITY: Final = "x-mesh-priority"
# run identity (ISSUE 17): "<run_id>:<attempt_no>" — the run_id is minted
# ONCE per logical ``execute()``/``stream()`` call and carried VERBATIM
# across retries, failover re-dispatches, hedge duplicates, and
# decode-from-offset resumes; the attempt counter beside it increments
# per placement.  Forwarded by every hop (like the deadline and the
# lease: downstream tool calls belong to the same logical run), unlike
# ``x-mesh-attempt`` which describes one placement only.  A corrupt
# header degrades to an UN-LINKED run — never a shared bogus run id,
# never a delivery fault (the PR 5 law).
HDR_RUN: Final = "x-mesh-run"

ALL_HEADERS: Final = (
    HDR_EMITTER,
    HDR_KIND,
    HDR_WIRE,
    HDR_ROUTE,
    HDR_TASK,
    HDR_CORRELATION,
    HDR_ERROR_TYPE,
    HDR_TRACE,
    HDR_SPAN,
    HDR_DEADLINE,
    HDR_ATTEMPT,
    HDR_LEASE,
    HDR_PRIORITY,
    HDR_RUN,
)

# the QoS class vocabulary (ISSUE 20), ordered best-first; everything
# that ranks, sheds, or renders by class iterates THIS tuple so the
# order is defined in exactly one place
PRIORITY_CLASSES: Final = ("interactive", "batch")
DEFAULT_PRIORITY: Final = "interactive"

# --------------------------------------------------------------------------- #
# kind vocabularies
# --------------------------------------------------------------------------- #

NodeKind = Literal["agent", "tool", "consumer", "toolbox", "client", "worker"]
MessageKind = Literal["call", "return", "fault", "cancel"]
WireKind = Literal["envelope", "step", "span"]

MESSAGE_KINDS: Final = ("call", "return", "fault", "cancel")
WIRE_KINDS: Final = ("envelope", "step", "span")

# --------------------------------------------------------------------------- #
# decode helpers
# --------------------------------------------------------------------------- #


def decode_header_str(value: bytes | str | None) -> str | None:
    """Decode a raw header value to ``str`` (headers may arrive as bytes)."""
    if value is None:
        return None
    if isinstance(value, bytes):
        try:
            return value.decode("utf-8")
        except UnicodeDecodeError:
            return None
    return value


def header_map(raw: dict[str, bytes | str] | None) -> dict[str, str]:
    """Normalize a raw header mapping to ``str -> str``, dropping undecodables."""
    out: dict[str, str] = {}
    for k, v in (raw or {}).items():
        s = decode_header_str(v)
        if s is not None:
            out[k] = s
    return out


def format_deadline(epoch_s: float) -> str:
    """Encode an absolute wall-clock deadline for the wire (ms precision:
    cross-host clock skew dwarfs anything finer)."""
    return f"{epoch_s:.3f}"


def parse_deadline(value: "bytes | str | None") -> "float | None":
    """Decode an ``x-mesh-deadline`` header value; ``None`` for a missing
    or malformed header (a corrupt deadline degrades to un-deadlined, it
    must never fault the delivery)."""
    s = decode_header_str(value)
    if not s:
        return None
    try:
        deadline = float(s)
    except ValueError:
        return None
    # NaN/inf are not deadlines; negative epochs are clock garbage
    if deadline != deadline or deadline in (float("inf"), float("-inf")):
        return None
    return deadline if deadline > 0 else None


def format_lease(lease_id: str, ttl_s: float) -> str:
    """Encode a caller lease for the wire: ``<lease_id>:<ttl_s>`` (lease
    ids are hex — never contain the separator)."""
    return f"{lease_id}:{ttl_s:.3f}"


def parse_lease(value: "bytes | str | None") -> "tuple[str, float] | None":
    """Decode an ``x-mesh-lease`` header to ``(lease_id, ttl_s)``; None
    for a missing or malformed header (a corrupt lease degrades to
    un-leased — the pre-lease behavior — and must never fault delivery)."""
    s = decode_header_str(value)
    if not s or ":" not in s:
        return None
    lease_id, _, raw_ttl = s.rpartition(":")
    try:
        ttl = float(raw_ttl)
    except ValueError:
        return None
    # NaN/inf/non-positive TTLs are not leases
    if ttl != ttl or ttl in (float("inf"), float("-inf")) or ttl <= 0:
        return None
    return (lease_id, ttl) if lease_id else None


def format_run(run_id: str, attempt: int) -> str:
    """Encode run identity for the wire: ``<run_id>:<attempt_no>`` (run
    ids are hex — never contain the separator)."""
    return f"{run_id}:{attempt:d}"


def parse_run(value: "bytes | str | None") -> "tuple[str, int] | None":
    """Decode an ``x-mesh-run`` header to ``(run_id, attempt_no)``; None
    for a missing or malformed header (a corrupt run header degrades to
    an UN-LINKED run — never a shared bogus run id, never a delivery
    fault)."""
    s = decode_header_str(value)
    if not s or ":" not in s:
        return None
    run_id, _, raw_attempt = s.rpartition(":")
    # int(), not float(): "1.5", "nan", "inf" are not attempt counters
    try:
        attempt = int(raw_attempt)
    except ValueError:
        return None
    if attempt < 0:
        return None
    return (run_id, attempt) if run_id else None


def format_priority(priority: str) -> str:
    """Encode a priority class for the wire (identity today; the single
    authority exists so a future vocabulary change has one mint site)."""
    return priority


def parse_priority(value: "bytes | str | None") -> "str | None":
    """Decode an ``x-mesh-priority`` header to a class name; ``None``
    for a missing, undecodable, or out-of-vocabulary value (a corrupt
    class degrades to the DEFAULT class downstream — it must never fault
    delivery, and it must never invent a third class)."""
    s = decode_header_str(value)
    if s in PRIORITY_CLASSES:
        return s
    return None


def emitter_header(node_kind: str, node_name: str) -> str:
    return f"{node_kind}/{node_name}"


def parse_emitter(value: str | None) -> tuple[str | None, str | None]:
    """Split ``<kind>/<name>`` (name may itself contain ``/``-free chars only)."""
    if not value or "/" not in value:
        return None, None
    kind, _, name = value.partition("/")
    return (kind or None), (name or None)


def wire_kind_of(headers: dict[str, str]) -> str | None:
    return headers.get(HDR_WIRE)


def is_envelope(headers: dict[str, str]) -> bool:
    """Subscriber filter: does this record carry an Envelope body?

    Records without a wire header are treated as envelopes for lenient
    interop; ``step`` records are explicitly not (reference: the
    ``wire_filter`` subscriber filter, calfkit/_protocol.py:89).
    """
    wk = headers.get(HDR_WIRE)
    return wk is None or wk == "envelope"


# --------------------------------------------------------------------------- #
# topic-name validation (Kafka legal-name rules)
# --------------------------------------------------------------------------- #

_TOPIC_LEGAL = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)
MAX_TOPIC_LEN: Final = 249


def is_topic_safe(name: str) -> bool:
    """True iff ``name`` is a legal Kafka topic name.

    Reference: calfkit/_protocol.py:110 (same rules: charset, length, and the
    reserved ``.``/``..`` names).
    """
    if not name or len(name) > MAX_TOPIC_LEN:
        return False
    if name in (".", ".."):
        return False
    return all(c in _TOPIC_LEGAL for c in name)


def require_topic_safe(name: str, *, what: str = "topic") -> str:
    if not is_topic_safe(name):
        raise ValueError(
            f"{what} {name!r} is not a legal topic name "
            f"(allowed: [a-zA-Z0-9._-], max {MAX_TOPIC_LEN} chars)"
        )
    return name


# --------------------------------------------------------------------------- #
# framework topic layout
# --------------------------------------------------------------------------- #
# One place computes every per-node topic name so that provisioning, workers,
# clients and the control plane all agree (reference spreads this across
# nodes/base.py and provisioning/provisioner.py; centralizing it is deliberate).


def agent_input_topic(name: str) -> str:
    return require_topic_safe(f"agent.{name}.private.input")


def agent_return_topic(name: str) -> str:
    return require_topic_safe(f"agent.{name}.private.return")


def agent_replica_topic(name: str, instance_id: str) -> str:
    """The replica-ADDRESSED input topic (ISSUE 7): one per engine-backed
    agent instance, consumed only by that instance.  The shared
    ``agent_input_topic`` load-balances blindly via consumer-group
    membership; the fleet router publishes here instead when a routing
    policy picked a specific replica (least-loaded, power-of-two,
    prefix-affinity).  The shared topic remains the fallback for meshes
    with no control plane or no live replica adverts."""
    return require_topic_safe(f"agent.{name}.replica.{instance_id}.private.input")


def agent_publish_topic(name: str) -> str:
    return require_topic_safe(f"agent.{name}.events")


def tool_input_topic(name: str) -> str:
    return require_topic_safe(f"tool.{name}.input")


def tool_publish_topic(name: str) -> str:
    return require_topic_safe(f"tool.{name}.output")


def toolbox_input_topic(name: str) -> str:
    return require_topic_safe(f"mcp_server.{name}.input")


def toolbox_publish_topic(name: str) -> str:
    return require_topic_safe(f"mcp_server.{name}.output")


def client_inbox_topic(client_id: str) -> str:
    return require_topic_safe(f"mesh.client.{client_id}.inbox")


AGENTS_TOPIC: Final = "mesh.agents"
CAPABILITIES_TOPIC: Final = "mesh.capabilities"
ENGINE_STATS_TOPIC: Final = "mesh.engine_stats"
# compacted span stream (key = trace_id/span_id: compaction dedupes
# re-emissions; spans are one-shot keys, so production clusters should
# ALSO set time retention — cleanup.policy=compact,delete — to bound
# total growth; see docs/observability.md)
TRACES_TOPIC: Final = "mesh.traces"
# compacted caller-liveness beats (ISSUE 10): key = lease id, value =
# the compact beat JSON (calfkit_tpu.leases.beat_payload); tombstone =
# clean caller departure (outstanding leased runs orphan immediately)
CALLER_LIVENESS_TOPIC: Final = "mesh.caller_liveness"
# run-scoped observability (ISSUE 17): compacted per-run records (key =
# run_id, value = RunRecord JSON — every attempt's placement/outcome/
# markers), published by the supervising client when a run finishes, and
# compacted per-agent SLO rollups (key = <agent>@<instance>, value =
# SloRollupRecord JSON) re-derived on the control-plane heartbeat
# cadence.  Like mesh.traces, run keys are one-shot: production clusters
# should pair compaction with time retention to bound growth.
RUNS_TOPIC: Final = "mesh.runs"
SLO_TOPIC: Final = "mesh.slo"


def fanout_state_topic(node_id: str) -> str:
    return require_topic_safe(f"mesh.fanout.{node_id}.state")


def fanout_basestate_topic(node_id: str) -> str:
    return require_topic_safe(f"mesh.fanout.{node_id}.basestate")
