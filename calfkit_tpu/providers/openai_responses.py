"""OpenAI Responses-API model client (reference:
calfkit/providers/pydantic_ai/openai.py:71 ``OpenAIResponsesModelClient`` —
there a thin subclass of the vendored pydantic-ai Responses model; here a
direct httpx client speaking the same ModelClient seam).

The Responses API differs from chat completions in shape, not in role:

- history is a flat ``input`` item list (messages, ``function_call`` items,
  ``function_call_output`` items) instead of role-tagged chat messages;
- tools are flat (``{"type": "function", "name", ...}``) rather than nested
  under a ``function`` key;
- ``max_output_tokens`` replaces both max-token spellings;
- streaming is TYPED events (``response.output_text.delta``,
  ``response.completed``) instead of chat chunks, and the terminal
  ``response.completed`` event carries the whole final response — so the
  stream accumulates text for deltas but builds the final ModelResponse
  from the terminal payload (no tool-call delta reassembly needed).
"""

from __future__ import annotations

import json
import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import (
    ModelAPIError,
    content_str,
    post_json,
    sse_lines,
)

_DEFAULT_BASE_URL = "https://api.openai.com/v1"


def render_responses_input(
    messages: list[ModelMessage],
) -> tuple[str | None, list[dict]]:
    """Our wire vocabulary → (instructions, Responses ``input`` items)."""
    instructions: str | None = None
    items: list[dict] = []
    for message in messages:
        if isinstance(message, ModelResponse):
            text = message.text()
            if text:
                items.append({
                    "type": "message", "role": "assistant",
                    "content": [{"type": "output_text", "text": text}],
                })
            for call in message.tool_calls():
                items.append({
                    "type": "function_call",
                    "call_id": call.tool_call_id,
                    "name": call.tool_name,
                    "arguments": (
                        call.args
                        if isinstance(call.args, str)
                        else json.dumps(call.args)
                    ),
                })
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            # the API carries system guidance in a dedicated field; the
            # LAST request's instructions win (same precedence as sending
            # a trailing system message in chat completions)
            instructions = message.instructions
        for part in message.parts:
            if isinstance(part, SystemPart):
                items.append({"role": "system", "content": part.content})
            elif isinstance(part, UserPart):
                items.append({
                    "role": "user", "content": content_str(part.content),
                })
            elif isinstance(part, ToolReturnPart):
                items.append({
                    "type": "function_call_output",
                    "call_id": part.tool_call_id,
                    "output": content_str(part.content),
                })
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    items.append({
                        "type": "function_call_output",
                        "call_id": part.tool_call_id,
                        "output": part.content,
                    })
                else:
                    items.append({"role": "user", "content": part.content})
    return instructions, items


def parse_responses_output(data: dict, model: str) -> ModelResponse:
    """The ``output`` item list → ModelResponse (shared by the request path
    and the stream's terminal ``response.completed`` payload)."""
    output = data.get("output")
    if not isinstance(output, list):
        raise ModelAPIError(
            f"openai responses payload missing output: {data!r}"[:500]
        )
    parts: list[Any] = []
    for item in output:
        kind = item.get("type")
        if kind == "message":
            for block in item.get("content") or []:
                if block.get("type") == "output_text" and block.get("text"):
                    parts.append(TextOutput(text=block["text"]))
        elif kind == "function_call":
            parts.append(ToolCallOutput(
                tool_call_id=item.get("call_id", ""),
                tool_name=item.get("name", ""),
                args=item.get("arguments") or "{}",
            ))
        # reasoning / web_search / other built-in items carry no parts we
        # transport; tool use beyond function calls is out of scope here
    usage = data.get("usage") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("input_tokens", 0),
            output_tokens=usage.get("output_tokens", 0),
        ),
        model_name=data.get("model", model),
    )


def _is_hard_failure(data: dict) -> bool:
    """True when a terminal Responses payload must raise.

    ``status="incomplete"`` with reason ``max_output_tokens`` is NOT a
    failure: the partial output is returned, matching the chat-completions
    client's behavior on ``finish_reason="length"`` (divergent handling
    would make the same cap fatal behind one provider and benign behind
    the other — and burn FallbackModelClient attempts on a condition every
    fallback hits too)."""
    status = data.get("status")
    if status == "failed":
        return True
    if status == "incomplete":
        reason = (data.get("incomplete_details") or {}).get("reason")
        return reason != "max_output_tokens"
    return False


class OpenAIResponsesModelClient(ModelClient):
    """The Responses API over httpx.  ``http_client=`` injects a configured
    ``httpx.AsyncClient`` (timeouts, proxies, MockTransport in tests)."""

    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
        reasoning_effort: str | None = None,
    ):
        self._model = model
        self._api_key = api_key or os.environ.get("OPENAI_API_KEY", "")
        self._base_url = base_url.rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None
        self._reasoning_effort = reasoning_effort

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        instructions, items = render_responses_input(messages)
        payload: dict[str, Any] = {"model": self._model, "input": items}
        if instructions:
            payload["instructions"] = instructions
        tools = [
            {
                "type": "function",
                "name": t.name,
                "description": t.description,
                "parameters": t.parameters_schema,
            }
            for t in params.all_tools()
        ]
        if tools:
            payload["tools"] = tools
            if not params.allow_text_output:
                payload["tool_choice"] = "required"
        if settings.max_tokens is not None:
            payload["max_output_tokens"] = settings.max_tokens
        if settings.temperature is not None:
            payload["temperature"] = settings.temperature
        if settings.top_p is not None:
            payload["top_p"] = settings.top_p
        if self._reasoning_effort is not None:
            payload["reasoning"] = {"effort": self._reasoning_effort}
        # stop_sequences / seed have no Responses-API equivalent; extra
        # carries anything provider-specific verbatim
        payload.update(settings.extra)
        return payload

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        data = await post_json(
            self._http(),
            f"{self._base_url}/responses",
            headers={"Authorization": f"Bearer {self._api_key}"},
            payload=self._build_payload(messages, settings, params),
            provider="openai-responses",
        )
        if _is_hard_failure(data):
            err = data.get("error") or data.get("incomplete_details") or {}
            raise ModelAPIError(
                f"openai responses run {data.get('status')}: {err}"[:500],
                body=json.dumps(data)[:2000],
            )
        return parse_responses_output(data, self._model)

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ):
        """Typed-event SSE: yields TextDelta per ``response.output_text.delta``,
        then one ResponseDone built from ``response.completed``'s payload."""
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        payload = self._build_payload(messages, settings, params)
        payload["stream"] = True

        final: dict | None = None
        async for data in sse_lines(
            self._http(), f"{self._base_url}/responses",
            headers={"Authorization": f"Bearer {self._api_key}"},
            payload=payload, provider="openai-responses",
        ):
            if data == "[DONE]":
                break
            try:
                event = json.loads(data)
            except ValueError:
                continue
            kind = event.get("type", "")
            if kind == "response.output_text.delta" and event.get("delta"):
                yield TextDelta(event["delta"])
            elif kind == "response.completed":
                final = event.get("response") or {}
            elif kind == "response.incomplete":
                # terminal-but-capped: a max_output_tokens cap keeps the
                # partial output (chat-completions parity, see
                # _is_hard_failure); other reasons (content filter) raise
                # the typed error instead of the generic truncation guard
                resp = event.get("response") or {}
                if _is_hard_failure({**resp, "status": "incomplete"}):
                    raise ModelAPIError(
                        "openai responses run incomplete: "
                        f"{resp.get('incomplete_details')}"[:500],
                        body=json.dumps(resp)[:2000],
                    )
                final = resp
            elif kind in ("response.failed", "error"):
                detail = (
                    (event.get("response") or {}).get("error")
                    if kind == "response.failed" else event
                )
                # mid-stream failure: a truncated answer must not pass as
                # success (mirrors the chat-completions guard)
                raise ModelAPIError(
                    f"openai responses mid-stream error: {detail}"[:500]
                )

        if final is None:
            raise ModelAPIError(
                "openai responses stream closed without response.completed "
                "(response may be truncated)"
            )
        yield ResponseDone(parse_responses_output(final, self._model))
