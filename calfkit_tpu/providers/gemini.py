"""Google Gemini generateContent model client (reference: the vendored
pydantic-ai provider set includes a Google adapter,
calfkit/_vendor/pydantic_ai/models/google.py; here a direct httpx client
on the same ModelClient seam — no google-genai SDK).

Protocol notes that shape the mapping:

- history is ``contents`` with roles ``user``/``model``; function results
  ride a user turn as ``functionResponse`` parts;
- Gemini has NO tool-call ids — calls and responses correlate by function
  NAME.  Outbound, ids minted by this client are ``<name>#<n>`` so the
  framework's id-keyed bookkeeping still works; inbound, the id is
  dropped and the name carries the correlation;
- system guidance is the dedicated ``systemInstruction`` field;
- streaming is ``:streamGenerateContent?alt=sse`` — chunks are whole
  GenerateContentResponse objects (function calls arrive complete, not as
  deltas), so the stream accumulates text and keeps the LAST usage.
"""

from __future__ import annotations

import json
import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import (
    ModelAPIError,
    content_str,
    post_json,
    sse_lines,
)

_DEFAULT_BASE_URL = "https://generativelanguage.googleapis.com/v1beta"

# finish reasons that mean the answer was cut for a non-length reason —
# surfaced as typed errors instead of silently-partial output
_HARD_FINISH = ("SAFETY", "RECITATION", "BLOCKLIST", "PROHIBITED_CONTENT",
                "MALFORMED_FUNCTION_CALL")


def render_gemini_contents(
    messages: list[ModelMessage],
) -> tuple[str, list[dict]]:
    """Our wire vocabulary → (system_instruction, contents)."""
    system_chunks: list[str] = []
    contents: list[dict] = []

    def emit(role: str, parts: list[dict]) -> None:
        if not parts:
            return
        if contents and contents[-1]["role"] == role:
            contents[-1]["parts"].extend(parts)
        else:
            contents.append({"role": role, "parts": parts})

    for message in messages:
        if isinstance(message, ModelResponse):
            parts: list[dict] = []
            text = message.text()
            if text:
                parts.append({"text": text})
            for call in message.tool_calls():
                parts.append({
                    "functionCall": {
                        "name": call.tool_name,
                        "args": call.args_dict(),
                    }
                })
            emit("model", parts)
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            system_chunks.append(message.instructions)
        parts = []
        for part in message.parts:
            if isinstance(part, SystemPart):
                system_chunks.append(part.content)
            elif isinstance(part, UserPart):
                parts.append({"text": content_str(part.content)})
            elif isinstance(part, ToolReturnPart):
                parts.append({
                    "functionResponse": {
                        "name": part.tool_name,
                        "response": {"result": content_str(part.content)},
                    }
                })
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    # name-correlated: the retry's tool_name carries it
                    parts.append({
                        "functionResponse": {
                            "name": part.tool_name or "tool",
                            "response": {"error": part.content},
                        }
                    })
                else:
                    parts.append({"text": part.content})
        emit("user", parts)
    return "\n\n".join(system_chunks), contents


def parse_gemini_response(data: dict, model: str) -> ModelResponse:
    candidates = data.get("candidates")
    if not isinstance(candidates, list) or not candidates:
        # prompt-level block arrives with no candidates at all
        feedback = data.get("promptFeedback") or {}
        raise ModelAPIError(
            f"gemini response has no candidates "
            f"(blockReason={feedback.get('blockReason')!r})",
            body=json.dumps(data)[:2000],
        )
    candidate = candidates[0]
    finish = candidate.get("finishReason")
    if finish in _HARD_FINISH:
        raise ModelAPIError(
            f"gemini candidate finished {finish}",
            body=json.dumps(candidate)[:2000],
        )
    parts: list[Any] = []
    n_calls = 0
    for part in (candidate.get("content") or {}).get("parts") or []:
        if part.get("text"):
            parts.append(TextOutput(text=part["text"]))
        elif part.get("functionCall"):
            call = part["functionCall"]
            # Gemini carries no call ids; mint a stable per-response one
            parts.append(ToolCallOutput(
                tool_call_id=f"{call.get('name', 'tool')}#{n_calls}",
                tool_name=call.get("name", ""),
                args=call.get("args") or {},
            ))
            n_calls += 1
    usage = data.get("usageMetadata") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("promptTokenCount", 0),
            output_tokens=usage.get("candidatesTokenCount", 0),
        ),
        model_name=data.get("modelVersion", model),
    )


class GeminiModelClient(ModelClient):
    """generateContent over httpx.  ``http_client=`` injects a configured
    ``httpx.AsyncClient`` (timeouts, proxies, MockTransport in tests)."""

    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
    ):
        self._model = model
        self._api_key = api_key or os.environ.get("GEMINI_API_KEY", "") or (
            os.environ.get("GOOGLE_API_KEY", "")
        )
        self._base_url = base_url.rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        system, contents = render_gemini_contents(messages)
        payload: dict[str, Any] = {"contents": contents}
        if system:
            payload["systemInstruction"] = {"parts": [{"text": system}]}
        declarations = [
            {
                "name": t.name,
                "description": t.description,
                "parameters": t.parameters_schema,
            }
            for t in params.all_tools()
        ]
        if declarations:
            payload["tools"] = [{"functionDeclarations": declarations}]
            if not params.allow_text_output:
                payload["toolConfig"] = {
                    "functionCallingConfig": {"mode": "ANY"}
                }
        config: dict[str, Any] = {}
        if settings.max_tokens is not None:
            config["maxOutputTokens"] = settings.max_tokens
        if settings.temperature is not None:
            config["temperature"] = settings.temperature
        if settings.top_p is not None:
            config["topP"] = settings.top_p
        if settings.top_k is not None:
            config["topK"] = settings.top_k
        if settings.stop_sequences:
            config["stopSequences"] = settings.stop_sequences
        if config:
            payload["generationConfig"] = config
        payload.update(settings.extra)
        return payload

    def _headers(self) -> dict[str, str]:
        return {"x-goog-api-key": self._api_key}

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        data = await post_json(
            self._http(),
            f"{self._base_url}/models/{self._model}:generateContent",
            headers=self._headers(),
            payload=self._build_payload(messages, settings, params),
            provider="gemini",
        )
        return parse_gemini_response(data, self._model)

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ):
        """SSE streaming: each chunk is a whole GenerateContentResponse;
        text parts yield TextDelta, function calls arrive complete, the
        LAST chunk's usage/finishReason wins; one ResponseDone."""
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        payload = self._build_payload(messages, settings, params)

        text_chunks: list[str] = []
        calls: list[dict] = []
        usage = Usage()
        model_name = self._model
        finish: str | None = None
        async for data in sse_lines(
            self._http(),
            f"{self._base_url}/models/{self._model}:streamGenerateContent?alt=sse",
            headers=self._headers(), payload=payload, provider="gemini",
        ):
            try:
                event = json.loads(data)
            except ValueError:
                continue
            if event.get("error"):
                raise ModelAPIError(
                    f"gemini mid-stream error: {event['error']}"[:500]
                )
            model_name = event.get("modelVersion", model_name)
            meta = event.get("usageMetadata")
            if meta:
                usage = Usage(
                    input_tokens=meta.get("promptTokenCount", 0),
                    output_tokens=meta.get("candidatesTokenCount", 0),
                )
            for candidate in event.get("candidates") or []:
                if candidate.get("finishReason"):
                    finish = candidate["finishReason"]
                for part in (candidate.get("content") or {}).get("parts") or []:
                    if part.get("text"):
                        text_chunks.append(part["text"])
                        yield TextDelta(part["text"])
                    elif part.get("functionCall"):
                        calls.append(part["functionCall"])

        if finish is None:
            # a clean close without any finishReason may hide truncation
            raise ModelAPIError(
                "gemini stream closed without a finishReason "
                "(response may be truncated)"
            )
        if finish in _HARD_FINISH:
            raise ModelAPIError(f"gemini candidate finished {finish}")

        parts: list[Any] = []
        if text_chunks:
            parts.append(TextOutput(text="".join(text_chunks)))
        for i, call in enumerate(calls):
            parts.append(ToolCallOutput(
                tool_call_id=f"{call.get('name', 'tool')}#{i}",
                tool_name=call.get("name", ""),
                args=call.get("args") or {},
            ))
        yield ResponseDone(ModelResponse(
            parts=parts, usage=usage, model_name=model_name,
        ))
