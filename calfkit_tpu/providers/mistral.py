"""Mistral (la Plateforme) model client (reference: the vendored
pydantic-ai mistral adapter, calfkit/_vendor/pydantic_ai/models/mistral.py
— there a bespoke SDK wrapper; here the same ModelClient seam over the
shared http layer).

Mistral's chat-completions API is OpenAI-shaped with deliberate
deviations, which is why this is a subclass with targeted overrides
rather than a copy:

- ``tool_choice`` uses ``"any"`` where OpenAI spells it ``"required"``;
- only the legacy ``max_tokens`` spelling exists (no reasoning split);
- tool messages carry ``name`` alongside ``tool_call_id``;
- streaming is OpenAI-style SSE with ``[DONE]``, reused verbatim.
"""

from __future__ import annotations

import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelRequestParameters,
    ModelSettings,
)
from calfkit_tpu.models.messages import ModelMessage
from calfkit_tpu.providers.openai import OpenAIModelClient

_DEFAULT_BASE_URL = "https://api.mistral.ai/v1"


class MistralModelClient(OpenAIModelClient):
    """Mistral chat completions over httpx; shares the OpenAI render /
    parse / SSE machinery and overrides only the documented deviations."""

    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
    ):
        super().__init__(
            model,
            api_key=api_key or os.environ.get("MISTRAL_API_KEY", ""),
            base_url=base_url,
            http_client=http_client,
            max_tokens_param="max_tokens",  # Mistral has no reasoning split
        )

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        payload = super()._build_payload(messages, settings, params)
        if payload.get("tool_choice") == "required":
            payload["tool_choice"] = "any"
        # Mistral's tool-result messages carry the tool NAME as well; the
        # OpenAI renderer leaves it off, so thread it back in from the
        # preceding assistant turn's calls
        names: dict[str, str] = {}
        for entry in payload["messages"]:
            for call in entry.get("tool_calls") or []:
                names[call["id"]] = call["function"]["name"]
            if entry.get("role") == "tool" and entry.get("tool_call_id") in names:
                entry["name"] = names[entry["tool_call_id"]]
        return payload
