"""Remote-API model clients — the reference's provider layer, rebuilt.

Reference: calfkit/providers/pydantic_ai/*.py (thin sugar over the vendored
``Model`` ABC; SURVEY.md §1 layer 4).  Here the TPU-local
``JaxLocalModelClient`` is the DEFAULT path; these HTTP clients exist so a
reference user migrating an OpenAI/Anthropic deployment finds the same
providers, speaking the same :class:`calfkit_tpu.engine.ModelClient` seam.

Both are httpx-based (no vendor SDKs), honor ``ModelSettings``, map tool
calls both ways, and raise :class:`ModelAPIError` with the HTTP status and
body on failure — which the agent turn runner converts into a typed
``mesh.model_error`` fault.
"""

from calfkit_tpu.providers.anthropic import AnthropicModelClient
from calfkit_tpu.providers.bedrock import BedrockModelClient
from calfkit_tpu.providers.fallback import (
    FallbackExhaustedError,
    FallbackModelClient,
)
from calfkit_tpu.providers.gemini import GeminiModelClient
from calfkit_tpu.providers.http import ModelAPIError
from calfkit_tpu.providers.mistral import MistralModelClient
from calfkit_tpu.providers.openai import OpenAIModelClient
from calfkit_tpu.providers.openai_responses import OpenAIResponsesModelClient

__all__ = [
    "AnthropicModelClient",
    "BedrockModelClient",
    "FallbackExhaustedError",
    "FallbackModelClient",
    "GeminiModelClient",
    "MistralModelClient",
    "ModelAPIError",
    "OpenAIModelClient",
    "OpenAIResponsesModelClient",
]
