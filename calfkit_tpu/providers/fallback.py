"""Provider composition: try models in order, falling back on failure.

Reference: calfkit/_vendor/pydantic_ai/models/fallback.py:23-158
(``FallbackModel``).  Same semantics on our ModelClient seam: each model is
tried in sequence; exceptions matching ``fallback_on`` accumulate and the
next model runs; a non-matching exception propagates immediately; when
every model fails, a :class:`FallbackExhaustedError` carries all of them.

The load-bearing composition here is **local TPU first, remote API as the
parachute**: ``FallbackModelClient(JaxLocalModelClient(...),
OpenAIModelClient(...))`` keeps the default quickstart fully local and
only pays network latency when the local engine refuses a request.

Streaming: our seam is an async generator, so fallback applies only while
nothing has been yielded — once the consumer saw an event, a mid-stream
failure propagates (tokens cannot be un-streamed; the reference's
context-manager seam has the same cutoff at stream open).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Callable, Sequence

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    StreamEvent,
)
from calfkit_tpu.exceptions import CalfkitError
from calfkit_tpu.models.messages import ModelMessage, ModelResponse
from calfkit_tpu.providers.http import ModelAPIError


class FallbackExhaustedError(CalfkitError):
    """Every model in a FallbackModelClient failed.

    ``exceptions`` holds each model's failure in try order; the message
    names the models so a mesh fault stays diagnosable after safe_str.
    """

    def __init__(self, models: Sequence[str], exceptions: list[Exception]):
        self.exceptions = list(exceptions)
        details = "; ".join(
            f"{name}: {type(exc).__name__}: {exc}"[:200]
            for name, exc in zip(models, exceptions)
        )
        super().__init__(
            f"all {len(exceptions)} fallback models failed ({details})"
        )


def _condition(
    fallback_on: "Callable[[Exception], bool] | tuple[type[Exception], ...]",
) -> Callable[[Exception], bool]:
    if isinstance(fallback_on, tuple):
        types = fallback_on

        def matches(exc: Exception) -> bool:
            return isinstance(exc, types)

        return matches
    return fallback_on


class FallbackModelClient(ModelClient):
    """Try each model in order; fall back on matching failures.

    ``fallback_on`` is a tuple of exception types (default: the typed
    remote-API failure plus transport-level errors, so a dead local engine
    or an unreachable endpoint both roll over) or a callable predicate.
    """

    def __init__(
        self,
        *models: ModelClient,
        fallback_on: (
            "Callable[[Exception], bool] | tuple[type[Exception], ...]"
        ) = (ModelAPIError, ConnectionError, TimeoutError, OSError),
    ):
        if not models:
            raise ValueError("FallbackModelClient needs at least one model")
        self.models = list(models)
        self._fallback_on = _condition(fallback_on)

    @property
    def model_name(self) -> str:
        return "fallback:" + ",".join(m.model_name for m in self.models)

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        exceptions: list[Exception] = []
        for model in self.models:
            try:
                return await model.request(messages, settings, params)
            except Exception as exc:  # noqa: BLE001 - classified below
                if not self._fallback_on(exc):
                    raise
                exceptions.append(exc)
        raise FallbackExhaustedError(
            [m.model_name for m in self.models], exceptions
        )

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> AsyncIterator[StreamEvent]:
        from calfkit_tpu.engine.model_client import ResumeOffset

        exceptions: list[Exception] = []
        for model in self.models:
            yielded = False
            # a ResumeOffset is HELD until the same backend produces a
            # text-bearing event: it carries no text (a backend that
            # announced a resume then failed delivered nothing, so
            # fallback stays legal), and forwarding it eagerly would
            # poison the consumer's offset space if the NEXT backend
            # regenerates from zero — the held offset is simply dropped
            # with the failed backend
            pending_offset: "ResumeOffset | None" = None
            try:
                async for event in model.request_stream(
                    messages, settings, params
                ):
                    if isinstance(event, ResumeOffset):
                        pending_offset = event
                        continue
                    if pending_offset is not None:
                        yielded = True
                        yield pending_offset
                        pending_offset = None
                    yielded = True
                    yield event
                return
            except Exception as exc:  # noqa: BLE001 - classified below
                if yielded or not self._fallback_on(exc):
                    # tokens already reached the consumer: a silent retry
                    # would duplicate them — surface the truth instead
                    raise
                exceptions.append(exc)
        raise FallbackExhaustedError(
            [m.model_name for m in self.models], exceptions
        )

    async def aclose(self) -> None:
        for model in self.models:
            close = getattr(model, "aclose", None)
            if close is not None:
                await close()

    async def start(self) -> None:
        """Start any child that wants starting (JaxLocalModelClient does)."""
        for model in self.models:
            start: Any = getattr(model, "start", None)
            if start is not None:
                await start()
