"""OpenAI chat-completions model client (reference:
calfkit/providers/pydantic_ai/openai.py — there a thin subclass of the
vendored model; here a direct httpx client speaking the same ModelClient
seam)."""

from __future__ import annotations

import json
import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import (
    ModelAPIError,
    content_str,
    post_json,
)

_DEFAULT_BASE_URL = "https://api.openai.com/v1"


def render_openai_messages(messages: list[ModelMessage]) -> list[dict]:
    """Our wire vocabulary → chat-completions messages."""
    out: list[dict] = []
    for message in messages:
        if isinstance(message, ModelResponse):
            entry: dict[str, Any] = {"role": "assistant"}
            text = message.text()
            entry["content"] = text or None
            calls = [
                {
                    "id": c.tool_call_id,
                    "type": "function",
                    "function": {
                        "name": c.tool_name,
                        "arguments": (
                            c.args
                            if isinstance(c.args, str)
                            else json.dumps(c.args)
                        ),
                    },
                }
                for c in message.tool_calls()
            ]
            if calls:
                entry["tool_calls"] = calls
            out.append(entry)
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            out.append({"role": "system", "content": message.instructions})
        for part in message.parts:
            if isinstance(part, SystemPart):
                out.append({"role": "system", "content": part.content})
            elif isinstance(part, UserPart):
                out.append({"role": "user", "content": content_str(part.content)})
            elif isinstance(part, ToolReturnPart):
                out.append({
                    "role": "tool",
                    "tool_call_id": part.tool_call_id,
                    "content": content_str(part.content),
                })
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    out.append({
                        "role": "tool",
                        "tool_call_id": part.tool_call_id,
                        "content": part.content,
                    })
                else:
                    out.append({"role": "user", "content": part.content})
    return out


def parse_openai_response(data: dict, model: str) -> ModelResponse:
    try:
        message = data["choices"][0]["message"]
    except (KeyError, IndexError, TypeError) as exc:
        raise ModelAPIError(
            f"openai response missing choices: {data!r}"[:500]
        ) from exc
    parts: list[Any] = []
    if message.get("content"):
        parts.append(TextOutput(text=message["content"]))
    for call in message.get("tool_calls") or []:
        function = call.get("function", {})
        parts.append(ToolCallOutput(
            tool_call_id=call.get("id", ""),
            tool_name=function.get("name", ""),
            args=function.get("arguments", "{}"),
        ))
    usage = data.get("usage") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("prompt_tokens", 0),
            output_tokens=usage.get("completion_tokens", 0),
        ),
        model_name=data.get("model", model),
    )


class OpenAIModelClient(ModelClient):
    """Chat-completions over httpx.  ``http_client=`` injects a configured
    ``httpx.AsyncClient`` (timeouts, proxies, MockTransport in tests)."""

    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
    ):
        self._model = model
        self._api_key = api_key or os.environ.get("OPENAI_API_KEY", "")
        self._base_url = base_url.rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        # close only the DEFAULT client this instance created; a
        # caller-injected http_client= stays the caller's to close
        # (it may be shared across model clients)
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        payload: dict[str, Any] = {
            "model": self._model,
            "messages": render_openai_messages(messages),
        }
        tools = [
            {
                "type": "function",
                "function": {
                    "name": t.name,
                    "description": t.description,
                    "parameters": t.parameters_schema,
                },
            }
            for t in params.all_tools()
        ]
        if tools:
            payload["tools"] = tools
            if not params.allow_text_output:
                payload["tool_choice"] = "required"
        if settings.max_tokens is not None:
            payload["max_tokens"] = settings.max_tokens
        if settings.temperature is not None:
            payload["temperature"] = settings.temperature
        if settings.top_p is not None:
            payload["top_p"] = settings.top_p
        if settings.seed is not None:
            payload["seed"] = settings.seed
        if settings.stop_sequences:
            payload["stop"] = settings.stop_sequences
        payload.update(settings.extra)

        data = await post_json(
            self._http(),
            f"{self._base_url}/chat/completions",
            headers={"Authorization": f"Bearer {self._api_key}"},
            payload=payload,
            provider="openai",
        )
        return parse_openai_response(data, self._model)
