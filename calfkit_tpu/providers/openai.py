"""OpenAI chat-completions model client (reference:
calfkit/providers/pydantic_ai/openai.py — there a thin subclass of the
vendored model; here a direct httpx client speaking the same ModelClient
seam)."""

from __future__ import annotations

import json
import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import (
    ModelAPIError,
    content_str,
    post_json,
    sse_lines,
)

_DEFAULT_BASE_URL = "https://api.openai.com/v1"


def render_openai_messages(messages: list[ModelMessage]) -> list[dict]:
    """Our wire vocabulary → chat-completions messages."""
    out: list[dict] = []
    for message in messages:
        if isinstance(message, ModelResponse):
            entry: dict[str, Any] = {"role": "assistant"}
            text = message.text()
            entry["content"] = text or None
            calls = [
                {
                    "id": c.tool_call_id,
                    "type": "function",
                    "function": {
                        "name": c.tool_name,
                        "arguments": (
                            c.args
                            if isinstance(c.args, str)
                            else json.dumps(c.args)
                        ),
                    },
                }
                for c in message.tool_calls()
            ]
            if calls:
                entry["tool_calls"] = calls
            out.append(entry)
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            out.append({"role": "system", "content": message.instructions})
        for part in message.parts:
            if isinstance(part, SystemPart):
                out.append({"role": "system", "content": part.content})
            elif isinstance(part, UserPart):
                out.append({"role": "user", "content": content_str(part.content)})
            elif isinstance(part, ToolReturnPart):
                out.append({
                    "role": "tool",
                    "tool_call_id": part.tool_call_id,
                    "content": content_str(part.content),
                })
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    out.append({
                        "role": "tool",
                        "tool_call_id": part.tool_call_id,
                        "content": part.content,
                    })
                else:
                    out.append({"role": "user", "content": part.content})
    return out


def parse_openai_response(data: dict, model: str) -> ModelResponse:
    try:
        message = data["choices"][0]["message"]
    except (KeyError, IndexError, TypeError) as exc:
        raise ModelAPIError(
            f"openai response missing choices: {data!r}"[:500]
        ) from exc
    parts: list[Any] = []
    if message.get("content"):
        parts.append(TextOutput(text=message["content"]))
    for call in message.get("tool_calls") or []:
        function = call.get("function", {})
        parts.append(ToolCallOutput(
            tool_call_id=call.get("id", ""),
            tool_name=function.get("name", ""),
            args=function.get("arguments", "{}"),
        ))
    usage = data.get("usage") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("prompt_tokens", 0),
            output_tokens=usage.get("completion_tokens", 0),
        ),
        model_name=data.get("model", model),
    )


def _merge_tool_call_delta(
    acc: dict[int, dict], delta: dict, last: int | None = None
) -> int:
    """Accumulate a streaming tool_calls delta by index.

    Compatible backends sometimes omit ``index``; defaulting it to 0 would
    merge distinct parallel calls into one slot (concatenated names/args).
    Fallback order: match by call id, else continue the MOST-RECENTLY-
    TOUCHED slot (``last`` — streaming order; matching the highest index
    instead misattributes continuations when a backend interleaves id-less
    chunks across parallel calls), else open a fresh one.  Returns the
    touched index for the caller to thread back in as ``last``.
    """
    index = delta.get("index")
    if index is None:
        call_id = delta.get("id") or ""
        if call_id:
            index = next(
                (k for k, s in acc.items() if s["id"] == call_id), None
            )
        elif last in acc:
            index = last
        else:
            index = max(acc, default=None)
        if index is None:
            index = max(acc, default=-1) + 1
    slot = acc.setdefault(index, {"id": "", "name": "", "arguments": ""})
    if delta.get("id"):
        slot["id"] = delta["id"]
    function = delta.get("function") or {}
    if function.get("name"):
        slot["name"] += function["name"]
    if function.get("arguments"):
        slot["arguments"] += function["arguments"]
    return index


class OpenAIModelClient(ModelClient):
    """Chat-completions over httpx.  ``http_client=`` injects a configured
    ``httpx.AsyncClient`` (timeouts, proxies, MockTransport in tests)."""

    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
        max_tokens_param: str = "auto",
    ):
        if max_tokens_param not in ("auto", "max_tokens", "max_completion_tokens"):
            raise ValueError(
                "max_tokens_param must be 'auto', 'max_tokens' or "
                f"'max_completion_tokens', got {max_tokens_param!r}"
            )
        self._model = model
        self._api_key = api_key or os.environ.get("OPENAI_API_KEY", "")
        self._base_url = base_url.rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None
        self._max_tokens_param = max_tokens_param

    # reasoning-model families reject the legacy ``max_tokens`` spelling in
    # favor of ``max_completion_tokens``; OpenAI-compatible third-party
    # backends mostly only know the legacy one, so 'auto' decides by model
    # name and the constructor knob / settings.extra override it
    _REASONING_PREFIXES = ("o1", "o3", "o4", "gpt-5")

    def _max_tokens_key(self) -> str:
        if self._max_tokens_param != "auto":
            return self._max_tokens_param
        if self._model.lower().startswith(self._REASONING_PREFIXES):
            return "max_completion_tokens"
        return "max_tokens"

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        # close only the DEFAULT client this instance created; a
        # caller-injected http_client= stays the caller's to close
        # (it may be shared across model clients)
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        payload: dict[str, Any] = {
            "model": self._model,
            "messages": render_openai_messages(messages),
        }
        tools = [
            {
                "type": "function",
                "function": {
                    "name": t.name,
                    "description": t.description,
                    "parameters": t.parameters_schema,
                },
            }
            for t in params.all_tools()
        ]
        if tools:
            payload["tools"] = tools
            if not params.allow_text_output:
                payload["tool_choice"] = "required"
        if settings.max_tokens is not None:
            payload[self._max_tokens_key()] = settings.max_tokens
        if settings.temperature is not None:
            payload["temperature"] = settings.temperature
        if settings.top_p is not None:
            payload["top_p"] = settings.top_p
        if settings.seed is not None:
            payload["seed"] = settings.seed
        if settings.stop_sequences:
            payload["stop"] = settings.stop_sequences
        payload.update(settings.extra)
        # an explicit key in settings.extra wins outright — never send both
        # spellings (the API rejects the pair)
        if "max_completion_tokens" in settings.extra:
            payload.pop("max_tokens", None)
        elif "max_tokens" in settings.extra:
            payload.pop("max_completion_tokens", None)
        return payload

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        data = await post_json(
            self._http(),
            f"{self._base_url}/chat/completions",
            headers={"Authorization": f"Bearer {self._api_key}"},
            payload=self._build_payload(messages, settings, params),
            provider="openai",
        )
        return parse_openai_response(data, self._model)

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ):
        """SSE streaming: yields TextDelta per content delta, accumulates
        tool-call deltas by index, then one ResponseDone."""
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        payload = self._build_payload(messages, settings, params)
        payload["stream"] = True
        payload["stream_options"] = {"include_usage": True}

        text_chunks: list[str] = []
        calls: dict[int, dict] = {}
        last_call: int | None = None
        usage = Usage()
        model_name = self._model
        terminated = False
        finish_seen = False
        async for data in sse_lines(
            self._http(), f"{self._base_url}/chat/completions",
            headers={"Authorization": f"Bearer {self._api_key}"},
            payload=payload, provider="openai",
        ):
            if data == "[DONE]":
                terminated = True
                break
            try:
                event = json.loads(data)
            except ValueError:
                continue
            if event.get("error"):
                # mid-stream failure: a truncated answer must not pass as
                # success (the non-streaming path raises for this state)
                raise ModelAPIError(
                    f"openai mid-stream error: {event['error']}"[:500]
                )
            model_name = event.get("model", model_name)
            if event.get("usage"):
                usage = Usage(
                    input_tokens=event["usage"].get("prompt_tokens", 0),
                    output_tokens=event["usage"].get("completion_tokens", 0),
                )
            for choice in event.get("choices") or []:
                if choice.get("finish_reason"):
                    finish_seen = True
                delta = choice.get("delta") or {}
                if delta.get("content"):
                    text_chunks.append(delta["content"])
                    yield TextDelta(delta["content"])
                for call_delta in delta.get("tool_calls") or []:
                    last_call = _merge_tool_call_delta(
                        calls, call_delta, last_call
                    )

        if not terminated and not finish_seen:
            # a clean TCP close with neither the [DONE] sentinel nor any
            # finish_reason-bearing chunk means the answer may be truncated
            # — that must not pass as success.  Some OpenAI-compatible
            # proxies end successful streams without [DONE]; a seen
            # finish_reason is the alternate completion signal.
            raise ModelAPIError(
                "openai stream closed without [DONE] or a finish_reason "
                "(response may be truncated)"
            )

        parts: list[Any] = []
        if text_chunks:
            parts.append(TextOutput(text="".join(text_chunks)))
        for index in sorted(calls):
            slot = calls[index]
            parts.append(ToolCallOutput(
                tool_call_id=slot["id"], tool_name=slot["name"],
                args=slot["arguments"] or "{}",
            ))
        yield ResponseDone(ModelResponse(
            parts=parts, usage=usage, model_name=model_name,
        ))

