"""Anthropic messages-API model client (reference:
calfkit/providers/pydantic_ai/anthropic.py — thin subclass there; a direct
httpx client here, same ModelClient seam)."""

from __future__ import annotations

import json
import os
from typing import Any

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import (
    ModelAPIError,
    content_str,
    post_json,
    sse_lines,
)

_DEFAULT_BASE_URL = "https://api.anthropic.com"
_API_VERSION = "2023-06-01"
_DEFAULT_MAX_TOKENS = 4096


def render_anthropic_messages(
    messages: list[ModelMessage],
) -> tuple[str, list[dict]]:
    """Our wire vocabulary → (system, messages-with-content-blocks).

    Consecutive same-role messages are merged — the API requires
    alternation and tool_result blocks must ride user messages."""
    system_chunks: list[str] = []
    rendered: list[dict] = []

    def emit(role: str, blocks: list[dict]) -> None:
        if not blocks:
            return
        if rendered and rendered[-1]["role"] == role:
            rendered[-1]["content"].extend(blocks)
        else:
            rendered.append({"role": role, "content": blocks})

    for message in messages:
        if isinstance(message, ModelResponse):
            blocks: list[dict] = []
            text = message.text()
            if text:
                blocks.append({"type": "text", "text": text})
            for call in message.tool_calls():
                blocks.append({
                    "type": "tool_use",
                    "id": call.tool_call_id,
                    "name": call.tool_name,
                    "input": call.args_dict(),
                })
            emit("assistant", blocks)
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            system_chunks.append(message.instructions)
        blocks = []
        for part in message.parts:
            if isinstance(part, SystemPart):
                system_chunks.append(part.content)
            elif isinstance(part, UserPart):
                blocks.append({"type": "text", "text": content_str(part.content)})
            elif isinstance(part, ToolReturnPart):
                blocks.append({
                    "type": "tool_result",
                    "tool_use_id": part.tool_call_id,
                    "content": [{"type": "text", "text": content_str(part.content)}],
                })
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    blocks.append({
                        "type": "tool_result",
                        "tool_use_id": part.tool_call_id,
                        "is_error": True,
                        "content": [{"type": "text", "text": part.content}],
                    })
                else:
                    blocks.append({"type": "text", "text": part.content})
        emit("user", blocks)
    return "\n\n".join(system_chunks), rendered


def parse_anthropic_response(data: dict, model: str) -> ModelResponse:
    content = data.get("content")
    if not isinstance(content, list):
        raise ModelAPIError(f"anthropic response missing content: {data!r}"[:500])
    parts: list[Any] = []
    for block in content:
        kind = block.get("type")
        if kind == "text" and block.get("text"):
            parts.append(TextOutput(text=block["text"]))
        elif kind == "tool_use":
            parts.append(ToolCallOutput(
                tool_call_id=block.get("id", ""),
                tool_name=block.get("name", ""),
                args=block.get("input") or {},
            ))
    usage = data.get("usage") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("input_tokens", 0),
            output_tokens=usage.get("output_tokens", 0),
        ),
        model_name=data.get("model", model),
    )


class AnthropicModelClient(ModelClient):
    def __init__(
        self,
        model: str,
        *,
        api_key: str | None = None,
        base_url: str = _DEFAULT_BASE_URL,
        http_client: Any | None = None,
        default_max_tokens: int = _DEFAULT_MAX_TOKENS,
    ):
        self._model = model
        self._api_key = api_key or os.environ.get("ANTHROPIC_API_KEY", "")
        self._base_url = base_url.rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None
        self._default_max_tokens = default_max_tokens

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        # close only the DEFAULT client this instance created; a
        # caller-injected http_client= stays the caller's to close
        # (it may be shared across model clients)
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        system, rendered = render_anthropic_messages(messages)
        payload: dict[str, Any] = {
            "model": self._model,
            "messages": rendered,
            # max_tokens is REQUIRED by the API
            "max_tokens": settings.max_tokens or self._default_max_tokens,
        }
        if system:
            payload["system"] = system
        tools = [
            {
                "name": t.name,
                "description": t.description,
                "input_schema": t.parameters_schema,
            }
            for t in params.all_tools()
        ]
        if tools:
            payload["tools"] = tools
            if not params.allow_text_output:
                payload["tool_choice"] = {"type": "any"}
        if settings.temperature is not None:
            payload["temperature"] = settings.temperature
        if settings.top_p is not None:
            payload["top_p"] = settings.top_p
        if settings.top_k is not None:
            payload["top_k"] = settings.top_k
        if settings.stop_sequences:
            payload["stop_sequences"] = settings.stop_sequences
        payload.update(settings.extra)
        return payload

    def _headers(self) -> dict[str, str]:
        return {
            "x-api-key": self._api_key,
            "anthropic-version": _API_VERSION,
        }

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        data = await post_json(
            self._http(),
            f"{self._base_url}/v1/messages",
            headers=self._headers(),
            payload=self._build_payload(messages, settings, params),
            provider="anthropic",
        )
        return parse_anthropic_response(data, self._model)

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ):
        """SSE streaming: text_delta blocks yield TextDelta; tool_use
        blocks accumulate their input_json_delta; one ResponseDone."""
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        payload = self._build_payload(messages, settings, params)
        payload["stream"] = True

        text_chunks: list[str] = []
        tools_by_index: dict[int, dict] = {}
        usage = Usage()
        model_name = self._model
        terminated = False
        async for data in sse_lines(
            self._http(), f"{self._base_url}/v1/messages",
            headers=self._headers(), payload=payload, provider="anthropic",
        ):
            try:
                event = json.loads(data)
            except ValueError:
                continue
            kind = event.get("type")
            if kind == "error":
                # mid-stream failure (e.g. overloaded_error): a truncated
                # answer must not pass as success
                raise ModelAPIError(
                    f"anthropic mid-stream error: {event.get('error')}"[:500]
                )
            if kind == "message_start":
                message = event.get("message") or {}
                model_name = message.get("model", model_name)
                start_usage = message.get("usage") or {}
                usage = Usage(
                    input_tokens=start_usage.get("input_tokens", 0),
                    output_tokens=usage.output_tokens,
                )
            elif kind == "content_block_start":
                block = event.get("content_block") or {}
                if block.get("type") == "tool_use":
                    tools_by_index[event.get("index", 0)] = {
                        "id": block.get("id", ""),
                        "name": block.get("name", ""),
                        "json": "",
                    }
            elif kind == "content_block_delta":
                delta = event.get("delta") or {}
                if delta.get("type") == "text_delta" and delta.get("text"):
                    text_chunks.append(delta["text"])
                    yield TextDelta(delta["text"])
                elif delta.get("type") == "input_json_delta":
                    slot = tools_by_index.get(event.get("index", 0))
                    if slot is not None:
                        slot["json"] += delta.get("partial_json", "")
            elif kind == "message_delta":
                delta_usage = event.get("usage") or {}
                if delta_usage.get("output_tokens"):
                    usage = Usage(
                        input_tokens=usage.input_tokens,
                        output_tokens=delta_usage["output_tokens"],
                    )
            elif kind == "message_stop":
                terminated = True

        if not terminated:
            # a clean close without message_stop means the answer may be
            # truncated — that must not pass as success
            raise ModelAPIError(
                "anthropic stream closed without message_stop "
                "(response may be truncated)"
            )

        parts: list[Any] = []
        if text_chunks:
            parts.append(TextOutput(text="".join(text_chunks)))
        for index in sorted(tools_by_index):
            slot = tools_by_index[index]
            parts.append(ToolCallOutput(
                tool_call_id=slot["id"], tool_name=slot["name"],
                args=slot["json"] or "{}",
            ))
        yield ResponseDone(ModelResponse(
            parts=parts, usage=usage, model_name=model_name,
        ))
