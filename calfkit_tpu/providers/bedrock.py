"""AWS Bedrock model client over the Converse API (reference: the
vendored pydantic-ai bedrock adapter,
calfkit/_vendor/pydantic_ai/models/bedrock.py — there a botocore wrapper;
here the same ModelClient seam with no AWS SDK at all: a stdlib SigV4
signer, the Converse request/response mapping, and a binary
``application/vnd.amazon.eventstream`` decoder for ConverseStream).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import struct
import urllib.parse
import zlib
from typing import Any, AsyncIterator

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.providers.http import ModelAPIError, content_str


# ------------------------------------------------------------------ sigv4
def sigv4_headers(
    *,
    method: str,
    url: str,
    region: str,
    service: str,
    access_key: str,
    secret_key: str,
    session_token: str | None = None,
    payload: bytes = b"",
    now: "datetime.datetime | None" = None,
    extra_headers: "dict[str, str] | None" = None,
) -> dict[str, str]:
    """AWS Signature Version 4 over stdlib hmac/hashlib.

    Returns the headers to attach (Authorization, X-Amz-Date, Host, and
    X-Amz-Security-Token when a session token is given).  ``now`` is
    injectable so the signer can be pinned against the published AWS
    test vectors."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    path = parsed.path or "/"
    when = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = when.strftime("%Y%m%dT%H%M%SZ")
    datestamp = when.strftime("%Y%m%d")

    headers = {"host": host, "x-amz-date": amz_date}
    for name, value in (extra_headers or {}).items():
        headers[name.lower()] = value
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_names = sorted(headers)
    canonical_headers = "".join(
        f"{n}:{headers[n].strip()}\n" for n in signed_names
    )
    signed_headers = ";".join(signed_names)

    query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(urllib.parse.parse_qsl(
            parsed.query, keep_blank_values=True
        ))
    )
    payload_hash = hashlib.sha256(payload).hexdigest()
    canonical = "\n".join([
        method, urllib.parse.quote(path, safe="/-_.~"), query,
        canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, message: str) -> bytes:
        return hmac.new(key, message.encode(), hashlib.sha256).digest()

    key = _hmac(("AWS4" + secret_key).encode(), datestamp)
    key = _hmac(key, region)
    key = _hmac(key, service)
    key = _hmac(key, "aws4_request")
    signature = hmac.new(key, to_sign.encode(), hashlib.sha256).hexdigest()

    out = {
        "Host": host,
        "X-Amz-Date": amz_date,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }
    for name, value in (extra_headers or {}).items():
        out[name] = value
    if session_token:
        out["X-Amz-Security-Token"] = session_token
    return out


# ------------------------------------------------- eventstream (binary)
def decode_event_frames(buffer: bytearray) -> "list[tuple[dict, bytes]]":
    """Consume complete ``application/vnd.amazon.eventstream`` frames from
    ``buffer`` (mutated in place) → [(headers, payload)].

    Frame: u32 total_len | u32 headers_len | u32 prelude_crc |
    headers | payload | u32 message_crc — CRCs are zlib crc32 and are
    VERIFIED (a corrupt frame raises ModelAPIError rather than
    mis-parsing the stream)."""
    out: list[tuple[dict, bytes]] = []
    while len(buffer) >= 16:
        total_len, headers_len, prelude_crc = struct.unpack_from(
            ">III", buffer, 0
        )
        if zlib.crc32(bytes(buffer[:8])) != prelude_crc:
            raise ModelAPIError("bedrock eventstream prelude crc mismatch")
        if total_len < 16 or total_len > (16 << 20):
            raise ModelAPIError(
                f"bedrock eventstream frame length {total_len} implausible"
            )
        if len(buffer) < total_len:
            break
        frame = bytes(buffer[:total_len])
        (message_crc,) = struct.unpack_from(">I", frame, total_len - 4)
        if zlib.crc32(frame[:-4]) != message_crc:
            raise ModelAPIError("bedrock eventstream message crc mismatch")
        headers: dict[str, Any] = {}
        pos = 12
        end = 12 + headers_len
        while pos < end:
            name_len = frame[pos]
            pos += 1
            name = frame[pos:pos + name_len].decode("utf-8", "replace")
            pos += name_len
            value_type = frame[pos]
            pos += 1
            if value_type == 7:  # string
                (vlen,) = struct.unpack_from(">H", frame, pos)
                pos += 2
                headers[name] = frame[pos:pos + vlen].decode("utf-8", "replace")
                pos += vlen
            elif value_type == 6:  # byte array
                (vlen,) = struct.unpack_from(">H", frame, pos)
                pos += 2
                headers[name] = frame[pos:pos + vlen]
                pos += vlen
            elif value_type in (0, 1):  # bool true/false
                headers[name] = value_type == 0
            elif value_type == 2:
                headers[name] = frame[pos]
                pos += 1
            elif value_type == 3:
                (headers[name],) = struct.unpack_from(">h", frame, pos)
                pos += 2
            elif value_type == 4:
                (headers[name],) = struct.unpack_from(">i", frame, pos)
                pos += 4
            elif value_type in (5, 8):  # i64 / timestamp
                (headers[name],) = struct.unpack_from(">q", frame, pos)
                pos += 8
            elif value_type == 9:  # uuid
                headers[name] = frame[pos:pos + 16]
                pos += 16
            else:
                raise ModelAPIError(
                    f"bedrock eventstream unknown header type {value_type}"
                )
        out.append((headers, frame[end:total_len - 4]))
        del buffer[:total_len]
    return out


# ------------------------------------------------------ converse mapping
def render_converse(messages: list[ModelMessage]) -> tuple[list, list]:
    """Our wire vocabulary → Converse ``(system, messages)``.  Converse
    requires strictly alternating user/assistant turns, so adjacent
    same-role entries are merged."""
    system: list[dict] = []
    turns: list[dict] = []

    def push(role: str, blocks: list[dict]) -> None:
        if turns and turns[-1]["role"] == role:
            turns[-1]["content"].extend(blocks)
        else:
            turns.append({"role": role, "content": list(blocks)})

    for message in messages:
        if isinstance(message, ModelResponse):
            blocks: list[dict] = []
            text = message.text()
            if text:
                blocks.append({"text": text})
            for call in message.tool_calls():
                args = call.args
                if isinstance(args, str):
                    try:
                        args = json.loads(args or "{}")
                    except ValueError:
                        args = {"raw": args}
                blocks.append({"toolUse": {
                    "toolUseId": call.tool_call_id,
                    "name": call.tool_name,
                    "input": args,
                }})
            push("assistant", blocks)
            continue
        assert isinstance(message, ModelRequest)
        if message.instructions:
            system.append({"text": message.instructions})
        for part in message.parts:
            if isinstance(part, SystemPart):
                system.append({"text": part.content})
            elif isinstance(part, UserPart):
                push("user", [{"text": content_str(part.content)}])
            elif isinstance(part, ToolReturnPart):
                push("user", [{"toolResult": {
                    "toolUseId": part.tool_call_id,
                    "content": [{"text": content_str(part.content)}],
                    "status": "success",
                }}])
            elif isinstance(part, RetryPart):
                if part.tool_call_id:
                    push("user", [{"toolResult": {
                        "toolUseId": part.tool_call_id,
                        "content": [{"text": part.content}],
                        "status": "error",
                    }}])
                else:
                    push("user", [{"text": part.content}])
    return system, turns


def parse_converse(data: dict, model: str) -> ModelResponse:
    try:
        content = data["output"]["message"]["content"]
    except (KeyError, TypeError) as exc:
        raise ModelAPIError(
            f"bedrock response missing output.message: {data!r}"[:500]
        ) from exc
    parts: list[Any] = []
    for block in content:
        if "text" in block:
            parts.append(TextOutput(text=block["text"]))
        elif "toolUse" in block:
            use = block["toolUse"]
            parts.append(ToolCallOutput(
                tool_call_id=use.get("toolUseId", ""),
                tool_name=use.get("name", ""),
                args=json.dumps(use.get("input") or {}),
            ))
    usage = data.get("usage") or {}
    return ModelResponse(
        parts=parts,
        usage=Usage(
            input_tokens=usage.get("inputTokens", 0),
            output_tokens=usage.get("outputTokens", 0),
        ),
        model_name=model,
    )


class BedrockModelClient(ModelClient):
    """Converse / ConverseStream over httpx with stdlib SigV4 — no
    botocore.  Credentials default to the standard AWS env vars."""

    def __init__(
        self,
        model: str,
        *,
        region: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        session_token: str | None = None,
        base_url: str | None = None,
        http_client: Any | None = None,
    ):
        self._model = model
        self._region = region or os.environ.get("AWS_REGION", "us-east-1")
        self._access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self._secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", ""
        )
        self._session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN"
        ) or None
        self._base_url = (base_url or (
            f"https://bedrock-runtime.{self._region}.amazonaws.com"
        )).rstrip("/")
        self._client = http_client
        self._owns_client = http_client is None

    @property
    def model_name(self) -> str:
        return self._model

    def _http(self) -> Any:
        if self._client is None:
            import httpx

            self._client = httpx.AsyncClient(timeout=120.0)
            self._owns_client = True
        return self._client

    async def aclose(self) -> None:
        if self._client is not None and self._owns_client:
            await self._client.aclose()
            self._client = None

    def _build_payload(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings,
        params: ModelRequestParameters,
    ) -> dict[str, Any]:
        system, turns = render_converse(messages)
        payload: dict[str, Any] = {"messages": turns}
        if system:
            payload["system"] = system
        config: dict[str, Any] = {}
        if settings.max_tokens is not None:
            config["maxTokens"] = settings.max_tokens
        if settings.temperature is not None:
            config["temperature"] = settings.temperature
        if settings.top_p is not None:
            config["topP"] = settings.top_p
        if settings.stop_sequences:
            config["stopSequences"] = settings.stop_sequences
        if config:
            payload["inferenceConfig"] = config
        tools = [
            {"toolSpec": {
                "name": t.name,
                "description": t.description or t.name,
                "inputSchema": {"json": t.parameters_schema},
            }}
            for t in params.all_tools()
        ]
        if tools:
            payload["toolConfig"] = {
                "tools": tools,
                "toolChoice": (
                    {"auto": {}} if params.allow_text_output else {"any": {}}
                ),
            }
        payload.update(settings.extra)
        return payload

    def _signed(self, url: str, body: bytes) -> dict[str, str]:
        return sigv4_headers(
            method="POST", url=url, region=self._region, service="bedrock",
            access_key=self._access_key, secret_key=self._secret_key,
            session_token=self._session_token, payload=body,
            extra_headers={"content-type": "application/json"},
        )

    def _url(self, verb: str) -> str:
        model = urllib.parse.quote(self._model, safe="")
        return f"{self._base_url}/model/{model}/{verb}"

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        body = json.dumps(
            self._build_payload(messages, settings, params)
        ).encode()
        url = self._url("converse")
        response = await self._http().post(
            url, content=body, headers=self._signed(url, body)
        )
        if response.status_code >= 400:
            raise ModelAPIError(
                f"bedrock converse {response.status_code}: "
                f"{response.text[:300]}",
                status=response.status_code, body=response.text,
            )
        return parse_converse(response.json(), self._model)

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> "AsyncIterator[Any]":
        """ConverseStream: binary eventstream → TextDelta per text delta,
        toolUse blocks accumulated per contentBlockIndex, one
        ResponseDone after messageStop."""
        settings = settings or ModelSettings()
        params = params or ModelRequestParameters()
        body = json.dumps(
            self._build_payload(messages, settings, params)
        ).encode()
        url = self._url("converse-stream")

        text_chunks: list[str] = []
        tools: dict[int, dict] = {}
        usage = Usage()
        stopped = False
        buffer = bytearray()
        async with self._http().stream(
            "POST", url, content=body, headers=self._signed(url, body)
        ) as response:
            if response.status_code >= 400:
                raw = await response.aread()
                raise ModelAPIError(
                    f"bedrock converse-stream {response.status_code}: "
                    f"{raw[:300]!r}",
                    status=response.status_code,
                    body=raw.decode("utf-8", "replace"),
                )
            async for chunk in response.aiter_bytes():
                buffer.extend(chunk)
                for headers, payload in decode_event_frames(buffer):
                    if headers.get(":message-type") == "exception":
                        raise ModelAPIError(
                            f"bedrock mid-stream exception "
                            f"{headers.get(':exception-type')}: "
                            f"{payload[:300]!r}"
                        )
                    event_type = headers.get(":event-type", "")
                    try:
                        event = json.loads(payload) if payload else {}
                    except ValueError:
                        continue
                    if event_type == "contentBlockStart":
                        start = (event.get("start") or {}).get("toolUse")
                        if start:
                            tools[event.get("contentBlockIndex", 0)] = {
                                "id": start.get("toolUseId", ""),
                                "name": start.get("name", ""),
                                "input": "",
                            }
                    elif event_type == "contentBlockDelta":
                        delta = event.get("delta") or {}
                        if "text" in delta:
                            text_chunks.append(delta["text"])
                            yield TextDelta(delta["text"])
                        elif "toolUse" in delta:
                            index = event.get("contentBlockIndex", 0)
                            slot = tools.setdefault(
                                index, {"id": "", "name": "", "input": ""}
                            )
                            slot["input"] += delta["toolUse"].get("input", "")
                    elif event_type == "messageStop":
                        stopped = True
                    elif event_type == "metadata" and event.get("usage"):
                        usage = Usage(
                            input_tokens=event["usage"].get("inputTokens", 0),
                            output_tokens=event["usage"].get("outputTokens", 0),
                        )
        if not stopped:
            raise ModelAPIError(
                "bedrock stream closed without messageStop "
                "(response may be truncated)"
            )
        parts: list[Any] = []
        if text_chunks:
            parts.append(TextOutput(text="".join(text_chunks)))
        for index in sorted(tools):
            slot = tools[index]
            parts.append(ToolCallOutput(
                tool_call_id=slot["id"], tool_name=slot["name"],
                args=slot["input"] or "{}",
            ))
        yield ResponseDone(ModelResponse(
            parts=parts, usage=usage, model_name=self._model,
        ))
