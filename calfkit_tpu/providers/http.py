"""Shared HTTP plumbing for the remote providers."""

from __future__ import annotations

from typing import Any

import json

from calfkit_tpu.exceptions import CalfkitError


def content_str(content: Any) -> str:
    """Coerce arbitrary tool-return / user content to transportable text."""
    from calfkit_tpu.models.payload import render_parts_as_text

    if isinstance(content, str):
        return content
    if isinstance(content, list):
        try:
            return render_parts_as_text(content)
        except Exception:  # noqa: BLE001
            return str(content)
    try:
        return json.dumps(content)
    except (TypeError, ValueError):
        return str(content)


class ModelAPIError(CalfkitError):
    """A remote model API failure (non-2xx or malformed payload).

    ``error_code`` / ``error_message`` carry the provider's STRUCTURED error
    fields (OpenAI ``error.code``/``error.type``, Anthropic
    ``error.type``/``error.message``) when the body parsed — classification
    downstream (engine/turn.py) prefers these over substring-matching the
    raw body, which can echo user text."""

    def __init__(self, message: str, *, status: int | None = None,
                 body: str | None = None):
        self.status = status
        # parse the UNTRUNCATED body (truncation would cut the JSON and
        # silently demote classification to the substring fallback), then
        # truncate for storage
        self.error_code, self.error_message = _parse_error_fields(body or "")
        self.body = (body or "")[:2000]
        super().__init__(
            f"{message}" + (f" (HTTP {status})" if status else "")
            + (f": {self.body[:400]}" if self.body else "")
        )


def _parse_error_fields(body: str) -> tuple[str | None, str | None]:
    """Extract (code-or-type, provider message) from a JSON error body."""
    if not body:
        return None, None
    try:
        data = json.loads(body)
    except ValueError:
        return None, None
    err = data.get("error") if isinstance(data, dict) else None
    if not isinstance(err, dict):
        return None, None
    # first STRING among code/type — some backends put an int HTTP status in
    # 'code', which must not shadow a usable string 'type'
    code = next(
        (v for v in (err.get("code"), err.get("type")) if isinstance(v, str)),
        None,
    )
    msg = err.get("message")
    return code, msg if isinstance(msg, str) else None


async def post_json(
    client: Any, url: str, *, headers: dict[str, str], payload: dict,
    provider: str,
) -> dict:
    """POST and decode, normalizing every failure into ModelAPIError."""
    import httpx

    try:
        response = await client.post(url, headers=headers, json=payload)
    except httpx.HTTPError as exc:
        raise ModelAPIError(f"{provider} request failed: {exc}") from exc
    if response.status_code // 100 != 2:
        raise ModelAPIError(
            f"{provider} API error", status=response.status_code,
            body=response.text,
        )
    try:
        return response.json()
    except ValueError as exc:
        raise ModelAPIError(
            f"{provider} returned non-JSON", status=response.status_code,
            body=response.text,
        ) from exc


async def sse_lines(client: Any, url: str, *, headers: dict[str, str],
                    payload: dict, provider: str):
    """POST with ``stream=True`` and yield SSE ``data:`` payload strings.

    Normalizes transport failures and non-2xx into ModelAPIError before the
    first yield, so callers can trust the stream once it starts."""
    import httpx

    try:
        async with client.stream(
            "POST", url, headers=headers, json=payload
        ) as response:
            if response.status_code // 100 != 2:
                body = (await response.aread()).decode("utf-8", "replace")
                raise ModelAPIError(
                    f"{provider} API error", status=response.status_code,
                    body=body,
                )
            async for line in response.aiter_lines():
                line = line.strip()
                if line.startswith("data:"):
                    yield line[5:].strip()
    except httpx.HTTPError as exc:
        raise ModelAPIError(f"{provider} stream failed: {exc}") from exc
