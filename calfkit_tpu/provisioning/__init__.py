"""Topic provisioning (SURVEY.md §1 layer 9)."""

from calfkit_tpu.provisioning.provisioner import (
    ProvisioningConfig,
    framework_topics_for_nodes,
    provision,
    topics_for_nodes,
)

__all__ = [
    "ProvisioningConfig",
    "framework_topics_for_nodes",
    "provision",
    "topics_for_nodes",
]
