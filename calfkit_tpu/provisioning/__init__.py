"""Topic provisioning (SURVEY.md §1 layer 9)."""

from calfkit_tpu.provisioning.provisioner import (
    ProvisioningConfig,
    classify_topic_error,
    framework_topics_for_nodes,
    provision,
    topics_for_nodes,
)

__all__ = [
    "ProvisioningConfig",
    "classify_topic_error",
    "framework_topics_for_nodes",
    "provision",
    "topics_for_nodes",
]
