"""Topic-set derivation + idempotent creation.

Reference: calfkit/provisioning/provisioner.py:28-73 (``topics_for_nodes`` /
``framework_topics_for_nodes``) and the created/existing/unauthorized
classification at :13-18.  The transport's ``ensure_topics`` performs the
actual creation; this module owns which topics exist and why.
"""

from __future__ import annotations

import logging
from typing import Iterable

from pydantic import BaseModel

from calfkit_tpu import protocol
from calfkit_tpu.exceptions import ProvisioningError
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.nodes.base import BaseNodeDef

logger = logging.getLogger(__name__)


class ProvisioningConfig(BaseModel):
    enabled: bool = True
    include_framework: bool = True


def topics_for_nodes(nodes: Iterable[BaseNodeDef]) -> list[str]:
    """Every topic the nodes themselves consume or publish."""
    topics: set[str] = set()
    for node in nodes:
        topics.update(node.all_topics())
    return sorted(topics)


def framework_topics_for_nodes(nodes: Iterable[BaseNodeDef]) -> list[str]:
    """Framework-owned topics backing the nodes: control plane + durable
    fan-out tables (compacted)."""
    topics: set[str] = {protocol.AGENTS_TOPIC, protocol.CAPABILITIES_TOPIC}
    for node in nodes:
        topics.add(protocol.fanout_state_topic(node.node_id))
        topics.add(protocol.fanout_basestate_topic(node.node_id))
    return sorted(topics)


async def provision(
    transport: MeshTransport,
    nodes: Iterable[BaseNodeDef],
    config: ProvisioningConfig | None = None,
) -> dict[str, list[str]]:
    """Create all topics for ``nodes``; returns {"plain": [...], "compacted":
    [...]} of what was ensured.  Raises ProvisioningError on failure."""
    config = config or ProvisioningConfig()
    if not config.enabled:
        return {"plain": [], "compacted": []}
    nodes = list(nodes)
    plain = topics_for_nodes(nodes)
    compacted = framework_topics_for_nodes(nodes) if config.include_framework else []
    try:
        await transport.ensure_topics(plain)
        if compacted:
            await transport.ensure_topics(compacted, compacted=True)
    except Exception as exc:  # noqa: BLE001
        raise ProvisioningError(f"topic provisioning failed: {exc}") from exc
    logger.info(
        "provisioned %d topics (%d compacted)", len(plain) + len(compacted),
        len(compacted),
    )
    return {"plain": plain, "compacted": compacted}
