"""Topic-set derivation + idempotent creation with error classification.

Reference: calfkit/provisioning/provisioner.py:28-73 (``topics_for_nodes`` /
``framework_topics_for_nodes``) and the created/existing/unauthorized/retry
classification at :13-18.  The transport's ``ensure_topics`` performs the
actual creation; this module owns which topics exist, why, and how their
creation failures are treated:

- **existing** — another worker won the race; success.
- **retry** — transient broker trouble (timeouts, leader elections,
  connection loss); bounded backoff, then give up loudly.
- **unauthorized** — an ACL problem no retry will fix; fail immediately
  with a message that says so (the reference's most important distinction:
  an unauthorized cluster must not look like a flaky one).
- **fatal** — everything else; fail immediately.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Iterable

from pydantic import BaseModel, ConfigDict, Field

from calfkit_tpu import protocol
from calfkit_tpu.exceptions import ProvisioningError
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.nodes.base import BaseNodeDef

logger = logging.getLogger(__name__)


class ProvisioningConfig(BaseModel):
    model_config = ConfigDict(extra="forbid", frozen=True)

    enabled: bool = True
    include_framework: bool = True
    max_attempts: int = Field(3, ge=1)
    retry_backoff_s: float = Field(0.5, ge=0.0)


_EXISTING_MARKERS = ("alreadyexists", "already exists")
_UNAUTHORIZED_MARKERS = (
    "authorization", "authentication", "unauthorized", "accessdenied",
    "saslauthentication", "aclauthorization",
)
_RETRIABLE_MARKERS = (
    "timeout", "timedout", "connection", "notcontroller", "retriable",
    "unavailable", "leadernotavailable", "notcoordinator", "networkerror",
    "nodenotready", "brokerresponseerror",
)


def classify_topic_error(exc: BaseException) -> str:
    """→ "existing" | "unauthorized" | "retry" | "fatal".

    Matching is by exception type name and message (transport-agnostic: the
    kafka client's error class names carry the semantics; other transports
    raise stdlib TimeoutError/ConnectionError which land in "retry").
    """
    haystack = f"{type(exc).__name__} {exc}".lower()
    if isinstance(exc, (PermissionError,)):
        return "unauthorized"
    for marker in _UNAUTHORIZED_MARKERS:
        if marker in haystack:
            return "unauthorized"
    for marker in _EXISTING_MARKERS:
        if marker in haystack:
            return "existing"
    if isinstance(exc, (TimeoutError, ConnectionError, OSError)):
        return "retry"
    for marker in _RETRIABLE_MARKERS:
        if marker in haystack:
            return "retry"
    return "fatal"


def topics_for_nodes(nodes: Iterable[BaseNodeDef]) -> list[str]:
    """Every topic the nodes themselves consume or publish."""
    topics: set[str] = set()
    for node in nodes:
        topics.update(node.all_topics())
    return sorted(topics)


def framework_topics_for_nodes(nodes: Iterable[BaseNodeDef]) -> list[str]:
    """Framework-owned topics backing the nodes: control plane + durable
    fan-out tables (compacted)."""
    topics: set[str] = {
        protocol.AGENTS_TOPIC,
        protocol.CAPABILITIES_TOPIC,
        protocol.ENGINE_STATS_TOPIC,
        protocol.TRACES_TOPIC,
        protocol.CALLER_LIVENESS_TOPIC,
    }
    for node in nodes:
        topics.add(protocol.fanout_state_topic(node.node_id))
        topics.add(protocol.fanout_basestate_topic(node.node_id))
    return sorted(topics)


async def provision(
    transport: MeshTransport,
    nodes: Iterable[BaseNodeDef],
    config: ProvisioningConfig | None = None,
) -> dict[str, list[str]]:
    """Create all topics for ``nodes``; returns {"plain": [...], "compacted":
    [...]} of what was ensured.  Raises ProvisioningError on failure."""
    config = config or ProvisioningConfig()
    if not config.enabled:
        return {"plain": [], "compacted": []}
    nodes = list(nodes)
    plain = topics_for_nodes(nodes)
    compacted = framework_topics_for_nodes(nodes) if config.include_framework else []

    class _ExistsInBatch(Exception):
        """Batch create hit an already-exists: fall back to per-topic."""

    async def attempt(names: list[str], *, compact: bool) -> None:
        for attempt in range(1, config.max_attempts + 1):
            try:
                await transport.ensure_topics(names, compacted=compact)
                return
            except Exception as exc:  # noqa: BLE001 - classified below
                kind = classify_topic_error(exc)
                if kind == "existing":
                    if len(names) > 1:
                        # one existing topic must not mask missing siblings
                        raise _ExistsInBatch from exc
                    return  # a racing worker created it: success
                if kind == "retry" and attempt < config.max_attempts:
                    delay = config.retry_backoff_s * (2 ** (attempt - 1))
                    logger.warning(
                        "topic provisioning attempt %d/%d failed (%s); "
                        "retrying in %.1fs: %s",
                        attempt, config.max_attempts, kind, delay, exc,
                    )
                    await asyncio.sleep(delay)
                    continue
                if kind == "unauthorized":
                    raise ProvisioningError(
                        "topic provisioning UNAUTHORIZED (no retry will "
                        f"fix this — grant create-topics ACLs or pre-create "
                        f"{names}): {exc}"
                    ) from exc
                raise ProvisioningError(
                    f"topic provisioning failed ({kind}, "
                    f"attempt {attempt}/{config.max_attempts}): {exc}"
                ) from exc

    async def ensure(names: list[str], *, compact: bool) -> None:
        if not names:
            return
        # layering note: in-repo transports implement ensure_topics
        # idempotently (KafkaWireMesh does its own batch→per-topic exists
        # handling), so this fallback is the cross-transport safety net for
        # implementations that DO surface already-exists errors
        try:
            await attempt(names, compact=compact)  # one round trip, usually
        except _ExistsInBatch:
            for name in names:  # fallback: per-topic, each one classified
                await attempt([name], compact=compact)

    await ensure(plain, compact=False)
    if compacted:
        await ensure(compacted, compact=True)
    logger.info(
        "provisioned %d topics (%d compacted)", len(plain) + len(compacted),
        len(compacted),
    )
    return {"plain": plain, "compacted": compacted}
