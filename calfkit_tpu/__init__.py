"""calfkit_tpu — a TPU-native decentralized multi-agent framework.

Agents run as independent event-driven services over a Kafka-compatible mesh;
model turns execute on a local JAX/XLA/Pallas inference backend instead of a
remote HTTPS API.  See SURVEY.md at the repo root for the full design map.

Public API (lazy — importing :mod:`calfkit_tpu` never pulls in JAX):

- ``Client`` / ``Worker`` — caller surface and serving host
- ``Agent`` / ``StatelessAgent`` / ``agent_tool`` / ``consumer`` — node kinds
- ``Tools`` / ``Toolboxes`` / ``Messaging`` / ``Handoff`` — selectors/peers
- ``models`` — the wire vocabulary
- ``JaxLocalModelClient`` — the local TPU inference provider
"""

from __future__ import annotations

from importlib import import_module
from typing import TYPE_CHECKING, Any

__version__ = "0.1.0"

_LAZY: dict[str, str] = {
    # caller surface + serving host
    "Client": "calfkit_tpu.client",
    "AgentGateway": "calfkit_tpu.client",
    "InvocationHandle": "calfkit_tpu.client",
    "InvocationResult": "calfkit_tpu.client",
    "EventStream": "calfkit_tpu.client",
    "RunCompleted": "calfkit_tpu.client",
    "RunFailed": "calfkit_tpu.client",
    "Mesh": "calfkit_tpu.client",
    "Worker": "calfkit_tpu.worker",
    # node kinds + selectors
    "Agent": "calfkit_tpu.nodes",
    "StatelessAgent": "calfkit_tpu.nodes",
    "BaseNodeDef": "calfkit_tpu.nodes",
    "agent_tool": "calfkit_tpu.nodes",
    "consumer": "calfkit_tpu.nodes",
    "ConsumerNode": "calfkit_tpu.nodes",
    "Tools": "calfkit_tpu.nodes",
    "render_fault_for_model": "calfkit_tpu.nodes",
    "surface_to_model": "calfkit_tpu.nodes",
    "Toolbox": "calfkit_tpu.mcp",
    "Toolboxes": "calfkit_tpu.mcp",
    "MCPToolboxNode": "calfkit_tpu.mcp",
    "MCPServerSpec": "calfkit_tpu.mcp",
    "Messaging": "calfkit_tpu.peers",
    "Handoff": "calfkit_tpu.peers",
    # fleet routing (replicated engines; ISSUE 7)
    "FleetRouter": "calfkit_tpu.fleet",
    "FailoverPolicy": "calfkit_tpu.fleet",
    "ReplicaRegistry": "calfkit_tpu.fleet",
    # faults + exceptions
    "NodeFaultError": "calfkit_tpu.exceptions",
    "ClientTimeoutError": "calfkit_tpu.exceptions",
    "ClientClosedError": "calfkit_tpu.exceptions",
    "DeserializationError": "calfkit_tpu.exceptions",
    "MeshUnavailableError": "calfkit_tpu.exceptions",
    "LifecycleConfigError": "calfkit_tpu.exceptions",
    "FaultTypes": "calfkit_tpu.models",
    "ErrorReport": "calfkit_tpu.models",
    "ExceptionInfo": "calfkit_tpu.models",
    # control plane + provisioning + tuning
    "ControlPlaneConfig": "calfkit_tpu.controlplane",
    "ControlPlaneRecord": "calfkit_tpu.controlplane",
    "ControlPlaneStamp": "calfkit_tpu.controlplane",
    "ControlPlaneView": "calfkit_tpu.controlplane",
    "ProvisioningConfig": "calfkit_tpu.provisioning",
    "FanoutConfig": "calfkit_tpu.tuning",
    # transports
    "InMemoryMesh": "calfkit_tpu.mesh",
    "TcpMesh": "calfkit_tpu.mesh",
    "KafkaWireMesh": "calfkit_tpu.mesh",
    "ConnectionProfile": "calfkit_tpu.mesh",
    "WireSecurity": "calfkit_tpu.mesh",
    # observability: tracing + metrics (dependency-free)
    "TraceContext": "calfkit_tpu.observability",
    "Tracer": "calfkit_tpu.observability",
    "MetricsRegistry": "calfkit_tpu.observability",
    "MetricsServer": "calfkit_tpu.observability",
    "metrics_text": "calfkit_tpu.observability",
    # model clients (local TPU path + remote adapters)
    "JaxLocalModelClient": "calfkit_tpu.inference",
    "EchoModelClient": "calfkit_tpu.engine",
    "FunctionModelClient": "calfkit_tpu.engine",
    "OpenAIModelClient": "calfkit_tpu.providers",
    "OpenAIResponsesModelClient": "calfkit_tpu.providers",
    "AnthropicModelClient": "calfkit_tpu.providers",
    "GeminiModelClient": "calfkit_tpu.providers",
    "MistralModelClient": "calfkit_tpu.providers",
    "BedrockModelClient": "calfkit_tpu.providers",
    "FallbackModelClient": "calfkit_tpu.providers",
}

if TYPE_CHECKING:  # pragma: no cover
    from calfkit_tpu.client import Client
    from calfkit_tpu.exceptions import NodeFaultError
    from calfkit_tpu.worker import Worker


def __getattr__(name: str) -> Any:
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    try:
        return getattr(import_module(module), name)
    except ModuleNotFoundError as exc:
        # only mask the *target* module being absent, never its dependencies
        if exc.name == module:
            raise AttributeError(
                f"{name!r} requires {module!r}, which is not available in this build"
            ) from exc
        raise


def __dir__() -> list[str]:
    return sorted(set(globals()) | set(_LAZY))
