"""The worker-owned heartbeat publisher.

Contract (reference: calfkit/controlplane/publisher.py:42-127):

- the FIRST publish of every advert is fail-loud: a worker that cannot
  announce itself must not report a healthy boot;
- subsequent ticks are resilient: a transient publish failure logs WARNING
  and the loop continues;
- shutdown cancels the tick task BEFORE writing tombstones, so a tick can't
  resurrect a record mid-withdrawal.
"""

from __future__ import annotations

import asyncio
import logging
import time
import weakref
from dataclasses import dataclass
from typing import Any

from calfkit_tpu import cancellation
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.models.records import ControlPlaneRecord, ControlPlaneStamp
from calfkit_tpu.observability.metrics import REGISTRY
from calfkit_tpu.controlplane.config import ControlPlaneConfig

logger = logging.getLogger(__name__)

# a REAL staleness signal (ISSUE 4 satellite): computed at scrape time
# from the last successful publish, so a wedged heartbeat loop shows a
# climbing number instead of a frozen last-write.  Directory readers see
# staleness per node via ControlPlaneStamp.heartbeat_at; this gauge is
# the LOCAL view — "is MY publisher still getting beats out?" — which is
# what a node-level alert needs when the broker (and thus the directory)
# is the thing that broke.
_HB_STALENESS = REGISTRY.gauge(
    "calfkit_heartbeat_staleness_s",
    "seconds since this process's last successful control-plane "
    "heartbeat publish (scrape-time computed)",
)


def _bind_staleness(publisher: "ControlPlanePublisher") -> None:
    """Point the gauge at ``publisher`` without pinning it alive: a
    collected (or stopped) publisher reads as 0 rather than climbing
    forever on a process that deliberately shut its control plane."""
    ref = weakref.ref(publisher)

    def staleness() -> float:
        p = ref()
        if p is None or p._last_beat_at is None:
            return 0.0
        return max(0.0, time.monotonic() - p._last_beat_at)

    _HB_STALENESS.set_fn(staleness)


@dataclass(frozen=True)
class Advert:
    topic: str
    node_name: str
    node_kind: str
    instance_id: str
    payload: dict[str, Any]  # AgentCard / CapabilityRecord dump
    # re-derives the payload per heartbeat tick so runtime changes (e.g. an
    # MCP toolbox re-listing after tools/list_changed) reach the directory
    payload_fn: Any = None  # Callable[[], dict] | None

    @property
    def key(self) -> str:
        return f"{self.node_name}@{self.instance_id}"

    def current_payload(self) -> dict[str, Any]:
        if self.payload_fn is not None:
            try:
                return self.payload_fn()
            except Exception:  # noqa: BLE001 - fall back to the boot snapshot
                logger.warning(
                    "advert payload refresh failed for %s", self.key, exc_info=True
                )
        return self.payload


class ControlPlanePublisher:
    def __init__(
        self,
        transport: MeshTransport,
        adverts: list[Advert],
        config: ControlPlaneConfig | None = None,
    ):
        self._transport = transport
        self._adverts = adverts
        self._config = config or ControlPlaneConfig()
        self._writers = {
            topic: transport.table_writer(topic)
            for topic in {a.topic for a in adverts}
        }
        self._task: asyncio.Task[None] | None = None
        # liveness stamps ride the ONE deadline clock (cancellation.
        # wall_clock): readers compare heartbeat_at against the same seam,
        # so a chaos virtual clock drives staleness deterministically
        self._started_at = cancellation.wall_clock()
        self._last_beat_at: float | None = None  # monotonic; None pre-start

    def _record(self, advert: Advert) -> ControlPlaneRecord:
        return ControlPlaneRecord(
            stamp=ControlPlaneStamp(
                node_name=advert.node_name,
                node_kind=advert.node_kind,
                instance_id=advert.instance_id,
                started_at=self._started_at,
                heartbeat_at=cancellation.wall_clock(),
            ),
            record=advert.current_payload(),
        )

    async def start(self, *, ensure: bool = True) -> None:
        if ensure:  # False when the worker's provisioner owns topic admin
            topics = sorted(self._writers)
            await self._transport.ensure_topics(topics, compacted=True)
        # first adverts: fail-loud
        for advert in self._adverts:
            await self._writers[advert.topic].put(
                advert.key, self._record(advert).to_wire()
            )
        self._last_beat_at = time.monotonic()
        _bind_staleness(self)
        self._task = asyncio.get_running_loop().create_task(
            self._beat(), name="control-plane-heartbeat"
        )

    async def _beat(self) -> None:
        while True:
            await asyncio.sleep(self._config.heartbeat_interval)
            beat_ok = bool(self._adverts)
            for advert in self._adverts:
                try:
                    await self._writers[advert.topic].put(
                        advert.key, self._record(advert).to_wire()
                    )
                except Exception:  # noqa: BLE001 - per-tick resilience
                    beat_ok = False
                    logger.warning(
                        "heartbeat publish failed for %s (retrying next tick)",
                        advert.key,
                        exc_info=True,
                    )
            if beat_ok:
                # only a fully-successful tick resets staleness: a tick
                # where any advert failed leaves the gauge climbing
                self._last_beat_at = time.monotonic()

    async def stop(self) -> None:
        # cancel BEFORE tombstoning: no tick may resurrect a record
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        for advert in self._adverts:
            try:
                await self._writers[advert.topic].tombstone(advert.key)
            except Exception:  # noqa: BLE001
                logger.warning("tombstone failed for %s", advert.key, exc_info=True)
        # a DELIBERATELY stopped publisher must read as 0 staleness, not
        # climb forever: the publisher object may stay referenced (the
        # control plane holds it), so the weakref alone doesn't cover this
        self._last_beat_at = None
