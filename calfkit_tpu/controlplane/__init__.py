"""Control plane: discovery + liveness over compacted mesh tables
(SURVEY.md §1 layer 5)."""

from calfkit_tpu.controlplane.config import ControlPlaneConfig
from calfkit_tpu.controlplane.publisher import ControlPlanePublisher
from calfkit_tpu.controlplane.view import ControlPlaneView
from calfkit_tpu.controlplane.plane import ControlPlane
from calfkit_tpu.models.records import ControlPlaneRecord, ControlPlaneStamp

__all__ = [
    "ControlPlane",
    "ControlPlaneConfig",
    "ControlPlaneRecord",
    "ControlPlaneStamp",
    "ControlPlanePublisher",
    "ControlPlaneView",
]
