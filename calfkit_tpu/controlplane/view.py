"""Typed read views over control-plane tables.

A view collapses instance-keyed records (``<name>@<instance>``) to ONE live
record per node name: freshest heartbeat wins, stale instances
(``now - heartbeat_at ≥ stale_after``) and foreign schema versions are
filtered out (reference: calfkit/controlplane/view.py:67-195 — including the
surfaced health: ``status``/``failure``/``is_caught_up``).
"""

from __future__ import annotations

import logging
from typing import Generic, Type, TypeVar

from pydantic import BaseModel, ValidationError

from calfkit_tpu import cancellation
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.models.records import SCHEMA_VERSION, ControlPlaneRecord

logger = logging.getLogger(__name__)

RecordT = TypeVar("RecordT", bound=BaseModel)


class ControlPlaneView(Generic[RecordT]):
    def __init__(
        self,
        transport: MeshTransport,
        topic: str,
        record_type: Type[RecordT],
        *,
        stale_after: float = 15.0,
        catchup_timeout: float = 30.0,
    ):
        self._reader = transport.table_reader(topic)
        self._topic = topic
        self._record_type = record_type
        self._stale_after = stale_after
        self._catchup_timeout = catchup_timeout
        self._status = "new"  # new -> catching_up -> live | failed
        self._failure: str | None = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        self._status = "catching_up"
        try:
            await self._reader.start(timeout=self._catchup_timeout)
        except Exception as exc:  # noqa: BLE001
            self._status = "failed"
            self._failure = f"catch-up failed: {exc}"
            raise
        self._status = "live"

    async def stop(self) -> None:
        await self._reader.stop()
        self._status = "new"

    # -------------------------------------------------------------- health
    @property
    def status(self) -> str:
        return self._status

    @property
    def failure(self) -> str | None:
        return self._failure

    @property
    def is_caught_up(self) -> bool:
        return self._status == "live" and self._reader.is_caught_up

    # --------------------------------------------------------------- reads
    def _live_members(self) -> dict[str, ControlPlaneRecord]:
        """name -> freshest live instance record."""
        # same clock seam the publisher stamps with (chaos-patchable)
        now = cancellation.wall_clock()
        best: dict[str, ControlPlaneRecord] = {}
        for key, raw in self._reader.items().items():
            try:
                record = ControlPlaneRecord.from_wire(raw)
            except (ValidationError, ValueError):
                logger.debug("undecodable control-plane record %s", key)
                continue
            if record.schema_version != SCHEMA_VERSION:
                continue
            if now - record.stamp.heartbeat_at >= self._stale_after:
                continue
            name = record.stamp.node_name
            incumbent = best.get(name)
            if (
                incumbent is None
                or record.stamp.heartbeat_at > incumbent.stamp.heartbeat_at
            ):
                best[name] = record
        return best

    def records(self) -> list[RecordT]:
        """One typed payload per live node."""
        out: list[RecordT] = []
        for record in self._live_members().values():
            try:
                out.append(self._record_type.model_validate(record.record))
            except ValidationError:
                logger.debug(
                    "control-plane payload failed %s validation",
                    self._record_type.__name__,
                )
        return out

    async def barrier(self) -> None:
        await self._reader.barrier()
