"""Control-plane tuning (reference: calfkit/controlplane/config.py)."""

from __future__ import annotations

from pydantic import BaseModel, ConfigDict, Field


class ControlPlaneConfig(BaseModel):
    model_config = ConfigDict(extra="forbid", frozen=True)

    enabled: bool = True
    heartbeat_interval: float = Field(default=5.0, gt=0)
    # a node is live while now - heartbeat_at < stale_multiplier × interval
    stale_multiplier: float = Field(default=3.0, ge=1.0)
    catchup_timeout: float = Field(default=30.0, gt=0)

    @property
    def stale_after(self) -> float:
        return self.heartbeat_interval * self.stale_multiplier
