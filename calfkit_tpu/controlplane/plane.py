"""ControlPlane: the worker-side wiring of publisher + views.

``Worker`` calls :meth:`attach` at boot (reference: the auto-registration in
calfkit/worker/worker.py:197-330): every hosted node's adverts start
heartbeating, and capability/agents views are attached to node resources so
selectors (`Tools(discover=True)`, `Messaging`, `Handoff`) resolve live.
"""

from __future__ import annotations

import logging
from typing import Any

from calfkit_tpu import protocol
from calfkit_tpu.controlplane.config import ControlPlaneConfig
from calfkit_tpu.controlplane.publisher import Advert, ControlPlanePublisher
from calfkit_tpu.controlplane.view import ControlPlaneView
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.capability import CapabilityRecord

logger = logging.getLogger(__name__)

CAPABILITY_VIEW_KEY = "capability_view"
AGENTS_VIEW_KEY = "agents_view"
# set truthy on every node once the caller-liveness feed is consuming:
# the node kernel only ENFORCES leases (registers runs for the orphan
# reaper) where beats can actually arrive — a worker with no control
# plane must not orphan a live caller's run one TTL after admission
CALLER_LIVENESS_FEED_KEY = "caller_liveness_feed"


class _Attached:
    def __init__(
        self,
        publisher: ControlPlanePublisher,
        views: list[ControlPlaneView[Any]],
        liveness: Any = None,  # caller-liveness feed subscription
        runs_feed: Any = None,  # mesh.runs feed subscription (ISSUE 17)
    ):
        self._publisher = publisher
        self._views = views
        self._liveness = liveness
        self._runs_feed = runs_feed

    async def stop(self) -> None:
        await self._publisher.stop()  # tombstones first
        for view in self._views:
            try:
                await view.stop()
            except Exception:  # noqa: BLE001
                logger.debug("view stop failed", exc_info=True)
        for feed, label in (
            (self._liveness, "liveness"),
            (self._runs_feed, "runs"),
        ):
            if feed is not None:
                try:
                    await feed.stop()
                except Exception:  # noqa: BLE001
                    logger.debug("%s feed stop failed", label, exc_info=True)


async def _fold_caller_liveness(record: Any) -> None:
    """The caller-liveness feed handler (ISSUE 10): fold every beat /
    tombstone on ``mesh.caller_liveness`` into the process-wide lease
    store the engine's orphan reaper reads.  Fail-open by construction
    (``fold_liveness_record`` drops undecodables)."""
    from calfkit_tpu import leases

    leases.fold_liveness_record(record.key, record.value)


async def _fold_run_record(record: Any) -> None:
    """The ``mesh.runs`` feed handler (ISSUE 17): fold every finished
    run record into the process-wide window store the SLO adverts read.
    Fail-open by construction (the store drops undecodables)."""
    from calfkit_tpu.observability.runledger import run_window_store

    run_window_store().fold(record.key, record.value)


class ControlPlane:
    def __init__(self, config: ControlPlaneConfig | None = None):
        self.config = config or ControlPlaneConfig()

    def adverts_for(self, node: Any) -> list[Advert]:
        adverts: list[Advert] = []
        if hasattr(node, "agent_card"):
            card: AgentCard = node.agent_card()
            adverts.append(
                Advert(
                    topic=protocol.AGENTS_TOPIC,
                    node_name=card.name,
                    node_kind=node.kind,
                    instance_id=node.instance_id,
                    payload=card.model_dump(),
                    payload_fn=lambda n=node: n.agent_card().model_dump(),
                )
            )

            # fleet SLO rollup (ISSUE 17): per-agent run-level window
            # stats, re-derived from the worker's mesh.runs fold on
            # every heartbeat tick — the per-host→per-zone rollup shape
            # the autoscaler consumes, published compacted to mesh.slo
            def slo_payload(n=node, agent=card.name):
                from calfkit_tpu import cancellation
                from calfkit_tpu.observability.runledger import (
                    run_window_store,
                )

                return run_window_store().rollup_for(
                    agent,
                    window_end=cancellation.wall_clock(),
                    node_id=n.instance_id,
                ).model_dump()

            adverts.append(
                Advert(
                    topic=protocol.SLO_TOPIC,
                    node_name=card.name,
                    node_kind=node.kind,
                    instance_id=node.instance_id,
                    payload=slo_payload(),
                    payload_fn=slo_payload,
                )
            )
        if hasattr(node, "capability_record"):
            record: CapabilityRecord = node.capability_record()
            adverts.append(
                Advert(
                    topic=protocol.CAPABILITIES_TOPIC,
                    node_name=record.node_id,
                    node_kind=node.kind,
                    instance_id=node.instance_id,
                    payload=record.model_dump(),
                    payload_fn=lambda n=node: n.capability_record().model_dump(),
                )
            )
        if (
            hasattr(node, "engine_stats_record")
            and (stats := node.engine_stats_record()) is not None
        ):
            # live serving metrics, re-derived per heartbeat tick (SURVEY
            # §5: the TPU build surfaces tok/s, occupancy, memory)
            def stats_payload(n=node):
                snapshot = n.engine_stats_record()
                if snapshot is None:
                    # raise so the publisher's designed fallback (last good
                    # payload) applies — publishing {} would overwrite the
                    # compacted record with an unreadable one
                    raise RuntimeError("engine stats unavailable this tick")
                return snapshot

            adverts.append(
                Advert(
                    topic=protocol.ENGINE_STATS_TOPIC,
                    node_name=stats["node_id"],
                    node_kind=node.kind,
                    instance_id=node.instance_id,
                    payload=stats,
                    payload_fn=stats_payload,
                )
            )
        return adverts

    async def attach(self, worker: Any, *, ensure: bool = True) -> _Attached:
        transport = worker.mesh
        config = self.config

        capability_view: ControlPlaneView[CapabilityRecord] = ControlPlaneView(
            transport,
            protocol.CAPABILITIES_TOPIC,
            CapabilityRecord,
            stale_after=config.stale_after,
            catchup_timeout=config.catchup_timeout,
        )
        agents_view: ControlPlaneView[AgentCard] = ControlPlaneView(
            transport,
            protocol.AGENTS_TOPIC,
            AgentCard,
            stale_after=config.stale_after,
            catchup_timeout=config.catchup_timeout,
        )
        if ensure:  # False when the worker's provisioner already ran
            await transport.ensure_topics(
                [
                    protocol.AGENTS_TOPIC,
                    protocol.CAPABILITIES_TOPIC,
                    protocol.ENGINE_STATS_TOPIC,
                    protocol.TRACES_TOPIC,
                    protocol.CALLER_LIVENESS_TOPIC,
                    protocol.RUNS_TOPIC,
                    protocol.SLO_TOPIC,
                ],
                compacted=True,
            )
        # views catch up BEFORE serving: a turn must not resolve against a
        # half-read directory.  Anything started before a failure is stopped
        # again — a failed attach must not orphan readers.
        started: list[ControlPlaneView[Any]] = []
        liveness = None
        runs_feed = None
        try:
            for view in (capability_view, agents_view):
                await view.start()
                started.append(view)

            # caller-liveness feed (ISSUE 10): every worker folds the
            # compacted beat table into the process lease store, so the
            # engines it hosts can reap runs whose caller died — no
            # per-engine subscription, one feed per worker process
            liveness = await transport.subscribe(
                [protocol.CALLER_LIVENESS_TOPIC],
                _fold_caller_liveness,
                group_id=None,
                from_latest=False,
                ordered=False,
            )

            # runs feed (ISSUE 17): fold finished run records into the
            # process window store behind the per-agent SLO adverts —
            # same one-feed-per-worker shape as the liveness fold
            runs_feed = await transport.subscribe(
                [protocol.RUNS_TOPIC],
                _fold_run_record,
                group_id=None,
                from_latest=False,
                ordered=False,
            )

            adverts: list[Advert] = []
            for node in worker.nodes:
                adverts.extend(self.adverts_for(node))
                node.resources.setdefault(CAPABILITY_VIEW_KEY, capability_view)
                node.resources.setdefault(AGENTS_VIEW_KEY, agents_view)
                node.resources.setdefault(CALLER_LIVENESS_FEED_KEY, True)
            worker.resources.setdefault(CAPABILITY_VIEW_KEY, capability_view)
            worker.resources.setdefault(AGENTS_VIEW_KEY, agents_view)
            worker.resources.setdefault(CALLER_LIVENESS_FEED_KEY, True)

            publisher = ControlPlanePublisher(transport, adverts, config)
            await publisher.start(ensure=ensure)  # fail-loud first adverts
        except BaseException:
            for view in started:
                try:
                    await view.stop()
                except Exception:  # noqa: BLE001
                    logger.debug("view rollback stop failed", exc_info=True)
            for feed in (liveness, runs_feed):
                if feed is not None:
                    try:
                        await feed.stop()
                    except Exception:  # noqa: BLE001
                        logger.debug(
                            "feed rollback stop failed", exc_info=True
                        )
            raise
        logger.info(
            "control plane attached: %d adverts, views live", len(adverts)
        )
        return _Attached(
            publisher,
            [capability_view, agents_view],
            liveness=liveness,
            runs_feed=runs_feed,
        )
