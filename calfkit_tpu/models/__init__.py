"""Wire body models: everything that travels inside a mesh record.

Layering (reference: SURVEY.md §1 layer 1): these are pure pydantic models
with no transport or node dependencies.
"""

from calfkit_tpu.models.payload import (
    ContentPart,
    DataPart,
    FilePart,
    TextPart,
    ToolCallPart,
    is_retry,
    render_parts_as_text,
    retry_text_part,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ThinkingOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.models.marker import CallMarker, Marker, ToolCallMarker
from calfkit_tpu.models.error_report import ErrorReport, ExceptionInfo, FaultTypes
from calfkit_tpu.models.state import State
from calfkit_tpu.models.reply import FaultMessage, Reply, ReturnMessage
from calfkit_tpu.models.session_context import (
    CallFrame,
    Envelope,
    SessionContext,
    WorkflowState,
)
from calfkit_tpu.models.actions import Call, Next, NodeResult, ReturnCall, TailCall
from calfkit_tpu.models.step import (
    AgentMessageStep,
    HandoffStep,
    InferenceStep,
    Step,
    StepEvent,
    StepMessage,
    ThinkingStep,
    TokenStep,
    ToolCallStep,
    ToolResultStep,
)
from calfkit_tpu.models.fanout import (
    EnvelopeSnapshot,
    FanoutOpen,
    FanoutOutcome,
    FanoutState,
    SlotRef,
)
from calfkit_tpu.models.capability import CapabilityRecord, ToolDef, resolve_capability
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.records import ControlPlaneRecord, ControlPlaneStamp
from calfkit_tpu.models.tool_dispatch import ToolBinding, ToolCallRef
from calfkit_tpu.models.node_result import InvocationResult

__all__ = [
    "AgentCard",
    "AgentMessageStep",
    "Call",
    "CallFrame",
    "CallMarker",
    "CapabilityRecord",
    "ContentPart",
    "ControlPlaneRecord",
    "ControlPlaneStamp",
    "DataPart",
    "Envelope",
    "EnvelopeSnapshot",
    "ErrorReport",
    "ExceptionInfo",
    "FanoutOpen",
    "FanoutOutcome",
    "FanoutState",
    "FaultMessage",
    "FaultTypes",
    "FilePart",
    "HandoffStep",
    "InferenceStep",
    "InvocationResult",
    "Marker",
    "ModelMessage",
    "ModelRequest",
    "ModelResponse",
    "Next",
    "NodeResult",
    "Reply",
    "RetryPart",
    "ReturnCall",
    "ReturnMessage",
    "SessionContext",
    "SlotRef",
    "State",
    "Step",
    "StepEvent",
    "StepMessage",
    "SystemPart",
    "TailCall",
    "TextOutput",
    "TextPart",
    "ThinkingOutput",
    "ThinkingStep",
    "TokenStep",
    "ToolBinding",
    "ToolCallOutput",
    "ToolCallPart",
    "ToolCallRef",
    "ToolCallStep",
    "ToolDef",
    "ToolResultStep",
    "ToolReturnPart",
    "Usage",
    "UserPart",
    "WorkflowState",
    "is_retry",
    "render_parts_as_text",
    "resolve_capability",
    "retry_text_part",
]
