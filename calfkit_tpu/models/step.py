"""Step (telemetry) wire models — per-hop *semantic* events, not spans.

Two frozen families (reference: calfkit/models/step.py:96-186):

- wire ``*Step`` — identity-free facts minted by the node's step ledger and
  shipped in a :class:`StepMessage` to the run's root callback topic;
- surface :class:`StepEvent` — the caller-side projection with identity
  (correlation/task/node) stamped on, fed to ``handle.stream()`` and the
  client firehose.

Only the hop ledger may mint wire steps (single-authority rule); nodes return
facts, the ledger turns them into steps.  ``InferenceStep`` is new to the TPU
build: per-turn prefill/decode metrics from the local backend (SURVEY.md §5
tracing note).
"""

from __future__ import annotations

from typing import Annotated, Any, Literal, Union

from pydantic import BaseModel, ConfigDict, Field


class AgentMessageStep(BaseModel):
    model_config = ConfigDict(frozen=True)
    kind: Literal["agent_message"] = "agent_message"
    author: str | None = None
    text: str = ""


class ThinkingStep(BaseModel):
    """Defined but not emitted by default (parity with the reference)."""

    model_config = ConfigDict(frozen=True)
    kind: Literal["thinking"] = "thinking"
    author: str | None = None
    text: str = ""


class ToolCallStep(BaseModel):
    model_config = ConfigDict(frozen=True)
    kind: Literal["tool_call"] = "tool_call"
    tool_call_id: str
    tool_name: str
    args: dict[str, Any] = Field(default_factory=dict)
    denied: bool = False  # born-closed pair for calls denied before dispatch


class ToolResultStep(BaseModel):
    model_config = ConfigDict(frozen=True)
    kind: Literal["tool_result"] = "tool_result"
    tool_call_id: str
    tool_name: str
    ok: bool = True
    content: str = ""


class HandoffStep(BaseModel):
    model_config = ConfigDict(frozen=True)
    kind: Literal["handoff"] = "handoff"
    from_agent: str | None = None
    to_agent: str = ""


class TokenStep(BaseModel):
    """Incremental generated text from a streaming model turn.

    ``offset`` (ISSUE 10) is the absolute character offset of this chunk
    within the run's delivered answer text, stamped ONLY by a turn that
    RESUMED decode-from-offset (its first chunk starts at the
    delivered-prefix length) — the caller-side
    :class:`~calfkit_tpu.fleet.failover.StreamLedger` then dedupes
    exactly, suppressing nothing.  ``None`` (non-resumed turns,
    pre-ISSUE-10 emitters, internal output retries) rides the ledger's
    cumulative law, which carries across an agent's tool-calling turns."""

    model_config = ConfigDict(frozen=True)
    kind: Literal["token"] = "token"
    author: str | None = None
    text: str = ""
    offset: int | None = None


class InferenceStep(BaseModel):
    """Local-backend metrics for one model turn (TPU-build extension)."""

    model_config = ConfigDict(frozen=True)
    kind: Literal["inference"] = "inference"
    model_name: str = ""
    prefill_ms: float = 0.0
    decode_ms: float = 0.0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    batch_occupancy: float = 0.0
    tokens_per_second: float = 0.0


Step = Annotated[
    Union[
        AgentMessageStep,
        ThinkingStep,
        ToolCallStep,
        ToolResultStep,
        HandoffStep,
        TokenStep,
        InferenceStep,
    ],
    Field(discriminator="kind"),
]


class StepMessage(BaseModel):
    """Wire batch: every step minted during one hop, flushed once at hop exit."""
    steps: list[Step] = Field(default_factory=list)
    emitter: str = ""  # "<kind>/<name>" of the minting node

    def to_wire(self) -> bytes:
        return self.model_dump_json(exclude_none=True).encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "StepMessage":
        return cls.model_validate_json(data)


class StepEvent(BaseModel):
    """Surface event: a wire step with run identity stamped caller-side."""

    model_config = ConfigDict(frozen=True)
    correlation_id: str
    task_id: str | None = None
    node: str | None = None
    step: Step
