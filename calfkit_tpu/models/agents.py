"""Agent directory records (the ``mesh.agents`` compacted topic).

Reference: calfkit/models/agents.py:29-87 (AgentCard is name-keyed, carries a
bounded human description, and derives the agent's input topic so callers can
dispatch by name alone).
"""

from __future__ import annotations

from pydantic import BaseModel, Field, field_validator

from calfkit_tpu import protocol

MAX_DESCRIPTION = 512


class AgentCard(BaseModel):

    name: str
    description: str = ""
    structured_output: bool = False
    tools: list[str] = Field(default_factory=list)  # advertised tool names, directory only

    @field_validator("name")
    @classmethod
    def _name_topic_safe(cls, v: str) -> str:
        protocol.require_topic_safe(v, what="agent name")
        return v

    @field_validator("description")
    @classmethod
    def _bounded(cls, v: str) -> str:
        if len(v) > MAX_DESCRIPTION:
            raise ValueError(f"description exceeds {MAX_DESCRIPTION} chars")
        return v

    def derive_input_topic(self) -> str:
        return protocol.agent_input_topic(self.name)

    def derive_publish_topic(self) -> str:
        return protocol.agent_publish_topic(self.name)
