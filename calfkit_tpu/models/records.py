"""Control-plane record envelope: liveness stamp + schema version + payload.

Every control-plane table value is a :class:`ControlPlaneRecord` keyed by
``<node_name>@<instance_id>``; readers collapse instances to one live record
per node and filter by staleness and schema version (reference:
calfkit/controlplane/records.py:54, view at controlplane/view.py:116-123).
"""

from __future__ import annotations

import time
from typing import Any

from pydantic import BaseModel, Field

SCHEMA_VERSION = 1


class ControlPlaneStamp(BaseModel):

    node_name: str
    node_kind: str
    instance_id: str
    started_at: float = Field(default_factory=time.time)
    heartbeat_at: float = Field(default_factory=time.time)

    def key(self) -> str:
        return f"{self.node_name}@{self.instance_id}"


class ControlPlaneRecord(BaseModel):

    schema_version: int = SCHEMA_VERSION
    stamp: ControlPlaneStamp
    record: dict[str, Any] = Field(default_factory=dict)  # AgentCard / CapabilityRecord dump

    def to_wire(self) -> bytes:
        return self.model_dump_json().encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "ControlPlaneRecord":
        return cls.model_validate_json(data)
