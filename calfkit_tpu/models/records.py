"""Control-plane record envelope: liveness stamp + schema version + payload.

Every control-plane table value is a :class:`ControlPlaneRecord` keyed by
``<node_name>@<instance_id>``; readers collapse instances to one live record
per node and filter by staleness and schema version (reference:
calfkit/controlplane/records.py:54, view at controlplane/view.py:116-123).
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, Field

from calfkit_tpu import cancellation

SCHEMA_VERSION = 1


def _now() -> float:
    # through the module attribute, NOT a bound reference: liveness stamps
    # must follow the ONE deadline clock (ISSUE 5's wall_clock seam) so
    # the chaos harness's virtual clock governs staleness-based replica
    # eligibility deterministically — a time.time stamp here would make a
    # frozen-clock fleet scenario see every replica as stale (or fresh)
    # depending on the host's real clock, not the script
    return cancellation.wall_clock()


class ControlPlaneStamp(BaseModel):

    node_name: str
    node_kind: str
    instance_id: str
    started_at: float = Field(default_factory=_now)
    heartbeat_at: float = Field(default_factory=_now)

    def key(self) -> str:
        return f"{self.node_name}@{self.instance_id}"


class ControlPlaneRecord(BaseModel):

    schema_version: int = SCHEMA_VERSION
    stamp: ControlPlaneStamp
    record: dict[str, Any] = Field(default_factory=dict)  # AgentCard / CapabilityRecord dump

    def to_wire(self) -> bytes:
        return self.model_dump_json().encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "ControlPlaneRecord":
        return cls.model_validate_json(data)


class EngineStatsRecord(BaseModel):
    """Live serving metrics for one worker's inference engine, heartbeated
    on the control plane (SURVEY §5: the TPU build adds real metrics —
    tok/s, batch occupancy, memory — where the reference had only logs).

    Re-derived per heartbeat tick, so readers see a rolling snapshot with
    the same staleness semantics as agent liveness.
    """

    node_id: str
    model_name: str = ""
    platform: str = ""
    # fleet identity + routability (ISSUE 7): which replica instance this
    # record describes, the replica-addressed topic the router may publish
    # to ("" = not individually addressable, shared-topic only), and the
    # worker's readiness/drain state at heartbeat time.  Defaults read a
    # pre-fleet record as an anonymous, routable-only-via-shared-topic
    # replica that is serving — not as unknown.
    instance_id: str = ""
    replica_topic: str = ""
    ready: bool = True
    draining: bool = False
    tokens_per_second: float = 0.0
    mean_occupancy: float = 0.0
    active_requests: int = 0
    # requests admitted but not yet holding a slot (queued + carry + long
    # queue): active + pending is the router's queue-depth load signal
    pending_requests: int = 0
    free_slots: int = 0
    max_batch_size: int = 0
    kv_layout: str = "dense"
    free_pages: int | None = None  # paged layout only
    prefill_tokens: int = 0
    decode_tokens: int = 0
    decode_dispatches: int = 0
    # overlapped execution: double-buffered dispatch enabled, and pad
    # tokens discarded by one-dispatch-late retirement (the overlap tax).
    # Default False so a record from a pre-overlap engine (key absent)
    # reads as off/unknown, not as overlapped-with-zero-waste
    overlap_dispatch: bool = False
    overlap_wasted_tokens: int = 0
    # ragged unified prefill+decode waves (ISSUE 6): whether the fused
    # lane is live, prefill chunk tokens absorbed into decode dispatches,
    # and tokens processed (decode + absorbed) per dispatch.  Defaults
    # read a pre-ragged engine's record as off/zero, not unknown.
    ragged_waves: bool = False
    prefill_absorbed_tokens: int = 0
    unified_dispatches: int = 0
    tokens_per_dispatch: float = 0.0
    # overload protection (ISSUE 5): admission sheds (max_pending bound),
    # deadline expiries, reaped consumer cancels (with the mesh-propagated
    # subset) and max_out_blocks stall-cancels.  Defaults 0 so records
    # from pre-ISSUE-5 engines read as "no overload events", not unknown.
    max_pending: int = 0
    shed_requests: int = 0
    expired_requests: int = 0
    # multi-tenant QoS (ISSUE 20): per-class splits of the shed/expired
    # counters and per-class QUEUED depth — `ck stats` class columns and
    # the routing policy's interactive-depth tiebreak.  Defaults 0 so a
    # pre-QoS record reads as "no class signal", not unknown.
    interactive_shed: int = 0
    batch_shed: int = 0
    interactive_expired: int = 0
    batch_expired: int = 0
    interactive_pending: int = 0
    batch_pending: int = 0
    cancelled_requests: int = 0
    cancel_propagated: int = 0
    delivery_stalled: int = 0
    # caller liveness (ISSUE 10): runs the server-side orphan reaper
    # abandoned because their CALLER's lease lapsed — `ck stats` ORPHANS.
    # Default 0 so pre-lease records read as "no orphans", not unknown.
    orphaned_requests: int = 0
    # EWMA decode-dispatch latency (ms): the many-router tiebreak signal
    # — PowerOfTwoChoices breaks queue-depth ties on it so N independent
    # routers seeing identical depths between beats stop herding.
    # Default 0.0 = "no signal" (pre-EWMA records tie-break on the key).
    dispatch_ewma_ms: float = 0.0
    # failure recovery (ISSUE 9): whether the engine's dispatch-progress
    # watchdog currently declares it wedged (ready goes false with it —
    # routers route around, and outstanding placements are declared
    # dead), its trip/fault lifetime counters, and how many of this
    # replica's arrivals were failover re-dispatches / hedge duplicates
    # (counted by the serving agent from the x-mesh-attempt marker).
    # Defaults read a pre-ISSUE-9 record as never-wedged / no-recovery.
    wedged: bool = False
    watchdog_trips: int = 0
    watchdog_faulted: int = 0
    failover_requests: int = 0
    hedge_requests: int = 0
    # run-scoped observability (ISSUE 17): arrivals counted from the
    # x-mesh-run header by the serving agent — run_requests counts
    # first attempts (attempt_no == 0), attempt_requests counts every
    # linked placement, so ATTEMPTS/RUNS in `ck stats` is the attempt
    # amplification failover/hedge re-dispatches add per replica.
    # Corrupt/missing run headers count in NEITHER (un-linked degrade).
    # Defaults read a pre-run-ledger record as zero, not unknown.
    run_requests: int = 0
    attempt_requests: int = 0
    # prefix-cache health (ISSUE 7): cached pages resident plus lifetime
    # hit/reuse counters — the signal prefix-affinity routing exists to
    # improve, surfaced per replica in `ck fleet` and ROUTER.json
    prefix_cached_pages: int = 0
    prefix_hits: int = 0
    prefix_reused_tokens: int = 0
    # capacity observatory (ISSUE 19): the headroom advert.  pages_total
    # is the allocatable pool (pool minus the trash page; 0 = dense
    # layout, no page signal); pages_in_use counts live-owner pages only
    # (slot-held private + referenced prefix pages — zero-ref cached
    # pages are evictable-on-demand and therefore headroom, not use);
    # prefix_resident_pages is cache residency regardless of refcount;
    # evictions_window is pages reclaimed under pressure THIS heartbeat
    # interval; alloc_stalls counts admissions whose page alloc came up
    # short (lifetime).  The registry derives headroom_pages =
    # pages_total - pages_in_use.  Defaults read a pre-capacity record
    # as a dense/no-signal replica, not as a full one.
    pages_total: int = 0
    pages_in_use: int = 0
    prefix_resident_pages: int = 0
    evictions_window: int = 0
    alloc_stalls: int = 0
    # flight-recorder ring accounting ({"appended", "dropped", "dumped"}):
    # None for records from engines predating the journal
    flightrec: dict[str, int] | None = None
    hbm_gb_in_use: float | None = None  # where the backend reports memory
    # latency percentiles (ms) from the engine's fixed-bucket histograms:
    # ttft_p50/p99, inter_token_p50/p99, queue_wait_p50/p99, prefill_p50/p99
    latency_ms: dict[str, float] | None = None
    # per-heartbeat-interval deltas (EngineStats.snapshot_and_delta), so
    # directory readers see rates, not lifetime cumulative values
    window: dict[str, Any] | None = None


class RunAttemptRecord(BaseModel):
    """One placement of a supervised run (ISSUE 17): which replica got
    the call, under which correlation id (== that attempt's trace id by
    client convention — the ``ck run`` stitch key), how it was marked
    (first | retry | failover | hedge | resume), and how it ended."""

    attempt_no: int = 0
    correlation_id: str = ""
    # first | retry | failover | hedge | resume
    kind: str = "first"
    # replica key "<agent>@<instance>" ("" = shared-topic / unrouted)
    placement: str = ""
    agent: str = ""
    started_at: float = 0.0  # wall_clock seam (virtual in sim)
    finished_at: float = 0.0  # 0.0 = never finished (superseded/killed)
    # ok | fault | shed | cancelled | superseded | pending
    outcome: str = "pending"
    error_type: str = ""  # typed fault code (x-mesh-error-type) if any
    queue_wait_s: float = 0.0
    tokens_delivered: int = 0
    device_time_s: float = 0.0  # from engine counters where reported


class RunRecord(BaseModel):
    """One logical run's ledger entry, published compacted to
    ``mesh.runs`` (key = ``run_id``) when the supervising client
    finishes the run.  The run-level view the per-attempt trace and
    flight-recorder timelines cannot give: one record spans every
    retry/failover/hedge/resume placement."""

    run_id: str
    agent: str = ""
    client_id: str = ""
    started_at: float = 0.0
    finished_at: float = 0.0
    # ok | fault | timeout | cancelled | pending
    outcome: str = "pending"
    error_type: str = ""
    # priority class (ISSUE 20): the run's effective class as the
    # supervising client resolved it.  Default = the default class, so
    # a pre-QoS record folds as interactive, never as a third bucket.
    priority: str = "interactive"
    attempts: "list[RunAttemptRecord]" = Field(default_factory=list)
    sheds: int = 0
    failovers: int = 0
    hedges: int = 0
    resumes: int = 0
    tokens_delivered: int = 0

    def run_key(self) -> str:
        """Compaction key: latest record per run survives."""
        return self.run_id

    def to_wire(self) -> bytes:
        return self.model_dump_json().encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "RunRecord":
        return cls.model_validate_json(data)


class SloRollupRecord(BaseModel):
    """Per-agent windowed run-level SLO rollup (ISSUE 17), re-derived on
    the control-plane heartbeat cadence from folded ``mesh.runs``
    records and published compacted to ``mesh.slo`` (key =
    ``<agent>@<instance>`` of the publishing worker).  Run-level, not
    attempt-level: completion ratio and latency percentiles describe
    what callers experienced, with failover/hedge amplification visible
    separately."""

    agent: str
    node_id: str = ""  # publishing worker's node@instance provenance
    window_s: float = 300.0
    window_end: float = 0.0  # wall_clock seam (virtual in sim)
    runs: int = 0
    completed: int = 0
    completion_ratio: float = 1.0
    e2e_p50_s: float = 0.0
    e2e_p95_s: float = 0.0
    e2e_p99_s: float = 0.0
    attempts: int = 0
    attempt_amplification: float = 1.0
    shed_rate: float = 0.0
    failover_rate: float = 0.0
    orphan_rate: float = 0.0
    # fraction of the window's error budget burned: observed failure
    # ratio / allowed failure ratio against the completion objective
    slo_completion_target: float = 0.999
    error_budget_burn: float = 0.0
    # per-class sub-rollups (ISSUE 20): the `ck slo` class split.  A
    # pre-QoS rollup reports zeros — "no class signal", not "no runs"
    # (the totals above stay authoritative).
    interactive_runs: int = 0
    interactive_completed: int = 0
    interactive_p95_s: float = 0.0
    batch_runs: int = 0
    batch_completed: int = 0
    batch_p95_s: float = 0.0

    def slo_key(self) -> str:
        return f"{self.agent}@{self.node_id}" if self.node_id else self.agent

    def to_wire(self) -> bytes:
        return self.model_dump_json().encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "SloRollupRecord":
        return cls.model_validate_json(data)


class SpanRecord(BaseModel):
    """One finished trace span, published to the compacted ``mesh.traces``
    topic (and kept in the process tracer's ring buffer as the zero-broker
    fallback).  ``trace_id`` equals the run's correlation id by client
    convention, so ``ck trace <correlation-id>`` needs no join."""

    trace_id: str
    span_id: str
    parent_span_id: str | None = None
    name: str = ""
    # client | dispatch | agent | tool | consumer | toolbox | engine | internal
    kind: str = "internal"
    emitter: str = ""
    start_s: float = 0.0  # wall clock (epoch seconds): waterfall alignment
    duration_ms: float = 0.0
    status: str = "ok"  # ok | error | cancelled
    attrs: dict[str, Any] = Field(default_factory=dict)

    def span_key(self) -> str:
        """Compaction key: latest record per span survives."""
        return f"{self.trace_id}/{self.span_id}"

    def to_wire(self) -> bytes:
        return self.model_dump_json().encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "SpanRecord":
        return cls.model_validate_json(data)
