"""The envelope's reply slot: ReturnMessage XOR FaultMessage.

``frame_id`` is the id of the frame the callee unwound to produce this reply;
the caller classifies the reply (pending slot vs fan-out sibling vs stray)
against it before any user code runs (reference: calfkit/models/reply.py:41-82).
``tag`` and ``marker`` are echoed verbatim from the call frame.
"""

from __future__ import annotations

from typing import Annotated, Literal, Union

from pydantic import BaseModel, Field

from calfkit_tpu.models.error_report import ErrorReport
from calfkit_tpu.models.marker import Marker
from calfkit_tpu.models.payload import ContentPart


class ReturnMessage(BaseModel):
    kind: Literal["return"] = "return"
    parts: list[ContentPart] = Field(default_factory=list)
    frame_id: str | None = None
    tag: str | None = None
    marker: Marker | None = None


class FaultMessage(BaseModel):
    kind: Literal["fault"] = "fault"
    report: ErrorReport
    frame_id: str | None = None
    tag: str | None = None
    marker: Marker | None = None


Reply = Annotated[Union[ReturnMessage, FaultMessage], Field(discriminator="kind")]
