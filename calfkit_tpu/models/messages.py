"""Model-turn message vocabulary — the conversation state that rides the wire.

This replaces the reference's use of vendored pydantic-ai messages
(reference: calfkit/models/state.py:8-15 importing ModelRequest/ModelResponse
etc. from the vendor tree).  We own the vocabulary: it is both the wire format
of conversation state AND the input/output contract of the model-client ABC
(:mod:`calfkit_tpu.engine.model_client`).

Attribution: requests and responses carry an optional ``author`` (the agent
name) so multi-agent histories can be re-projected per point-of-view — the
reference patched its vendor copy to add exactly this (vendor.txt note in
SURVEY.md §2.2).
"""

from __future__ import annotations

import json
from typing import Annotated, Any, Literal, Union

from pydantic import BaseModel, Field

from calfkit_tpu.models.payload import ContentPart


class Usage(BaseModel):
    input_tokens: int = 0
    output_tokens: int = 0
    cache_read_tokens: int = 0

    def __add__(self, other: "Usage") -> "Usage":
        return Usage(
            input_tokens=self.input_tokens + other.input_tokens,
            output_tokens=self.output_tokens + other.output_tokens,
            cache_read_tokens=self.cache_read_tokens + other.cache_read_tokens,
        )


# --------------------------------------------------------------------------- #
# request parts (caller -> model)
# --------------------------------------------------------------------------- #


class SystemPart(BaseModel):
    kind: Literal["system"] = "system"
    content: str


class UserPart(BaseModel):
    kind: Literal["user"] = "user"
    content: Union[str, list[ContentPart]]
    author: str | None = None  # attribution for POV projection


class ToolReturnPart(BaseModel):
    kind: Literal["tool_return"] = "tool_return"
    tool_call_id: str
    tool_name: str
    content: Any = None


class RetryPart(BaseModel):
    """Ask the model to retry: validation failure or tool-requested retry."""
    kind: Literal["retry"] = "retry"
    content: str
    tool_call_id: str | None = None
    tool_name: str | None = None


RequestPart = Annotated[
    Union[SystemPart, UserPart, ToolReturnPart, RetryPart],
    Field(discriminator="kind"),
]


class ModelRequest(BaseModel):
    role: Literal["request"] = "request"
    parts: list[RequestPart] = Field(default_factory=list)
    instructions: str | None = None


# --------------------------------------------------------------------------- #
# response parts (model -> caller)
# --------------------------------------------------------------------------- #


class TextOutput(BaseModel):
    kind: Literal["text"] = "text"
    text: str


class ThinkingOutput(BaseModel):
    kind: Literal["thinking"] = "thinking"
    text: str


class ToolCallOutput(BaseModel):
    kind: Literal["tool_call"] = "tool_call"
    tool_call_id: str
    tool_name: str
    args: Union[str, dict[str, Any]] = Field(default_factory=dict)

    def args_dict(self) -> dict[str, Any]:
        """Parse args to a dict; raises ``ValueError`` on malformed JSON."""
        if isinstance(self.args, dict):
            return self.args
        if not self.args.strip():
            return {}
        parsed = json.loads(self.args)
        if not isinstance(parsed, dict):
            raise ValueError(f"tool args must be a JSON object, got {type(parsed)}")
        return parsed


ResponsePart = Annotated[
    Union[TextOutput, ThinkingOutput, ToolCallOutput], Field(discriminator="kind")
]


class ModelResponse(BaseModel):
    role: Literal["response"] = "response"
    parts: list[ResponsePart] = Field(default_factory=list)
    usage: Usage = Field(default_factory=Usage)
    model_name: str | None = None
    author: str | None = None  # attribution for POV projection

    def text(self) -> str:
        return "".join(p.text for p in self.parts if isinstance(p, TextOutput))

    def tool_calls(self) -> list[ToolCallOutput]:
        return [p for p in self.parts if isinstance(p, ToolCallOutput)]


ModelMessage = Annotated[
    Union[ModelRequest, ModelResponse], Field(discriminator="role")
]


def user_message(content: str, *, author: str | None = None) -> ModelRequest:
    return ModelRequest(parts=[UserPart(content=content, author=author)])
