"""The call stack and the envelope — continuation-passing style over the mesh.

Control flow (call/return/fault) between nodes travels as a stack of
:class:`CallFrame` inside every envelope (reference:
calfkit/models/session_context.py:55-209 and SURVEY.md §1 invariants):

- To **call**, push a frame (target topic + callback topic + payload) and
  publish the envelope to the target topic.
- To **return**, pop your frame and publish a ``ReturnMessage`` to that
  frame's callback topic.
- A **fault** unwinds the same way, one hop at a time, giving every caller's
  recovery seams a chance.

There are no in-process RPCs: this stack IS the program counter of the run.
"""

from __future__ import annotations

import uuid
from typing import Any

from pydantic import BaseModel, Field

from calfkit_tpu.models.marker import Marker
from calfkit_tpu.models.payload import ContentPart
from calfkit_tpu.models.reply import Reply
from calfkit_tpu.models.state import State


def new_id() -> str:
    return uuid.uuid4().hex


class CallFrame(BaseModel):
    """One activation record of the distributed call stack."""


    frame_id: str = Field(default_factory=new_id)
    target_topic: str
    callback_topic: str
    route: str = "run"
    payload: list[ContentPart] = Field(default_factory=list)
    tag: str | None = None  # caller-side correlation (e.g. tool_call_id)
    marker: Marker | None = None  # echoed verbatim on the reply
    fanout_id: str | None = None  # set on the CALLER's frame while a batch is open
    caller_kind: str | None = None
    caller_name: str | None = None


class WorkflowState(BaseModel):
    """The frame stack plus mutation verbs (reference:
    session_context.py:109 — invoke_frame/unwind_frame/mark_fanout)."""


    frames: list[CallFrame] = Field(default_factory=list)

    @property
    def depth(self) -> int:
        return len(self.frames)

    def current(self) -> CallFrame | None:
        return self.frames[-1] if self.frames else None

    def require_current(self) -> CallFrame:
        frame = self.current()
        if frame is None:
            raise ValueError("workflow has no active frame")
        return frame

    def invoke_frame(self, frame: CallFrame) -> CallFrame:
        """Push an activation record for an outgoing call."""
        self.frames.append(frame)
        return frame

    def unwind_frame(self) -> CallFrame:
        """Pop the callee's own frame to produce a reply."""
        if not self.frames:
            raise ValueError("cannot unwind an empty workflow stack")
        return self.frames.pop()

    def mark_fanout(self, fanout_id: str | None) -> None:
        """Mark (or clear) an open durable batch on the current frame."""
        self.require_current().fanout_id = fanout_id

    def to_topology(self) -> list[str]:
        """Route chain root→leaf, for diagnostics and step telemetry."""
        return [f"{f.target_topic}#{f.route}" for f in self.frames]

    def root_callback_topic(self) -> str | None:
        """The run originator's inbox — where steps stream to."""
        return self.frames[0].callback_topic if self.frames else None


class SessionContext(BaseModel):
    """Durable run context: conversation state + user deps bag."""


    state: State = Field(default_factory=State)
    deps: dict[str, Any] = Field(default_factory=dict)


class Envelope(BaseModel):
    """The one wire body for all call/return/fault records.

    ``state_elided`` flags the degradation rung where conversation state was
    dropped to fit the wire budget (reference: envelope.py:12, reply slot
    contract at reply.py:41-82).
    """


    context: SessionContext = Field(default_factory=SessionContext)
    workflow: WorkflowState = Field(default_factory=WorkflowState)
    reply: Reply | None = None
    state_elided: bool = False

    def to_wire(self) -> bytes:
        return self.model_dump_json(exclude_none=True).encode("utf-8")

    @classmethod
    def from_wire(cls, data: bytes | str) -> "Envelope":
        return cls.model_validate_json(data)
