"""Over-the-wire tool invocation body and call-side binding models.

Reference: calfkit/models/tool_dispatch.py:26-147.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

from pydantic import BaseModel, Field

from calfkit_tpu.models.capability import ToolDef


class ToolCallRef(BaseModel):
    """The wire body of a dispatched tool invocation (carried as a DataPart)."""


    tool_call_id: str
    tool_name: str
    args: dict[str, Any] = Field(default_factory=dict)


class ToolBinding(BaseModel):
    """A tool def bound to its dispatch topic, ready for a model turn."""


    tool: ToolDef
    dispatch_topic: str


@runtime_checkable
class ToolSelector(Protocol):
    """Call-side selection of which live tools a model turn may see.

    Implementations: ``Tools`` (named XOR discover), ``Toolboxes``,
    ``Messaging``, ``Handoff`` — each resolves against the live capability /
    agents views at turn time.
    """

    def resolve(self, view: Any) -> list[ToolBinding]: ...
