"""Conversation state that crosses the wire with every envelope.

The node holds zero in-process run state — the envelope carries all of it, so
any worker replica can continue any run (the checkpoint/resume property,
reference: calfkit/models/state.py:22-145 and SURVEY.md §5 checkpoint notes).
"""

from __future__ import annotations

from typing import Annotated, Union

from pydantic import BaseModel, Field

from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    ToolCallOutput,
    ToolReturnPart,
)

ToolResult = Annotated[Union[ToolReturnPart, RetryPart], Field(discriminator="kind")]


class State(BaseModel):
    """The agent's durable conversation state.

    - ``message_history``: committed model turns (requests + responses).
    - ``uncommitted_message``: the staged incoming user prompt; committed by
      the agent when a turn completes so retried deliveries don't duplicate it.
    - ``temp_instructions``: per-run instruction override.
    - ``tool_calls`` / ``tool_results``: the in-flight tool ledger — calls the
      model issued that are out on the wire, and results that have landed but
      have not yet been fed back into a model turn.
    """


    message_history: list[ModelMessage] = Field(default_factory=list)
    uncommitted_message: ModelRequest | None = None
    temp_instructions: str | None = None
    tool_calls: dict[str, ToolCallOutput] = Field(default_factory=dict)
    tool_results: dict[str, ToolResult] = Field(default_factory=dict)

    def latest_response(self) -> ModelResponse | None:
        for msg in reversed(self.message_history):
            if isinstance(msg, ModelResponse):
                return msg
        return None

    def latest_tool_calls(self) -> list[ToolCallOutput]:
        """Tool calls from the most recent model response
        (reference: calfkit/models/state.py:98 ``latest_tool_calls``)."""
        resp = self.latest_response()
        return resp.tool_calls() if resp else []

    def pending_tool_call_ids(self) -> set[str]:
        return set(self.tool_calls) - set(self.tool_results)

    def commit_message(self, message: ModelMessage) -> None:
        self.message_history.append(message)

    def clear_inflight(self) -> None:
        self.tool_calls.clear()
        self.tool_results.clear()
