"""Durable fan-out batch wire models.

A parallel tool fan-out parks its state in compacted mesh tables so a worker
crash/rebalance never loses a batch (reference: calfkit/models/fanout.py and
calfkit/nodes/_fanout_store.py:50-64).  The write-order invariant:
**basestate before state, both acked** — registration implies restorability.
"""

from __future__ import annotations

from pydantic import BaseModel, Field

from calfkit_tpu.models.error_report import ErrorReport
from calfkit_tpu.models.marker import Marker
from calfkit_tpu.models.payload import ContentPart
from calfkit_tpu.models.session_context import SessionContext, WorkflowState


class SlotRef(BaseModel):
    """A pre-minted sibling slot: the sibling's frame_id IS the slot id."""
    slot_id: str
    tag: str | None = None
    tool_name: str | None = None


class FanoutOpen(BaseModel):
    fanout_id: str
    slots: list[SlotRef] = Field(default_factory=list)

    def slot_ids(self) -> set[str]:
        return {s.slot_id for s in self.slots}


class FanoutOutcome(BaseModel):
    """Result of one sibling: parts XOR fault (after on_callee_error seams)."""
    slot_id: str
    parts: list[ContentPart] | None = None
    fault: ErrorReport | None = None
    marker: Marker | None = None


class FanoutState(BaseModel):
    """The compacted ``state`` table value: open batch + folded outcomes."""
    open: FanoutOpen
    outcomes: dict[str, FanoutOutcome] = Field(default_factory=dict)
    closing: bool = False

    def is_complete(self) -> bool:
        return self.open.slot_ids() <= set(self.outcomes)


class EnvelopeSnapshot(BaseModel):
    """The compacted ``basestate`` table value: everything needed to resume
    the caller after the batch closes (state + stack + deps)."""
    context: SessionContext
    workflow: WorkflowState
