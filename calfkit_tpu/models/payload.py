"""A2A-style content-part vocabulary — the unit of user-visible payloads.

Everything a node returns to its caller, and everything a caller sends to a
node, is a list of these parts (reference: calfkit/models/payload.py:37-93).
"""

from __future__ import annotations

import json
from typing import Annotated, Any, Literal, Union

from pydantic import BaseModel, Field, model_validator

RETRY_KEY = "mesh.retry"


class _Part(BaseModel):

    metadata: dict[str, Any] | None = None


class TextPart(_Part):
    kind: Literal["text"] = "text"
    text: str


class FilePart(_Part):
    """A file by inline base64 payload or by URI (exactly one must be set)."""

    kind: Literal["file"] = "file"
    name: str | None = None
    media_type: str | None = None
    data_base64: str | None = None
    uri: str | None = None

    @model_validator(mode="after")
    def _exactly_one_source(self) -> "FilePart":
        if (self.data_base64 is None) == (self.uri is None):
            raise ValueError("FilePart requires exactly one of data_base64 or uri")
        return self


class DataPart(_Part):
    kind: Literal["data"] = "data"
    data: Any = None


class ToolCallPart(_Part):
    """A surfaced (not dispatched) tool call, for telemetry payloads."""

    kind: Literal["tool_call"] = "tool_call"
    tool_call_id: str
    tool_name: str
    args: dict[str, Any] = Field(default_factory=dict)


ContentPart = Annotated[
    Union[TextPart, FilePart, DataPart, ToolCallPart], Field(discriminator="kind")
]


def render_parts_as_text(parts: list[ContentPart]) -> str:
    """Collapse parts to a single text blob (model-facing rendering).

    Reference: calfkit/models/payload.py:40.
    """
    chunks: list[str] = []
    for part in parts:
        if isinstance(part, TextPart):
            chunks.append(part.text)
        elif isinstance(part, DataPart):
            try:
                chunks.append(json.dumps(part.data, ensure_ascii=False, default=str))
            except (TypeError, ValueError):
                chunks.append(str(part.data))
        elif isinstance(part, FilePart):
            label = part.name or part.uri or "file"
            chunks.append(f"[file: {label}]")
        elif isinstance(part, ToolCallPart):
            chunks.append(f"[tool call: {part.tool_name}]")
    return "\n".join(chunks)


def retry_text_part(text: str) -> TextPart:
    """A text part marked as a model-retry request (tool asked the model to
    try again, e.g. bad arguments).  Reference: calfkit/models/payload.py:80."""
    return TextPart(text=text, metadata={RETRY_KEY: True})


def is_retry(part: ContentPart) -> bool:
    return bool(part.metadata and part.metadata.get(RETRY_KEY))


def text_parts(*texts: str) -> list[ContentPart]:
    return [TextPart(text=t) for t in texts]
