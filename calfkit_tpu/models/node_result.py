"""Schema-on-read projection of a terminal reply into a typed result.

Reference: calfkit/models/node_result.py:25-134 (``InvocationResult`` /
``from_envelope``): the wire carries parts; the *caller's* declared output
type decides how to read them — at read time, not at publish time.
"""

from __future__ import annotations

import json
from typing import Any, Generic, TypeVar

from pydantic import BaseModel, ConfigDict, Field, TypeAdapter

from calfkit_tpu.models.payload import ContentPart, DataPart, TextPart, render_parts_as_text
from calfkit_tpu.models.session_context import Envelope
from calfkit_tpu.models.state import State

OutputT = TypeVar("OutputT")


class InvocationResult(BaseModel, Generic[OutputT]):
    model_config = ConfigDict(extra="allow", arbitrary_types_allowed=True)

    output: OutputT
    parts: list[ContentPart] = Field(default_factory=list)
    state: State = Field(default_factory=State)
    deps: dict[str, Any] = Field(default_factory=dict)
    correlation_id: str | None = None
    task_id: str | None = None
    state_elided: bool = False

    @classmethod
    def from_envelope(
        cls,
        envelope: Envelope,
        output_type: type[OutputT] = str,  # type: ignore[assignment]
        *,
        correlation_id: str | None = None,
        task_id: str | None = None,
    ) -> "InvocationResult[OutputT]":
        from calfkit_tpu.models.reply import ReturnMessage

        reply = envelope.reply
        if not isinstance(reply, ReturnMessage):
            raise ValueError("envelope does not carry a return reply")
        output = project_output(reply.parts, output_type)
        return cls(
            output=output,
            parts=list(reply.parts),
            state=envelope.context.state,
            deps=envelope.context.deps,
            correlation_id=correlation_id,
            task_id=task_id,
            state_elided=envelope.state_elided,
        )


def project_output(parts: list[ContentPart], output_type: type[OutputT]) -> OutputT:
    """Project reply parts into ``output_type``.

    - ``str``: rendered text of all parts.
    - pydantic model / typed object: the first DataPart validated against it,
      falling back to parsing text parts as JSON (``extract_lenient``,
      reference: node_result.py:330).
    """
    if output_type is str:
        return render_parts_as_text(parts)  # type: ignore[return-value]
    adapter: TypeAdapter[OutputT] = TypeAdapter(output_type)
    for part in parts:
        if isinstance(part, DataPart):
            return adapter.validate_python(part.data)
    for part in parts:
        if isinstance(part, TextPart):
            return extract_lenient(part.text, adapter)
    raise ValueError(f"no part projects into {output_type!r}")


def extract_lenient(text: str, adapter: TypeAdapter[OutputT]) -> OutputT:
    """Parse JSON out of model text, tolerating fences and surrounding prose."""
    candidates = [text.strip()]
    stripped = text.strip()
    if stripped.startswith("```"):
        body = stripped.split("```")[1] if "```" in stripped[3:] else stripped[3:]
        body = body.removeprefix("json").strip()
        candidates.insert(0, body)
    start, end = stripped.find("{"), stripped.rfind("}")
    if 0 <= start < end:
        candidates.append(stripped[start : end + 1])
    last_error: Exception | None = None
    for cand in candidates:
        try:
            return adapter.validate_python(json.loads(cand))
        except Exception as exc:  # noqa: BLE001 - try the next candidate form
            last_error = exc
    raise ValueError(f"could not project text into typed output: {last_error}")
