"""The typed fault vocabulary and the budgeted, total-constructor ErrorReport.

Design requirements carried over from the reference (calfkit/models/
error_report.py:46-657):

- **Typed codes** (``mesh.*``) so callers can dispatch on fault class without
  string-matching messages.
- **Total construction**: :meth:`ErrorReport.build_safe` must never raise —
  it is called from inside exception handlers, including against hostile
  objects whose ``__str__``/``__repr__`` raise.
- **Budgeted**: messages/tracebacks are truncated and cause-chains bounded so
  a report can always fit the wire budget; :meth:`to_minimal` is the last
  rung of the state-elision ladder.
"""

from __future__ import annotations

import traceback as _tb
from typing import Any

from pydantic import BaseModel, Field

# --------------------------------------------------------------------------- #
# fault codes
# --------------------------------------------------------------------------- #


class FaultTypes:
    """The ``mesh.*`` typed-fault vocabulary."""

    NODE_ERROR = "mesh.node_error"  # node body raised
    TOOL_ERROR = "mesh.tool_error"  # tool body raised
    CALLEE_FAULT = "mesh.callee_fault"  # downstream fault escalated through
    VALIDATION_ERROR = "mesh.validation_error"  # schema/args validation failed
    DESERIALIZATION_ERROR = "mesh.deserialization_error"
    TIMEOUT = "mesh.timeout"
    STRAY_REPLY = "mesh.stray_reply"
    FANOUT_ABORTED = "mesh.fanout_aborted"
    DECLINED = "mesh.declined"  # reply-owing delivery declined by all handlers
    CAPABILITY_UNAVAILABLE = "mesh.capability_unavailable"
    HANDOFF_REJECTED = "mesh.handoff_rejected"
    MODEL_ERROR = "mesh.model_error"
    CONTEXT_WINDOW_EXCEEDED = "mesh.model.context_window_exceeded"
    OVERSIZED_MESSAGE = "mesh.oversized_message"
    LIFECYCLE_ERROR = "mesh.lifecycle_error"
    # overload protection (ISSUE 5): a bounded queue shed the call, or a
    # draining worker refused it — RETRIABLE elsewhere/later by contract
    OVERLOADED = "mesh.overloaded"
    # the call's x-mesh-deadline passed (on arrival, in queue, or while
    # executing): the caller is gone, the work was abandoned — NOT
    # retriable (the budget is spent)
    DEADLINE_EXCEEDED = "mesh.deadline_exceeded"
    # the run's caller published a mesh `cancel` before this call started
    # executing (tombstone hit at the admission gate) — NOT retriable
    # (the caller abandoned the run on purpose)
    CANCELLED = "mesh.cancelled"
    # the engine's dispatch-progress watchdog declared the device wedged
    # (work pending, no dispatch landing within watchdog_stall_s) and
    # faulted the request instead of letting it burn its whole deadline —
    # RETRIABLE by contract: nothing was delivered to the caller, and a
    # different replica can serve the same call (ISSUE 9)
    WEDGED = "mesh.wedged"
    # multi-tenant QoS (ISSUE 20): the node kernel's per-tenant token
    # bucket refused the call — the tenant's admission budget is spent.
    # RETRIABLE by contract: the bucket refills on a known schedule, so
    # backing off and retrying is exactly the right caller response
    # (unlike a deadline, which is gone forever)
    RATE_LIMITED = "mesh.rate_limited"
    # the run's CALLER liveness lease lapsed (heartbeats stopped past the
    # lease TTL, or the caller released the lease on clean close) and the
    # server-side orphan reaper abandoned the run (ISSUE 10) — NOT
    # retriable: there is nobody to answer; the fault is published to the
    # (dead) reply topic for the record, not for a consumer
    ORPHANED = "mesh.orphaned"
    UNHANDLED = "mesh.unhandled_exception"

    @classmethod
    def all(cls) -> frozenset[str]:
        return frozenset(
            v for k, v in vars(cls).items() if isinstance(v, str) and not k.startswith("_")
        )


# --------------------------------------------------------------------------- #
# safe stringification (hostile-object guard)
# --------------------------------------------------------------------------- #

_MSG_BUDGET = 4096
_TB_BUDGET = 16384
_MAX_CAUSES = 8


def safe_str(obj: Any, limit: int = _MSG_BUDGET) -> str:
    """``str(obj)`` that survives hostile ``__str__``/``__repr__``.

    Reference: calfkit/_safe.py:34 (``safe_exc_message``).
    """
    try:
        s = str(obj)
    except BaseException:
        try:
            s = object.__repr__(obj)
        except BaseException:
            s = "<unprintable object>"
    if len(s) > limit:
        s = s[: limit - 1] + "…"
    return s


# --------------------------------------------------------------------------- #
# report models
# --------------------------------------------------------------------------- #


class ExceptionInfo(BaseModel):
    type: str
    message: str
    traceback: str | None = None


class ErrorReport(BaseModel):
    """A typed, wire-safe description of a failure.

    ``causes`` is the escalation chain (most-recent first): each hop a fault
    climbs up the call stack may wrap the prior report.  ``frame_chain`` is
    the list of frame ids the fault travelled through, for diagnostics.
    """


    error_type: str = FaultTypes.UNHANDLED
    message: str = ""
    node: str | None = None
    route: str | None = None
    frame_chain: list[str] = Field(default_factory=list)
    causes: list["ErrorReport"] = Field(default_factory=list)
    exception: ExceptionInfo | None = None
    data: dict[str, Any] | None = None

    # ---------------------------------------------------------------- build
    @classmethod
    def build_safe(
        cls,
        error_type: str,
        message: Any = None,
        *,
        exc: BaseException | None = None,
        node: str | None = None,
        route: str | None = None,
        cause: "ErrorReport | None" = None,
        frame_id: str | None = None,
        data: dict[str, Any] | None = None,
        include_traceback: bool = True,
    ) -> "ErrorReport":
        """Total constructor: never raises, whatever it is handed.

        Reference: the harvester at calfkit/models/error_report.py:611.
        """
        try:
            msg = safe_str(message) if message is not None else ""
            exc_info: ExceptionInfo | None = None
            if exc is not None:
                tb: str | None = None
                if include_traceback:
                    try:
                        tb = "".join(
                            _tb.format_exception(type(exc), exc, exc.__traceback__)
                        )[-_TB_BUDGET:]
                    except BaseException:
                        tb = None
                exc_info = ExceptionInfo(
                    type=safe_str(type(exc).__name__, 256),
                    message=safe_str(exc),
                    traceback=tb,
                )
                if not msg:
                    msg = exc_info.message
            # flatten the escalation chain: causes = [direct cause, its causes…]
            causes: list[ErrorReport] = []
            if cause is not None:
                causes = [cause.model_copy(update={"causes": []}), *cause.causes]
                causes = causes[:_MAX_CAUSES]
            frame_chain: list[str] = list(causes[0].frame_chain) if causes else []
            if frame_id:
                frame_chain = [frame_id, *frame_chain][:32]
            safe_data: dict[str, Any] | None = None
            if data is not None:
                try:
                    safe_data = {safe_str(k, 128): safe_str(v, 512) for k, v in data.items()}
                except BaseException:
                    safe_data = None
            return cls(
                error_type=error_type if isinstance(error_type, str) else FaultTypes.UNHANDLED,
                message=msg,
                node=node,
                route=route,
                frame_chain=frame_chain,
                causes=causes,
                exception=exc_info,
                data=safe_data,
            )
        except BaseException:
            # absolute floor: a report must always exist
            try:
                return cls(error_type=FaultTypes.UNHANDLED, message="error report construction failed")
            except BaseException:  # pragma: no cover - pydantic default ctor
                return cls.model_construct()

    # ------------------------------------------------------------- degrade
    def to_minimal(self) -> "ErrorReport":
        """Smallest useful report — the last rung of the elision ladder
        (reference: calfkit/nodes/base.py:838-905)."""
        return ErrorReport(
            error_type=self.error_type,
            message=safe_str(self.message, 512),
            node=self.node,
            route=self.route,
        )

    def without_tracebacks(self) -> "ErrorReport":
        """Middle rung: keep structure, drop tracebacks."""
        return self.model_copy(
            update={
                "exception": (
                    self.exception.model_copy(update={"traceback": None})
                    if self.exception
                    else None
                ),
                "causes": [c.without_tracebacks() for c in self.causes],
            }
        )

    def root_cause(self) -> "ErrorReport":
        return self.causes[-1] if self.causes else self

    def describe(self) -> str:
        head = f"[{self.error_type}] {self.message}"
        if self.node:
            head += f" (node={self.node})"
        return head
