"""The node-result vocabulary: what a node body may decide to do next.

A routed handler returns one of (reference: calfkit/models/actions.py:29-118):

- :class:`Call` — invoke another node and suspend this run until it replies.
  A ``list[Call]`` opens a durable parallel fan-out batch.
- :class:`TailCall` — hand the *current obligation* to another node (handoff):
  the active frame is retargeted; the new node replies to the original caller.
- :class:`ReturnCall` — produce the reply for the active frame.
- :class:`Next` — decline: let a less-specific handler in the chain take the
  delivery.  Declining a reply-owing delivery with no taker is auto-faulted
  by the kernel (no silent drops).
"""

from __future__ import annotations

from typing import Union

from pydantic import BaseModel, Field

from calfkit_tpu.models.marker import Marker
from calfkit_tpu.models.payload import ContentPart
from calfkit_tpu.models.state import State


class Call(BaseModel):

    target_topic: str
    route: str = "run"
    parts: list[ContentPart] = Field(default_factory=list)
    tag: str | None = None
    marker: Marker | None = None
    # Fresh-state call: callee gets an isolated (empty or overridden) State
    # instead of the caller's conversation (reference: actions.py:29
    # ``isolate_state`` — used by message_agent).
    isolate_state: bool = False
    state_override: State | None = None


class TailCall(BaseModel):

    target_topic: str
    route: str = "run"
    parts: list[ContentPart] = Field(default_factory=list)


class ReturnCall(BaseModel):

    parts: list[ContentPart] = Field(default_factory=list)


class Next(BaseModel):
    """Decline the delivery; chain-of-responsibility moves on."""


NodeResult = Union[Call, list[Call], TailCall, ReturnCall, Next, None]
