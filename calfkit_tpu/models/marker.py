"""The marker rail: typed correlation context echoed verbatim on replies.

A caller stamps a marker on the call frame; the callee's kernel echoes it on
the reply (return OR fault) without ever inspecting it.  This is how the agent
re-associates a reply with the model tool call that caused it
(reference: calfkit/models/marker.py).
"""

from __future__ import annotations

from typing import Annotated, Any, Literal, Union

from pydantic import BaseModel, Field


class CallMarker(BaseModel):
    kind: Literal["call"] = "call"
    data: dict[str, Any] = Field(default_factory=dict)


class ToolCallMarker(BaseModel):
    kind: Literal["tool_call"] = "tool_call"
    tool_call_id: str
    tool_name: str


Marker = Annotated[Union[CallMarker, ToolCallMarker], Field(discriminator="kind")]
