"""Capability records (the ``mesh.capabilities`` compacted topic) and the
capability-resolution kernel.

A capability is "this dispatch topic executes these tools".  Agents resolve
tool selectors against the live capability view each turn (reference:
calfkit/models/capability.py:49-219).
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, Field


class ToolDef(BaseModel):
    """A model-facing tool definition (name + JSON-schema parameters)."""


    name: str
    description: str = ""
    parameters_schema: dict[str, Any] = Field(
        default_factory=lambda: {"type": "object", "properties": {}}
    )


class CapabilityRecord(BaseModel):

    node_id: str
    node_kind: str = "tool"
    dispatch_topic: str
    tools: list[ToolDef] = Field(default_factory=list)

    def tool_names(self) -> list[str]:
        return [t.name for t in self.tools]


class ResolvedTool(BaseModel):
    """A tool def bound to the topic that executes it."""


    tool: ToolDef
    dispatch_topic: str
    provider_node_id: str


class CapabilityResolutionError(LookupError):
    pass


def resolve_capability(
    records: list[CapabilityRecord], tool_name: str
) -> ResolvedTool:
    """Find the one live provider of ``tool_name``.

    Ambiguity (two live providers) is an error, not a coin flip — the caller
    must disambiguate via selectors (reference: capability.py:138).
    """
    matches = [
        ResolvedTool(tool=t, dispatch_topic=r.dispatch_topic, provider_node_id=r.node_id)
        for r in records
        for t in r.tools
        if t.name == tool_name
    ]
    if not matches:
        raise CapabilityResolutionError(f"no live provider for tool {tool_name!r}")
    providers = {m.provider_node_id for m in matches}
    if len(providers) > 1:
        raise CapabilityResolutionError(
            f"tool {tool_name!r} offered by multiple providers: {sorted(providers)}"
        )
    return matches[0]


def resolve_all_capabilities(records: list[CapabilityRecord]) -> list[ResolvedTool]:
    """Every live tool, one entry per (provider, tool) — discovery mode.

    Reference: capability.py:198 (``resolve_all_capabilities``).
    """
    return [
        ResolvedTool(tool=t, dispatch_topic=r.dispatch_topic, provider_node_id=r.node_id)
        for r in records
        for t in r.tools
    ]
