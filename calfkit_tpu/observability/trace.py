"""Mesh-wide distributed tracing: contexts, spans, and the process tracer.

A :class:`TraceContext` (trace_id / span_id / parent_span_id) is minted at
the client, carried in Kafka record headers (``x-mesh-trace`` /
``x-mesh-span``, see :mod:`calfkit_tpu.protocol`) alongside the existing
``x-mesh-correlation``, and re-parented at every hop: the emitting hop's
span id rides the wire and becomes the receiving hop's parent.  The
client mints ``trace_id == correlation_id`` so operators can go from any
log line straight to ``ck trace <correlation-id>``.

Finished spans are :class:`~calfkit_tpu.models.records.SpanRecord` models.
Every export lands in a bounded in-process ring buffer (the zero-broker
fallback the e2e suite and the overhead bench read); hops that own a
transport additionally publish their collected spans to the compacted
``mesh.traces`` topic — see ``BaseNodeDef._publish_spans``.  The
``collect_spans`` context-local sink is how in-process children (the
inference engine's spans) reach that publish without holding a transport
themselves.

Failure policy: tracing is telemetry.  ``start_span`` / ``end`` /
``export`` never raise; a broken exporter loses spans, not requests.
"""

from __future__ import annotations

import time
import uuid
from collections import deque
from contextvars import ContextVar, Token
from dataclasses import dataclass
from typing import Any, Iterable

from calfkit_tpu import protocol
from calfkit_tpu.models.records import SpanRecord

__all__ = [
    "TraceContext",
    "Span",
    "Tracer",
    "TRACER",
    "current_context",
    "collect_spans",
    "release_spans",
]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """What travels in headers: enough to parent the next span."""

    trace_id: str
    span_id: str
    parent_span_id: str | None = None

    def headers(self) -> dict[str, str]:
        return {
            protocol.HDR_TRACE: self.trace_id,
            protocol.HDR_SPAN: self.span_id,
        }

    @classmethod
    def from_headers(cls, headers: dict[str, str]) -> "TraceContext | None":
        """Decode a remote context; ``None`` when the record carries no
        trace (consumers must tolerate missing headers)."""
        trace_id = headers.get(protocol.HDR_TRACE)
        if not trace_id:
            return None
        return cls(
            trace_id=trace_id,
            span_id=headers.get(protocol.HDR_SPAN) or "",
        )


# the active context for THIS task tree: set by the node kernel around a
# delivery (and by the agent around a model turn) so in-process children —
# the inference engine above all — parent correctly without any plumbing
current_context: ContextVar[TraceContext | None] = ContextVar(
    "calfkit_trace_context", default=None
)

# hop-local span sink: spans finished while a sink is installed are
# ALSO appended there, so the hop's owner can publish them to the mesh
_span_sink: ContextVar["list[SpanRecord] | None"] = ContextVar(
    "calfkit_trace_sink", default=None
)


def collect_spans() -> "tuple[list[SpanRecord], Token]":
    """Install a fresh hop-local sink; returns (sink, reset token)."""
    sink: list[SpanRecord] = []
    return sink, _span_sink.set(sink)


def release_spans(token: Token) -> None:
    try:
        _span_sink.reset(token)
    except Exception:  # noqa: BLE001 - cross-context reset; never fault the hop
        pass


class Span:
    """One timed operation; ``end()`` is idempotent and never raises."""

    __slots__ = (
        "name", "kind", "emitter", "context", "attrs", "status",
        "start_s", "_t0", "_tracer", "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        *,
        context: TraceContext,
        kind: str = "internal",
        emitter: str = "",
        attrs: dict[str, Any] | None = None,
    ):
        self._tracer = tracer
        self.name = name
        self.kind = kind
        self.emitter = emitter
        self.context = context
        self.attrs: dict[str, Any] = dict(attrs or {})
        self.status = "ok"
        self.start_s = time.time()
        self._t0 = time.perf_counter()
        self._ended = False

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def end(self, status: str | None = None, **attrs: Any) -> SpanRecord | None:
        """Finish + export; returns the record (None on double-end)."""
        if self._ended:
            return None
        self._ended = True
        try:
            if status is not None:
                self.status = status
            self.attrs.update(attrs)
            record = SpanRecord(
                trace_id=self.context.trace_id,
                span_id=self.context.span_id,
                parent_span_id=self.context.parent_span_id,
                name=self.name,
                kind=self.kind,
                emitter=self.emitter,
                start_s=self.start_s,
                duration_ms=(time.perf_counter() - self._t0) * 1000.0,
                status=self.status,
                attrs=self.attrs,
            )
            self._tracer.export(record)
            return record
        except Exception:  # noqa: BLE001 - tracing never faults the caller
            return None


class Tracer:
    """Process tracer: mints spans, keeps the bounded ring of finished
    records (the zero-broker fallback), and fans exports into the active
    hop sink when one is installed."""

    def __init__(self, ring_size: int = 2048):
        self._ring: deque[SpanRecord] = deque(maxlen=ring_size)
        self.enabled = True

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)

    def start_span(
        self,
        name: str,
        *,
        parent: TraceContext | None = None,
        trace_id: str | None = None,
        kind: str = "internal",
        emitter: str = "",
        attrs: dict[str, Any] | None = None,
    ) -> Span:
        """New span.  With ``parent``, the child joins that trace; without,
        a new trace is minted (``trace_id`` pins it — the client passes the
        correlation id so trace lookup needs no extra bookkeeping)."""
        if parent is not None:
            context = TraceContext(
                trace_id=parent.trace_id,
                span_id=new_span_id(),
                parent_span_id=parent.span_id or None,
            )
        else:
            context = TraceContext(
                trace_id=trace_id or uuid.uuid4().hex,
                span_id=new_span_id(),
            )
        return Span(
            self, name, context=context, kind=kind, emitter=emitter, attrs=attrs
        )

    def export(self, record: SpanRecord) -> None:
        if not self.enabled:
            return
        try:
            self._ring.append(record)
            sink = _span_sink.get()
            if sink is not None:
                sink.append(record)
        except Exception:  # noqa: BLE001 - export is best-effort by contract
            pass

    def finished(self, trace_id: str | None = None) -> list[SpanRecord]:
        """Ring-buffer contents (optionally one trace), oldest first."""
        records: Iterable[SpanRecord] = list(self._ring)
        if trace_id is not None:
            records = [r for r in records if r.trace_id == trace_id]
        return list(records)

    def clear(self) -> None:
        self._ring.clear()


TRACER = Tracer()


def publish_spans_soon(
    publish: Any,
    records: "list[SpanRecord]",
    tasks: "set[Any]",
    *,
    on_error: Any = None,
) -> None:
    """Fire-and-forget export of finished spans to ``mesh.traces`` via an
    async ``publish(topic, value, key=..., headers=...)`` callable — the
    ONE copy of the export/GC-safety/fail-open pattern the client and the
    node kernel share.  Awaiting the publishes inline would put broker
    round-trips on the caller's critical path (a traced hop finishes with
    ~5 spans), so the export rides a task held in ``tasks`` until done.
    Strictly fail-open: a failed export degrades to ring-buffer-only
    visibility; ``on_error`` (if given) is called once with the exception
    for debug logging."""
    if not records:
        return

    async def export() -> None:
        try:
            for record in records:
                await publish(
                    protocol.TRACES_TOPIC,
                    record.to_wire(),
                    key=record.span_key().encode("utf-8"),
                    headers={protocol.HDR_WIRE: "span"},
                )
        except Exception as exc:  # noqa: BLE001 - telemetry never faults
            if on_error is not None:
                try:
                    on_error(exc)
                except Exception:  # noqa: BLE001
                    pass

    try:
        import asyncio

        task = asyncio.get_running_loop().create_task(export())
        tasks.add(task)  # hold a ref until done (GC safety)
        task.add_done_callback(tasks.discard)
    except Exception:  # noqa: BLE001 - no loop / shutting down: ring only
        pass
