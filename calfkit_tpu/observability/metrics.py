"""Dependency-free metrics: counters, gauges, fixed-bucket histograms.

The serving hot path needs telemetry that costs nothing to record and
nothing to depend on (ISSUE 2 tentpole piece 2): every instrument here is
stdlib-only, ``observe()`` is an O(1) bucket increment under one small
lock, and rendering is Prometheus **text exposition format v0** so any
scraper (or ``curl``) can read it.  Instruments are get-or-create by name
from a registry, so repeated engine construction (tests build many
engines per process) shares one instrument per metric instead of
colliding.

Failure policy: recording must never fault serving.  ``inc`` / ``set`` /
``observe`` swallow bad values instead of raising; only *registration*
(a programming error: same name, different type) is loud.

Windowing: histograms and counters expose :meth:`snapshot_and_delta` for
periodic consumers that want per-interval rates instead of lifetime
cumulative values.  The delta state is per-instrument and
single-consumer by design — two independent delta readers would steal
each other's intervals.  (The engine's heartbeat advert windows its own
stats via ``EngineStats.snapshot_and_delta`` — same contract, applied to
the scheduler counters rather than these instruments.)
"""

from __future__ import annotations

import bisect
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "metrics_text",
]

# latency buckets in milliseconds: sub-ms queue waits through multi-second
# long-context prefills, ~2.5x spacing (13 buckets + +Inf)
DEFAULT_LATENCY_BUCKETS_MS: tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)

# inter-token latency needs finer low-end resolution (the north-star rate
# is hundreds of microseconds per token)
INTER_TOKEN_BUCKETS_MS: tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0,
)


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = _sanitize(name)
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()

    @property
    def _on(self) -> bool:
        return self._registry.enabled

    def render(self) -> str:
        raise NotImplementedError

    def _head(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} {self.kind}\n"
        )


def _fmt(v: float) -> str:
    """Render a sample value: integers without the trailing ``.0``."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        super().__init__(name, help, registry)
        self._value = 0.0
        self._window = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._on:
            return
        try:
            if n < 0:
                return  # counters are monotonic; a negative inc is a bug upstream
            with self._lock:
                self._value += n
        except TypeError:
            return  # non-numeric: recording never raises

    @property
    def value(self) -> float:
        return self._value

    def snapshot_and_delta(self) -> tuple[float, float]:
        """(cumulative, delta-since-last-call)."""
        with self._lock:
            cur = self._value
            delta = cur - self._window
            self._window = cur
        return cur, delta

    def render(self) -> str:
        return f"{self._head()}{self.name} {_fmt(self._value)}\n"


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        super().__init__(name, help, registry)
        self._value = 0.0
        self._fn: "object | None" = None

    def set(self, v: float) -> None:
        if not self._on:
            return
        try:
            self._value = float(v)
        except (TypeError, ValueError):
            return

    def set_fn(self, fn: "object | None") -> None:
        """Computed gauge: ``value``/``render`` call ``fn()`` at SCRAPE
        time instead of reporting the last ``set()``.  This is for
        staleness-style signals ("seconds since the last heartbeat
        publish") where a value written at event time is always 0 and the
        interesting number only exists when somebody reads it.  ``fn``
        must be cheap and never block; errors fall back to the last
        ``set()`` value.  Pass None to clear."""
        self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())  # type: ignore[operator]
            except Exception:  # noqa: BLE001 - a broken fn reads as the last set
                pass
        return self._value

    def render(self) -> str:
        return f"{self._head()}{self.name} {_fmt(self.value)}\n"


class Histogram(_Instrument):
    """Fixed-bucket histogram: ``observe`` is one ``bisect`` + three adds
    under the lock — O(log buckets), constant-size state, no per-sample
    allocation (the hot-path contract)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        registry: "MetricsRegistry",
        buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
    ):
        super().__init__(name, help, registry)
        self.buckets: tuple[float, ...] = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._window = (list(self._counts), 0.0, 0)

    def observe(self, v: float) -> None:
        if not self._on:
            return
        try:
            i = bisect.bisect_left(self.buckets, v)
            with self._lock:
                self._counts[i] += 1
                self._sum += v
                self._count += 1
        except TypeError:
            return

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """Approximate quantile from the bucket distribution: the upper
        bound of the bucket holding the q-th sample (the standard
        bucketed-histogram estimate; exact enough for dashboards)."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if not total:
            return 0.0
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank:
                if i < len(self.buckets):
                    return self.buckets[i]
                return self.buckets[-1] if self.buckets else 0.0
        return self.buckets[-1] if self.buckets else 0.0

    def snapshot_and_delta(self) -> tuple[dict, dict]:
        """(cumulative, delta-since-last-call) — each a dict with
        ``count``, ``sum``, and per-bucket ``counts``."""
        with self._lock:
            cur_counts = list(self._counts)
            cur = {"count": self._count, "sum": self._sum, "counts": cur_counts}
            prev_counts, prev_sum, prev_count = self._window
            delta = {
                "count": self._count - prev_count,
                "sum": self._sum - prev_sum,
                "counts": [a - b for a, b in zip(cur_counts, prev_counts)],
            }
            self._window = (cur_counts, self._sum, self._count)
        return cur, delta

    def render(self) -> str:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        lines = [self._head()]
        cumulative = 0
        for bound, c in zip(self.buckets, counts):
            cumulative += c
            lines.append(
                f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cumulative}\n'
            )
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}\n')
        lines.append(f"{self.name}_sum {_fmt(round(s, 6))}\n")
        lines.append(f"{self.name}_count {total}\n")
        return "".join(lines)


class MetricsRegistry:
    """Get-or-create instrument registry; same name must keep one type."""

    def __init__(self) -> None:
        self._instruments: dict[str, _Instrument] = {}
        self._lock = threading.Lock()
        self.enabled = True

    def set_enabled(self, on: bool) -> None:
        """Global kill switch (the overhead bench's tracing-off mode):
        recording becomes a single attribute check + return."""
        self.enabled = bool(on)

    def _get(self, cls: type, name: str, help: str, **kwargs) -> _Instrument:
        key = _sanitize(name)
        with self._lock:
            existing = self._instruments.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {key!r} already registered as "
                        f"{type(existing).__name__}, not {cls.__name__}"
                    )
                return existing
            inst = cls(name, help, self, **kwargs)
            self._instruments[key] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        inst = self._get(Counter, name, help)
        assert isinstance(inst, Counter)
        return inst

    def gauge(self, name: str, help: str = "") -> Gauge:
        inst = self._get(Gauge, name, help)
        assert isinstance(inst, Gauge)
        return inst

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
    ) -> Histogram:
        inst = self._get(Histogram, name, help, buckets=buckets)
        assert isinstance(inst, Histogram)
        return inst

    def render(self) -> str:
        """Prometheus text exposition v0 for every registered instrument."""
        with self._lock:
            instruments = sorted(
                self._instruments.values(), key=lambda i: i.name
            )
        return "".join(i.render() for i in instruments)


# the process default: engine + dispatcher instruments live here unless a
# caller wires its own registry
REGISTRY = MetricsRegistry()


def metrics_text(registry: MetricsRegistry | None = None) -> str:
    """The one public render entrypoint (and what the HTTP endpoint
    serves): Prometheus text exposition v0 of ``registry`` (default: the
    process registry)."""
    return (registry or REGISTRY).render()
