"""Engine flight recorder: a bounded ring journal of scheduler events.

The continuous-batching engine makes thousands of scheduling decisions per
second — admission, wave formation, page allocation, speculative drafting,
overlapped dispatch, deferred retirement — and when it misbehaves the
cumulative counters say *that* something went wrong, never *what sequence
of decisions* led there.  The flight recorder is the standard production
answer: a fixed-capacity ring of typed, timestamped events appended at
every decision point, cheap enough to leave on (``RuntimeConfig.
flightrec_events``, default on), dumped to JSONL only when someone asks:

- **engine fault** — any exception crossing the dispatch loop dumps the
  ring next to the traceback, so a crash ships its own postmortem;
- **SIGUSR2** — a live, healthy process can be asked for its recent
  history without stopping it (:func:`install_sigusr2`);
- **on demand** — ``GET /flightrec`` on the
  :class:`~calfkit_tpu.observability.http.MetricsServer`.

``ck timeline <correlation-id>`` reconstructs one request's lifecycle
from a dump (:func:`timeline_events` is the join; the CLI renders it),
keyed on the same trace/correlation id the tracing layer already
propagates.

Hot-path discipline (enforced by ``scripts/lint_hotpath.py``):
:meth:`FlightRecorder.append` is O(1) and lock-free — one atomic sequence
draw (``itertools.count`` increments under the GIL at C level), one tuple
store into a preallocated ring slot.  No dict construction, no string
formatting, no logging, on either side of the call.  Overflow overwrites
the oldest events and is *counted*, never silent
(``stats_snapshot()['flightrec']['dropped']``).

Failure policy: recording and dumping are telemetry.  A broken journal
writer must never mask the fault it was trying to document — every dump
trigger guards itself.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import itertools
import json
import os
import threading
import time
import weakref
from typing import Any, Iterable

__all__ = [
    "FlightRecorder",
    "EVENT_NAMES",
    "default_dump_dir",
    "dump_all",
    "dump_all_text",
    "install_sigusr2",
    "journals",
    "timeline_events",
]

# ------------------------------------------------------------ event codes
# One small int per scheduler decision point.  Event tuples are
# (seq, t_perf, code, corr, slot, a, b, note); the meaning of a/b per code
# is documented in ARG_LABELS (and docs/observability.md).
EV_SUBMIT = 0  # request entered a queue            a=prompt_len b=max_new
EV_ADMIT = 1  # short-lane activation               a=prompt_len b=reuse_len
EV_ADMIT_LONG = 2  # long-lane (sp) admission       a=prompt_len
EV_WAVE_FORM = 3  # prefill wave formed             a=rows b=bucket
EV_WAVE_LAND = 4  # prefill wave landed             a=rows b=elapsed_ms
EV_PREFILL_CHUNK = 5  # one chunk of a chunked wave a=idx b=n_chunks
EV_PAGE_ALLOC = 6  # KV pages reserved for a slot   a=pages b=shared_pages
EV_PAGE_FREE = 7  # a slot's page reservation freed
EV_PAGE_EVICT = 8  # prefix-cache eviction ran      a=pages_needed
EV_PREFIX_ACQ = 9  # shared-prefix pages acquired   a=pages
EV_PREFIX_REL = 10  # shared-prefix pages released  a=pages
EV_DISPATCH_LAUNCH = 11  # decode dispatch enqueued a=steps b=rows
EV_DISPATCH_LAND = 12  # decode dispatch synced     a=steps b=wasted
EV_SPEC_TICK = 13  # speculative verify dispatch    a=proposed b=emitted
EV_RETIRE = 14  # request retired (resources freed) a=generated
EV_RETIRE_DEFER = 15  # retired; frees deferred to the in-flight landing
EV_SLOT_FREE = 16  # slot returned to the free list
EV_CANCEL = 17  # consumer-cancelled request reaped
EV_FAULT = 18  # exception crossed the dispatch loop (note=repr)
EV_SHED = 19  # bounded admission refused the submit  a=pending b=limit
EV_EXPIRE = 20  # deadline passed (submit/queue/active) a=overdue_ms
EV_RAGGED_WAVE = 21  # unified dispatch: decode+chunk  a=decode_rows b=chunk_rows
EV_WEDGE = 22  # dispatch-progress watchdog tripped  a=stalled_ms b=pending
EV_ORPHAN = 23  # caller lease lapsed; run reaped    a=lapsed_ms

EVENT_NAMES: tuple[str, ...] = (
    "SUBMIT",
    "ADMIT",
    "ADMIT_LONG",
    "WAVE_FORM",
    "WAVE_LAND",
    "PREFILL_CHUNK",
    "PAGE_ALLOC",
    "PAGE_FREE",
    "PAGE_EVICT",
    "PREFIX_ACQ",
    "PREFIX_REL",
    "DISPATCH_LAUNCH",
    "DISPATCH_LAND",
    "SPEC_TICK",
    "RETIRE",
    "RETIRE_DEFER",
    "SLOT_FREE",
    "CANCEL",
    "FAULT",
    "SHED",
    "EXPIRE",
    "RAGGED_WAVE",
    "WEDGE",
    "ORPHAN",
)

# per-event meaning of the two int payload fields (the dump stays compact
# ints; labels are a render-time concern)
ARG_LABELS: dict[str, tuple[str, str]] = {
    "SUBMIT": ("prompt", "max_new"),
    "ADMIT": ("prompt", "reuse"),
    "ADMIT_LONG": ("prompt", ""),
    "WAVE_FORM": ("rows", "bucket"),
    "WAVE_LAND": ("rows", "ms"),
    "PREFILL_CHUNK": ("chunk", "n_chunks"),
    "PAGE_ALLOC": ("pages", "shared"),
    "PAGE_FREE": ("", ""),
    "PAGE_EVICT": ("needed", ""),
    "PREFIX_ACQ": ("pages", ""),
    "PREFIX_REL": ("pages", ""),
    "DISPATCH_LAUNCH": ("steps", "rows"),
    "DISPATCH_LAND": ("steps", "wasted"),
    "SPEC_TICK": ("proposed", "emitted"),
    "RETIRE": ("generated", ""),
    "RETIRE_DEFER": ("generated", ""),
    "SLOT_FREE": ("", ""),
    "CANCEL": ("", ""),
    "FAULT": ("", ""),
    "SHED": ("pending", "limit"),
    "EXPIRE": ("overdue_ms", ""),
    "RAGGED_WAVE": ("decode_rows", "chunk_rows"),
    "WEDGE": ("stalled_ms", "pending"),
    "ORPHAN": ("lapsed_ms", ""),
}

# batch-scoped events a request's timeline borrows from its active window
# (they have no corr of their own but describe dispatches/waves that
# covered the request's slot)
_BATCH_EVENTS = {
    "WAVE_FORM",
    "WAVE_LAND",
    "PREFILL_CHUNK",
    "DISPATCH_LAUNCH",
    "DISPATCH_LAND",
    "SPEC_TICK",
    "RAGGED_WAVE",
    "PAGE_EVICT",
    "FAULT",
}
# slot-scoped events included when their slot matches the request's
_SLOT_EVENTS = {"PAGE_FREE", "SLOT_FREE"}


# process-wide registry of live journals: what SIGUSR2 and the /flightrec
# endpoint dump.  WeakSet so an abandoned engine's journal is collectable.
_JOURNALS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()
_SIGUSR2_INSTALLED = False


def default_dump_dir() -> str:
    """Where fault/SIGUSR2 dumps land: ``$CALFKIT_FLIGHTREC_DIR`` else
    ``~/.cache/calfkit_tpu/flightrec``."""
    return os.environ.get("CALFKIT_FLIGHTREC_DIR") or os.path.expanduser(
        "~/.cache/calfkit_tpu/flightrec"
    )


class FlightRecorder:
    """Fixed-capacity ring journal of typed scheduler events.

    ``capacity`` rounds up to a power of two (the append path masks, never
    modulos); ``0`` disables recording entirely — :meth:`append` becomes a
    single attribute check.  Appends may come from the event loop AND the
    decode thread concurrently: the sequence counter is an
    ``itertools.count`` (atomic under the GIL) and each ring slot is
    replaced wholesale with an immutable tuple, so readers never observe a
    torn event — at worst a mix of generations, which :meth:`snapshot`
    re-orders by sequence number.
    """

    __slots__ = ("__weakref__", "_cap", "_mask", "_ring", "_seq", "dumped", "label")

    def __init__(self, capacity: int = 4096, *, label: str = ""):
        if capacity < 0:
            raise ValueError(f"flightrec capacity must be >= 0 (got {capacity})")
        cap = 1
        while cap < capacity:
            cap *= 2
        self._cap = cap if capacity else 0
        self._mask = self._cap - 1
        self._ring: "list[tuple | None]" = [None] * self._cap
        self._seq = itertools.count()
        self.dumped = 0
        self.label = label
        if self._cap:
            with _REGISTRY_LOCK:
                _JOURNALS.add(self)

    # ------------------------------------------------------------- record
    @hotpath
    def append(
        self,
        code: int,
        corr: "str | None" = None,
        slot: int = -1,
        a: int = 0,
        b: int = 0,
        note: "str | None" = None,
    ) -> None:
        """O(1) lock-free append — THE hot-path call.  ``corr`` must be a
        precomputed string (or None), never formatted here; ``a``/``b``
        are per-code int payloads (see ARG_LABELS).  ``note`` is for cold
        paths only (faults)."""
        if not self._cap:
            return
        i = next(self._seq)
        self._ring[i & self._mask] = (
            i, time.perf_counter(), code, corr, slot, a, b, note,
        )

    # ------------------------------------------------------------- inspect
    def snapshot(self) -> "list[tuple]":
        """The ring's current events, oldest first (sequence order)."""
        entries = [e for e in self._ring if e is not None]
        entries.sort(key=lambda e: e[0])
        return entries

    def counts(self) -> dict:
        """``{"appended", "dropped", "dumped"}`` — ring overflow is a
        counted signal, not silent truncation."""
        entries = self.snapshot()
        appended = (entries[-1][0] + 1) if entries else 0
        return {
            "appended": appended,
            "dropped": max(0, appended - self._cap),
            "dumped": self.dumped,
        }

    @property
    def capacity(self) -> int:
        return self._cap

    # --------------------------------------------------------------- dump
    def dump_lines(self, *, reason: str = "manual") -> "list[str]":
        """JSONL: one meta header line, then one line per event (oldest
        first).  Event times are converted to wall-clock seconds with an
        anchor taken NOW — good to the drift between construction and
        dump, which is what postmortems need."""
        entries = self.snapshot()
        anchor = time.time() - time.perf_counter()
        counts = self.counts()
        lines = [
            json.dumps(
                {
                    "flightrec": {
                        "label": self.label,
                        "capacity": self._cap,
                        "appended": counts["appended"],
                        "dropped": counts["dropped"],
                        "reason": reason,
                        "pid": os.getpid(),
                        "dumped_at_s": round(anchor + time.perf_counter(), 3),
                    }
                }
            )
        ]
        for seq, t, code, corr, slot, a, b, note in entries:
            event: dict = {
                "seq": seq,
                "t_s": round(anchor + t, 6),
                "event": (
                    EVENT_NAMES[code]
                    if 0 <= code < len(EVENT_NAMES)
                    else f"UNKNOWN_{code}"
                ),
                "corr": corr,
                "slot": slot,
                "a": a,
                "b": b,
            }
            if note is not None:
                event["note"] = note
            lines.append(json.dumps(event))
        return lines

    def dump(self, *, reason: str = "manual", path: "str | None" = None) -> str:
        """Write the JSONL dump; returns the file path.  Callers on fault
        rails must guard this — a broken writer never outranks the
        original fault."""
        if path is None:
            directory = default_dump_dir()
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S")
            name = self.label or "engine"
            path = os.path.join(
                directory,
                f"flightrec-{name}-{os.getpid()}-{stamp}-{id(self):x}.jsonl",
            )
        lines = self.dump_lines(reason=reason)
        # blocking-ok: the dump rails are fault/operator paths (dispatch
        # fault rail, SIGUSR2, /flightrec) — the process is already
        # failing or a human asked; stalling the loop here is accepted
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        self.dumped += 1
        return path


# ----------------------------------------------------- process-wide dumps
def journals() -> "list[FlightRecorder]":
    with _REGISTRY_LOCK:
        return list(_JOURNALS)


def dump_all(*, reason: str = "signal") -> "list[str]":
    """Dump every registered journal to its own file; broken writers are
    skipped (fail-open), successful paths returned."""
    paths: list[str] = []
    for journal in journals():
        try:
            paths.append(journal.dump(reason=reason))
        except Exception:  # noqa: BLE001 - telemetry never faults the caller
            continue
    return paths


def dump_all_text(*, reason: str = "http") -> str:
    """Concatenated JSONL of every registered journal (the ``/flightrec``
    endpoint body); empty string when none are registered."""
    lines: list[str] = []
    for journal in journals():
        try:
            lines.extend(journal.dump_lines(reason=reason))
            journal.dumped += 1
        except Exception:  # noqa: BLE001
            continue
    return "\n".join(lines) + ("\n" if lines else "")


def install_sigusr2() -> bool:
    """Best-effort, idempotent: SIGUSR2 dumps every registered journal to
    :func:`default_dump_dir`.  Returns True when the handler is (already)
    installed; False where signals are unavailable (non-main thread,
    restricted platforms) — callers never fault on this."""
    global _SIGUSR2_INSTALLED
    if _SIGUSR2_INSTALLED:
        return True
    try:
        import signal

        # chain, don't clobber: the host application may already use
        # SIGUSR2 (faulthandler stack dumps, log rotation) — its handler
        # keeps running after ours
        previous = signal.getsignal(signal.SIGUSR2)

        def _handler(signum: int, frame: Any) -> None:
            dump_all(reason="sigusr2")
            if callable(previous):
                try:
                    previous(signum, frame)
                except Exception:  # noqa: BLE001 - their handler, their bug
                    pass

        signal.signal(signal.SIGUSR2, _handler)
    except Exception:  # noqa: BLE001 - no SIGUSR2 here; recording still works
        return False
    _SIGUSR2_INSTALLED = True
    return True


# ------------------------------------------------------ timeline (ck CLI)
def parse_dump(lines: "Iterable[str]") -> "list[dict]":
    """Parse a JSONL dump into event dicts, skipping meta headers and
    undecodable lines (a truncated crash dump should still mostly read)."""
    events: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if (
            not isinstance(obj, dict)
            or "event" not in obj
            or not isinstance(obj.get("seq"), int)
        ):
            continue
        events.append(obj)
    events.sort(key=lambda e: e["seq"])
    return events


def timeline_events(events: "list[dict]", corr: str) -> "list[dict]":
    """One request's lifecycle from a parsed dump: every event carrying
    its correlation id, plus the batch-scoped events (waves, dispatches,
    spec ticks, faults) and slot-scoped frees that fall inside its active
    window — a deferred free lands AFTER the request's last own event
    (one-dispatch-late retirement), so the window extends to the slot's
    next SLOT_FREE."""
    own = [e for e in events if e.get("corr") == corr]
    if not own:
        return []
    start = own[0]["seq"]
    end = own[-1]["seq"]
    slot = next((e["slot"] for e in own if e.get("slot", -1) >= 0), -1)
    deferred = any(e["event"] == "RETIRE_DEFER" for e in own)
    freed = any(e["event"] == "SLOT_FREE" for e in own)
    if slot >= 0 and deferred and not freed:
        for e in events:
            if (
                e["seq"] > end
                and e.get("slot") == slot
                and e["event"] in _SLOT_EVENTS
            ):
                end = e["seq"]
                if e["event"] == "SLOT_FREE":
                    break
    selected = {e["seq"]: e for e in own}
    for e in events:
        if e["seq"] < start or e["seq"] > end or e["seq"] in selected:
            continue
        name = e["event"]
        if name in _BATCH_EVENTS or (
            name in _SLOT_EVENTS and slot >= 0 and e.get("slot") == slot
        ):
            selected[e["seq"]] = e
    return [selected[seq] for seq in sorted(selected)]
