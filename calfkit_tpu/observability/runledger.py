"""Run ledger: run-scoped observability across attempts (ISSUE 17).

PR 8/9 made runs survive replica and caller death, but every
failover/hedge/resume re-dispatch mints a fresh correlation id — so the
trace, the flight-recorder timeline, and the latency histograms all
describe *attempts*, never the *run* the user experienced.  This module
is the run-level half:

- :class:`RunLedger` — the supervising client's per-run record of every
  attempt (placement, marker kind, typed outcome, queue wait, tokens
  delivered, device time).  Appends are O(1) plain-dict mutations on the
  supervisor hot path (``@hotpath``-annotated so meshlint enforces no
  blocking/logging/formatting there); timestamps are passed IN by the
  caller from the ``cancellation.wall_clock`` seam, never read here.
  Typed :class:`~calfkit_tpu.models.records.RunRecord` models are built
  only on the cold paths (``run_report()``, export).
- :func:`publish_runs_soon` — fire-and-forget compacted export to
  ``mesh.runs`` (key = run_id), the ``publish_spans_soon`` pattern.
- :class:`RunWindowStore` + :func:`rollup_window` — the worker-side fold
  of ``mesh.runs`` records into per-agent sliding windows, and the PURE
  (``@no_wallclock``) rollup math producing
  :class:`~calfkit_tpu.models.records.SloRollupRecord`: run-level
  completion ratio, end-to-end p50/p95/p99, shed/failover/orphan rates,
  attempt amplification, error-budget burn.  Published compacted to
  ``mesh.slo`` on the control-plane heartbeat cadence; rendered by
  ``ck slo``; gateable as dotted metric paths in the sim suite.

Failure policy: the ledger is telemetry.  A corrupt run header degrades
to an un-linked run (``protocol.parse_run`` returns None — the PR 5
law); a broken export loses records, never requests; fold errors drop
the one record, never the feed.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Iterable

from calfkit_tpu import protocol
from calfkit_tpu.effects import hotpath, no_wallclock
from calfkit_tpu.models.records import (
    RunAttemptRecord,
    RunRecord,
    SloRollupRecord,
)

__all__ = [
    "RunLedger",
    "RunWindowStore",
    "publish_runs_soon",
    "rollup_window",
    "run_percentile",
    "DEFAULT_SLO_WINDOW_S",
    "DEFAULT_SLO_COMPLETION_TARGET",
]

# how many runs the client-side ledger retains (LRU; a long-lived client
# process must not grow without bound — finished runs age out oldest
# first once exported)
RUNS_CAP = 4096
# per-agent finished-run window entries the worker-side store retains
WINDOW_CAP = 2048
DEFAULT_SLO_WINDOW_S = 300.0
DEFAULT_SLO_COMPLETION_TARGET = 0.999

# attempt marker vocabulary (RunAttemptRecord.kind)
ATTEMPT_KINDS = ("first", "retry", "failover", "hedge", "resume")


class RunLedger:
    """Per-run attempt ledger on the client supervisor path.

    Hot-path appends mutate plain dicts/lists (no pydantic construction,
    no formatting, no clock reads — timestamps arrive as arguments);
    bounded LRU over run ids.  Cold paths (:meth:`run_report`,
    :meth:`export_record`, :meth:`finished_records`) build the typed
    models.
    """

    def __init__(self, cap: int = RUNS_CAP):
        self._cap = cap
        # run_id -> {"agent", "client_id", "started_at", "finished_at",
        #            "outcome", "error_type", "attempts": [dict, ...]}
        self._runs: "OrderedDict[str, dict[str, Any]]" = OrderedDict()

    # ------------------------------------------------------------ hot path
    @hotpath
    def begin_run(
        self,
        run_id: str,
        *,
        agent: str = "",
        client_id: str = "",
        started_at: float = 0.0,
        priority: str = "interactive",
    ) -> None:
        """O(1): open a run entry (idempotent — a resumed stream's second
        supervisor pass must not wipe recorded attempts)."""
        existing = self._runs.get(run_id)
        if existing is not None:
            self._runs.move_to_end(run_id)
            return
        self._runs[run_id] = {
            "agent": agent,
            "client_id": client_id,
            "started_at": started_at,
            "finished_at": 0.0,
            "outcome": "pending",
            "error_type": "",
            # priority class (ISSUE 20): the run's EFFECTIVE class as the
            # supervising client resolved it — the `ck slo` per-class fold
            "priority": priority,
            "attempts": [],
        }
        while len(self._runs) > self._cap:
            self._runs.popitem(last=False)

    @hotpath
    def note_attempt(
        self,
        run_id: str,
        *,
        attempt_no: int,
        correlation_id: str,
        kind: str = "first",
        placement: str = "",
        agent: str = "",
        started_at: float = 0.0,
    ) -> None:
        """O(1) append of one placement.  ``correlation_id`` is the join
        key to that attempt's spans and flight-recorder events (trace_id
        == correlation id by client convention) — the ``ck run`` stitch
        depends on it being recorded here."""
        run = self._runs.get(run_id)
        if run is None:
            return
        run["attempts"].append(
            {
                "attempt_no": attempt_no,
                "correlation_id": correlation_id,
                "kind": kind,
                "placement": placement,
                "agent": agent,
                "started_at": started_at,
                "finished_at": 0.0,
                "outcome": "pending",
                "error_type": "",
                "queue_wait_s": 0.0,
                "tokens_delivered": 0,
                "device_time_s": 0.0,
            }
        )

    @hotpath
    def note_outcome(
        self,
        run_id: str,
        correlation_id: str,
        *,
        outcome: str,
        error_type: str = "",
        finished_at: float = 0.0,
        tokens_delivered: int = 0,
        queue_wait_s: float = 0.0,
        device_time_s: float = 0.0,
    ) -> None:
        """Record one attempt's terminal.  Scans attempts newest-first
        (a run holds a handful of attempts; the latest is almost always
        the one terminating) — effectively O(1)."""
        run = self._runs.get(run_id)
        if run is None:
            return
        attempts = run["attempts"]
        for i in range(len(attempts) - 1, -1, -1):
            attempt = attempts[i]
            if attempt["correlation_id"] == correlation_id:
                if attempt["outcome"] != "pending":
                    # first signal wins: a zombie replica's late reply
                    # must not overwrite the supervisor's "superseded"
                    # verdict (and vice versa — whichever landed first
                    # is what the caller experienced)
                    return
                attempt["outcome"] = outcome
                attempt["error_type"] = error_type
                attempt["finished_at"] = finished_at
                if tokens_delivered:
                    attempt["tokens_delivered"] = tokens_delivered
                if queue_wait_s:
                    attempt["queue_wait_s"] = queue_wait_s
                if device_time_s:
                    attempt["device_time_s"] = device_time_s
                return

    @hotpath
    def add_tokens(self, run_id: str, correlation_id: str, n: int) -> None:
        """O(1) streaming token accounting for the attempt (newest-first
        scan, same law as :meth:`note_outcome`)."""
        run = self._runs.get(run_id)
        if run is None:
            return
        attempts = run["attempts"]
        for i in range(len(attempts) - 1, -1, -1):
            attempt = attempts[i]
            if attempt["correlation_id"] == correlation_id:
                attempt["tokens_delivered"] += n
                return

    @hotpath
    def finish_run(
        self,
        run_id: str,
        *,
        outcome: str,
        error_type: str = "",
        finished_at: float = 0.0,
    ) -> None:
        """O(1): close the run with its caller-visible outcome."""
        run = self._runs.get(run_id)
        if run is None:
            return
        run["outcome"] = outcome
        run["error_type"] = error_type
        run["finished_at"] = finished_at

    # ----------------------------------------------------------- cold path
    def run_report(self, run_id: str) -> "RunRecord | None":
        """The typed run-level report (``handle.run_report()``): every
        attempt with its placement, marker, and typed outcome."""
        run = self._runs.get(run_id)
        if run is None:
            return None
        return _build_record(run_id, run)

    def export_record(self, run_id: str) -> "RunRecord | None":
        return self.run_report(run_id)

    def run_ids(self) -> "list[str]":
        return list(self._runs)

    def finished_records(self) -> "list[RunRecord]":
        """Every closed run's record, oldest first (the sim harvest and
        test surface)."""
        return [
            _build_record(run_id, run)
            for run_id, run in self._runs.items()
            if run["outcome"] != "pending"
        ]


def _build_record(run_id: str, run: "dict[str, Any]") -> RunRecord:
    attempts = [RunAttemptRecord(**a) for a in run["attempts"]]
    return RunRecord(
        run_id=run_id,
        agent=run["agent"],
        client_id=run["client_id"],
        started_at=run["started_at"],
        finished_at=run["finished_at"],
        outcome=run["outcome"],
        error_type=run["error_type"],
        priority=run.get("priority", "interactive"),
        attempts=attempts,
        sheds=sum(1 for a in attempts if a.outcome == "shed"),
        failovers=sum(1 for a in attempts if a.kind == "failover"),
        hedges=sum(1 for a in attempts if a.kind == "hedge"),
        resumes=sum(1 for a in attempts if a.kind == "resume"),
        tokens_delivered=sum(a.tokens_delivered for a in attempts),
    )


def publish_runs_soon(
    publish: Any,
    records: "list[RunRecord]",
    tasks: "set[Any]",
    *,
    on_error: "Callable[[BaseException], None] | None" = None,
) -> None:
    """Fire-and-forget compacted export to ``mesh.runs`` (key = run_id)
    — the ``publish_spans_soon`` pattern: the export rides a task held in
    ``tasks`` until done, strictly fail-open (a failed export degrades to
    client-local ``run_report()`` visibility only)."""
    if not records:
        return

    async def export() -> None:
        try:
            for record in records:
                await publish(
                    protocol.RUNS_TOPIC,
                    record.to_wire(),
                    key=record.run_key().encode("utf-8"),
                    headers={protocol.HDR_WIRE: "span"},
                )
        except Exception as exc:  # noqa: BLE001 - telemetry never faults
            if on_error is not None:
                try:
                    on_error(exc)
                except Exception:  # noqa: BLE001
                    pass

    try:
        import asyncio

        task = asyncio.get_running_loop().create_task(export())
        tasks.add(task)  # hold a ref until done (GC safety)
        task.add_done_callback(tasks.discard)
    except Exception:  # noqa: BLE001 - no loop / shutting down: local only
        pass


# --------------------------------------------------------------- rollups
@no_wallclock
def run_percentile(values: "list[float]", q: float) -> float:
    """Deterministic nearest-rank percentile (the sim/report law — no
    interpolation jitter); 0.0 on empty input."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, int(q * len(ordered))))
    return float(ordered[rank])


@no_wallclock
def rollup_window(
    entries: "Iterable[dict[str, Any]]",
    *,
    agent: str,
    window_end: float,
    window_s: float = DEFAULT_SLO_WINDOW_S,
    node_id: str = "",
    target: float = DEFAULT_SLO_COMPLETION_TARGET,
) -> SloRollupRecord:
    """THE rollup fold: pure math from window entries (one dict per
    finished run — see :meth:`RunWindowStore.fold`) to the per-agent
    SLO record.  ``@no_wallclock`` by contract: the sim gates these
    numbers, so the fold must never observe host time — ``window_end``
    arrives from the caller's clock seam.

    Error-budget burn is the observed failure ratio over the allowed
    failure ratio for the completion objective: burn 1.0 = failing at
    exactly the budgeted rate, >1 = burning ahead of budget.
    """
    lo = window_end - window_s
    runs = 0
    completed = 0
    attempts = 0
    sheds = 0
    failovers = 0
    orphans = 0
    durations: "list[float]" = []
    # per-class sub-folds (ISSUE 20): entries predating the QoS ledger
    # carry no priority and count as the default class
    class_runs = {"interactive": 0, "batch": 0}
    class_completed = {"interactive": 0, "batch": 0}
    class_durations: "dict[str, list[float]]" = {
        "interactive": [], "batch": [],
    }
    for e in entries:
        if e["finished_at"] < lo:
            continue
        runs += 1
        attempts += max(1, int(e.get("attempts", 1)))
        cls = "batch" if e.get("priority") == "batch" else "interactive"
        class_runs[cls] += 1
        if e.get("outcome") == "ok":
            completed += 1
            class_completed[cls] += 1
        if e.get("sheds", 0):
            sheds += 1
        if e.get("failovers", 0):
            failovers += 1
        if e.get("error_type") == "mesh.orphaned":
            orphans += 1
        duration = max(0.0, e["finished_at"] - e.get("started_at", 0.0))
        durations.append(duration)
        class_durations[cls].append(duration)
    ratio = (completed / runs) if runs else 1.0
    allowed = 1.0 - target
    burn = ((1.0 - ratio) / allowed) if (runs and allowed > 0.0) else 0.0
    return SloRollupRecord(
        agent=agent,
        node_id=node_id,
        window_s=window_s,
        window_end=window_end,
        runs=runs,
        completed=completed,
        completion_ratio=ratio,
        e2e_p50_s=run_percentile(durations, 0.50),
        e2e_p95_s=run_percentile(durations, 0.95),
        e2e_p99_s=run_percentile(durations, 0.99),
        attempts=attempts,
        attempt_amplification=(attempts / runs) if runs else 1.0,
        shed_rate=(sheds / runs) if runs else 0.0,
        failover_rate=(failovers / runs) if runs else 0.0,
        orphan_rate=(orphans / runs) if runs else 0.0,
        slo_completion_target=target,
        error_budget_burn=burn,
        interactive_runs=class_runs["interactive"],
        interactive_completed=class_completed["interactive"],
        interactive_p95_s=run_percentile(class_durations["interactive"], 0.95),
        batch_runs=class_runs["batch"],
        batch_completed=class_completed["batch"],
        batch_p95_s=run_percentile(class_durations["batch"], 0.95),
    )


class RunWindowStore:
    """Worker-side fold of ``mesh.runs`` records into per-agent sliding
    windows (one bounded deque per agent), read by the control-plane
    heartbeat's SLO advert.  Fail-open by construction: an undecodable
    record drops, the feed lives on."""

    def __init__(self, cap: int = WINDOW_CAP):
        self._cap = cap
        self._by_agent: "dict[str, Deque[dict[str, Any]]]" = {}

    def fold(self, key: "bytes | str | None", value: "bytes | str | None") -> None:
        """Fold one ``mesh.runs`` record (tombstones and pending runs are
        skipped — windows hold FINISHED runs only)."""
        if not value:
            return
        try:
            record = RunRecord.from_wire(value)
        except Exception:  # noqa: BLE001 - fail-open: drop the one record
            return
        if record.outcome == "pending" or not record.agent:
            return
        window = self._by_agent.get(record.agent)
        if window is None:
            window = deque(maxlen=self._cap)
            self._by_agent[record.agent] = window
        window.append(
            {
                "started_at": record.started_at,
                "finished_at": record.finished_at,
                "outcome": record.outcome,
                "error_type": record.error_type,
                "priority": record.priority,
                "attempts": len(record.attempts),
                "sheds": record.sheds,
                "failovers": record.failovers,
            }
        )

    def agents(self) -> "list[str]":
        return list(self._by_agent)

    def rollup_for(
        self,
        agent: str,
        *,
        window_end: float,
        window_s: float = DEFAULT_SLO_WINDOW_S,
        node_id: str = "",
        target: float = DEFAULT_SLO_COMPLETION_TARGET,
    ) -> SloRollupRecord:
        return rollup_window(
            self._by_agent.get(agent, ()),
            agent=agent,
            window_end=window_end,
            window_s=window_s,
            node_id=node_id,
            target=target,
        )


# process-wide store: every worker's control plane folds the runs feed
# here (the leases-store pattern — one feed per worker process, shared
# by every hosted agent's SLO advert)
_STORE = RunWindowStore()


def run_window_store() -> RunWindowStore:
    return _STORE


def reset_run_window_store() -> RunWindowStore:
    """Fresh process store (test/sim isolation)."""
    global _STORE
    _STORE = RunWindowStore()
    return _STORE
