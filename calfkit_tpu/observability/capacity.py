"""Capacity observatory: page-grain HBM attribution and occupancy
timelines (ISSUE 19).

HBM pages are the scarcest serving resource — ~138 MB of HBM traffic per
decoded token at 1.1B, KV-dominated — yet before this module nothing in
the system could answer "who holds HBM right now, how full are we over
time, and how much headroom does this replica have?"  Two pieces:

- :class:`PageLedger` — a mirror of page *ownership* maintained O(1) at
  the engine's existing alloc/free/evict sites.  Every KV page is either
  **private** (held by a slot for one request, tagged with the request's
  correlation id, run id when present, and lane kind) or **chain-owned**
  (registered in the prefix cache under its chain hash, with a refcount
  mirroring :class:`~calfkit_tpu.inference.paged.PrefixCache`).  The
  ledger never allocates pages itself — it is telemetry over the
  allocator's decisions, queryable as the by-owner/by-chain breakdown in
  ``stats_snapshot()["capacity"]`` and the advert's headroom scalars.

- :class:`CapacitySampler` — a fixed-capacity, lock-free numeric ring
  (flightrec's ring discipline: power-of-two capacity, masked tuple
  stores, counted overflow; ``RuntimeConfig.capacity_samples``, 0=off)
  appending one occupancy sample per dispatch landing.  Dumps JSONL
  alongside flight-recorder dumps, serves ``GET /capacity`` on the
  MetricsServer, renders as ``ck capacity <agent>``.

Ownership semantics (the headroom contract): ``pages_in_use`` counts
pages attributed to a LIVE owner — slot-held private pages plus
referenced (refcount >= 1) prefix pages.  Zero-ref cached prefix pages
are *not* in use: the allocator can evict them on demand, so
``headroom_pages = pages_total - pages_in_use`` is exactly the page
count an admission could obtain right now (free-list pages + evictable
cached pages).  A drained engine therefore attributes every page to no
owner: ``pages_in_use == 0`` is the leak oracle
(:func:`calfkit_tpu.sim.chaos.assert_engine_drained`).

Hot-path discipline (enforced by meshlint ``RequiredRoots`` floors):
every ledger mutation and the sampler append are ``@hotpath`` — O(1)
dict/tuple work, no formatting, no logging; the rollup math
(:meth:`PageLedger.breakdown`, the analytic HBM model) is
``@no_wallclock`` — pure folds the simulator gates byte-identically.

Failure policy: attribution and sampling are telemetry.  A confused
ledger must never fault serving — every mutation tolerates pages or
slots it has never seen.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from contextvars import ContextVar
from typing import Any, Iterable

from calfkit_tpu.effects import hotpath, no_wallclock
from calfkit_tpu.observability import flightrec

__all__ = [
    "CapacitySampler",
    "PageLedger",
    "SAMPLE_FIELDS",
    "current_run",
    "dump_all_text",
    "hbm_bytes_per_token",
    "hbm_constants",
    "lane_kind",
    "parse_dump",
    "samplers",
]

# run-identity propagation into the engine (ISSUE 19): the node kernel
# sets this from the ``x-mesh-run`` header next to the deadline/lease
# contextvars, so the in-process engine's submit can tag page ownership
# with the logical run the request serves.  None = un-linked (pre-run
# emitters, direct engine use) — the ledger tags corr only.
current_run: "ContextVar[str | None]" = ContextVar(
    "calfkit_current_run", default=None
)


def lane_kind(history: Any = None, *, long_lane: bool = False) -> str:
    """The owner tag's lane: ``long`` for the sequence-parallel lane,
    ``spec`` when speculation maintains a history for the request,
    ``decode`` otherwise.  (``prefill`` is reserved for chunked
    admission waves that pin pages before activation — the current
    engine activates in the same tick, so it never appears.)"""
    if long_lane:
        return "long"
    return "spec" if history is not None else "decode"


# ------------------------------------------------------------- the ledger
class PageLedger:
    """Owner attribution for every page in a paged-KV pool (see module
    docstring for the ownership semantics).

    Mutations mirror the engine's allocator/prefix-cache transitions:

    - :meth:`alloc` — a slot reserved ``n`` private pages at admission
    - :meth:`transfer` — fresh full-prompt pages moved slot → chain
      ownership at prefix registration (refcount 1: the registering
      request still holds them as shared)
    - :meth:`acquire` / :meth:`release` — chain-page refcounts, exactly
      where ``PrefixCache.acquire/release`` run
    - :meth:`free` — a slot's remaining private pages returned
    - :meth:`evicted` — a zero-ref chain page reclaimed under pressure
      (the hook ``PrefixCache.evict`` calls per freed page)

    Single-writer by construction: the engine mutates pages from the
    event-loop admission path and the decode-thread retirement path,
    never concurrently — the same discipline the allocator itself relies
    on, so the ledger needs no lock.
    """

    __slots__ = (
        "pages_total",
        "_slots",
        "_chain_hash",
        "_chain_refs",
        "_private",
        "_shared_live",
        "_resident",
        "evicted_pages",
        "alloc_stalls",
    )

    def __init__(self, pages_total: int):
        # the allocatable pool (the allocator's pool minus its trash page)
        self.pages_total = max(0, int(pages_total))
        # slot -> (corr, run, lane, private_page_count)
        self._slots: "dict[int, tuple]" = {}
        # chain-owned pages: page -> chain hash / refcount (mirrors
        # PrefixCache._hash_of / _refs)
        self._chain_hash: "dict[int, Any]" = {}
        self._chain_refs: "dict[int, int]" = {}
        self._private = 0  # sum of slot-held private pages
        self._shared_live = 0  # chain pages with refcount >= 1
        self._resident = 0  # chain pages resident (any refcount)
        self.evicted_pages = 0  # cumulative pages reclaimed under pressure
        self.alloc_stalls = 0  # cumulative allocs that needed eviction

    # ----------------------------------------------------------- mutations
    @hotpath
    def alloc(
        self,
        slot: int,
        n: int,
        corr: "str | None" = None,
        run: "str | None" = None,
        lane: str = "decode",
    ) -> None:
        """A slot reserved ``n`` private pages.  ``corr``/``run`` must be
        precomputed strings (or None) — never formatted here."""
        prev = self._slots.pop(slot, None)
        if prev is not None:
            self._private -= prev[3]
        self._slots[slot] = (corr, run, lane, n)
        self._private += n

    @hotpath
    def free(self, slot: int) -> None:
        """A slot's private pages went back to the pool (idempotent,
        like ``PageAllocator.free``)."""
        prev = self._slots.pop(slot, None)
        if prev is not None:
            self._private -= prev[3]

    @hotpath
    def transfer(self, slot: int, pages: "list[int]", hashes: "list") -> None:
        """``len(pages)`` of a slot's private pages became chain-owned
        (prefix registration): each enters at refcount 1 — the
        registering request still references them as shared pages."""
        owner = self._slots.get(slot)
        if owner is not None and pages:
            corr, run, lane, n = owner
            moved = min(n, len(pages))
            self._slots[slot] = (corr, run, lane, n - moved)
            self._private -= moved
        refs = self._chain_refs
        for page, chain in zip(pages, hashes):
            held = refs.get(page)
            if held is not None:
                # already chain-owned (registration collision): acquire
                if held == 0:
                    self._shared_live += 1
                refs[page] = held + 1
                continue
            refs[page] = 1
            self._chain_hash[page] = chain
            self._resident += 1
            self._shared_live += 1

    @hotpath
    def acquire(self, pages: "list[int]") -> None:
        """Chain-page refcounts up (prefix reuse granted)."""
        refs = self._chain_refs
        for page in pages:
            held = refs.get(page)
            if held is None:
                continue  # not chain-owned here: tolerate, never fault
            if held == 0:
                self._shared_live += 1
            refs[page] = held + 1

    @hotpath
    def release(self, pages: "list[int]") -> None:
        """Chain-page refcounts down (retirement / dropped reuse plan)."""
        refs = self._chain_refs
        for page in pages:
            held = refs.get(page)
            if not held:
                continue  # unknown or already zero: tolerate
            refs[page] = held - 1
            if held == 1:
                self._shared_live -= 1

    @hotpath
    def evicted(self, page: int) -> None:
        """A chain page was reclaimed under allocation pressure — the
        per-page hook ``PrefixCache.evict`` calls."""
        held = self._chain_refs.pop(page, None)
        if held is None:
            return
        self._chain_hash.pop(page, None)
        self._resident -= 1
        if held > 0:
            self._shared_live -= 1
        self.evicted_pages += 1

    @hotpath
    def note_stall(self) -> None:
        """An admission's page alloc came up short and had to evict (or
        carry back) — the density pressure counter the advert exposes."""
        self.alloc_stalls += 1

    # ----------------------------------------------------------- occupancy
    @property
    def pages_in_use(self) -> int:
        """Pages attributed to a live owner (private + referenced chain
        pages).  0 on a drained engine — the leak oracle."""
        return self._private + self._shared_live

    @property
    def prefix_resident_pages(self) -> int:
        """Chain pages resident in the prefix cache (any refcount)."""
        return self._resident

    @property
    def headroom_pages(self) -> int:
        """Pages an admission could obtain right now: the free list plus
        evictable zero-ref cached pages."""
        return max(0, self.pages_total - self.pages_in_use)

    # ------------------------------------------------------------- rollups
    @no_wallclock
    def breakdown(self, top: int = 8) -> dict:
        """The by-owner / by-chain / by-lane occupancy rollup
        (``stats_snapshot()["capacity"]``, the ``ck capacity`` table).
        Row counts are capped at ``top`` with the remainder summed —
        truncation is counted, never silent."""
        owners = [o for o in self._slots.values() if o[3] > 0]
        owners.sort(key=lambda o: (-o[3], o[0] or ""))
        by_lane: dict = {}
        for _corr, _run, lane, n in owners:
            by_lane[lane] = by_lane.get(lane, 0) + n
        if self._shared_live:
            by_lane["shared"] = self._shared_live
        chains = sorted(
            self._chain_refs.items(), key=lambda kv: (-kv[1], kv[0])
        )
        return {
            "pages_total": self.pages_total,
            "pages_in_use": self.pages_in_use,
            "headroom_pages": self.headroom_pages,
            "private_pages": self._private,
            "shared_referenced_pages": self._shared_live,
            "prefix_resident_pages": self._resident,
            "evicted_pages": self.evicted_pages,
            "alloc_stalls": self.alloc_stalls,
            "by_owner": [
                {"corr": corr, "run": run, "lane": lane, "pages": n}
                for corr, run, lane, n in owners[:top]
            ],
            "by_owner_other_pages": sum(o[3] for o in owners[top:]),
            "by_lane": by_lane,
            "by_chain": [
                {"chain": _chain_str(self._chain_hash.get(page)), "refs": refs}
                for page, refs in chains[:top]
            ],
            "by_chain_other_pages": max(0, self._resident - top),
        }


def _chain_str(chain: Any) -> str:
    """Render a chain hash for rollups: hex for the engine's blake2b
    digests, str() for the simulator's synthetic keys."""
    if isinstance(chain, (bytes, bytearray)):
        return chain.hex()
    return str(chain)


# ------------------------------------------------------------- the sampler
# one sample per dispatch landing, in tuple position order (after seq, t)
SAMPLE_FIELDS: "tuple[str, ...]" = (
    "pages_in_use",
    "pages_free",
    "prefix_resident_pages",
    "active_slots",
    "pending",
    "tokens_per_dispatch",
    "hbm_bytes_per_token",
)

# process-wide registry of live samplers: what GET /capacity serves.
# WeakSet so an abandoned engine's sampler is collectable.
_SAMPLERS: "weakref.WeakSet[CapacitySampler]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


class CapacitySampler:
    """Fixed-capacity ring of numeric occupancy samples — flightrec's
    ring discipline applied to capacity timelines.

    ``capacity`` rounds up to a power of two (the append path masks,
    never modulos); ``0`` disables sampling entirely — :meth:`append`
    becomes a single attribute check, the default
    (``RuntimeConfig.capacity_samples = 0``).  Appends come from the
    decode thread (one per dispatch landing); readers on other threads
    never observe a torn sample — each ring slot is replaced wholesale
    with an immutable tuple and :meth:`snapshot` re-orders by sequence.

    ``append(..., t=...)`` takes an explicit timestamp so the simulator
    can inject virtual-clock time (``wall_anchor=False`` then keeps dump
    timestamps in virtual seconds instead of anchoring them to the wall
    clock).
    """

    __slots__ = (
        "__weakref__",
        "_cap",
        "_mask",
        "_ring",
        "_seq",
        "dumped",
        "label",
        "ledger",
        "wall_anchor",
    )

    def __init__(
        self,
        capacity: int = 0,
        *,
        label: str = "",
        ledger: "PageLedger | None" = None,
        wall_anchor: bool = True,
    ):
        if capacity < 0:
            raise ValueError(
                f"capacity_samples must be >= 0 (got {capacity})"
            )
        cap = 1
        while cap < capacity:
            cap *= 2
        self._cap = cap if capacity else 0
        self._mask = self._cap - 1
        self._ring: "list[tuple | None]" = [None] * self._cap
        self._seq = itertools.count()
        self.dumped = 0
        self.label = label
        # the ledger whose breakdown rides the dump's meta header (so a
        # capacity dump carries the attribution snapshot it sampled under)
        self.ledger = ledger
        self.wall_anchor = wall_anchor
        if self._cap:
            with _REGISTRY_LOCK:
                _SAMPLERS.add(self)

    # ------------------------------------------------------------- record
    @hotpath
    def append(
        self,
        pages_in_use: int,
        pages_free: int,
        prefix_resident_pages: int,
        active_slots: int,
        pending: int,
        tokens_per_dispatch: float,
        hbm_bytes_per_token: float,
        t: "float | None" = None,
    ) -> None:
        """O(1) lock-free append — one sample per dispatch landing.
        Field order is ``SAMPLE_FIELDS``; ``t`` defaults to
        ``time.perf_counter()`` (the simulator passes virtual time)."""
        if not self._cap:
            return
        i = next(self._seq)
        self._ring[i & self._mask] = (
            i,
            time.perf_counter() if t is None else t,
            pages_in_use,
            pages_free,
            prefix_resident_pages,
            active_slots,
            pending,
            tokens_per_dispatch,
            hbm_bytes_per_token,
        )

    # ------------------------------------------------------------ inspect
    def snapshot(self) -> "list[tuple]":
        """The ring's current samples, oldest first (sequence order)."""
        entries = [e for e in self._ring if e is not None]
        entries.sort(key=lambda e: e[0])
        return entries

    def counts(self) -> dict:
        """``{"appended", "dropped", "dumped"}`` — ring overflow is a
        counted signal, not silent truncation."""
        entries = self.snapshot()
        appended = (entries[-1][0] + 1) if entries else 0
        return {
            "appended": appended,
            "dropped": max(0, appended - self._cap),
            "dumped": self.dumped,
        }

    @property
    def capacity(self) -> int:
        return self._cap

    # --------------------------------------------------------------- dump
    def dump_lines(self, *, reason: str = "manual") -> "list[str]":
        """JSONL: one meta header line (including the ledger's current
        breakdown when attached), then one line per sample, oldest
        first."""
        entries = self.snapshot()
        anchor = (
            time.time() - time.perf_counter() if self.wall_anchor else 0.0
        )
        counts = self.counts()
        meta: dict = {
            "capacity": {
                "label": self.label,
                "capacity": self._cap,
                "appended": counts["appended"],
                "dropped": counts["dropped"],
                "reason": reason,
                "pid": os.getpid(),
                "fields": list(SAMPLE_FIELDS),
            }
        }
        if self.ledger is not None:
            meta["capacity"]["breakdown"] = self.ledger.breakdown()
        lines = [json.dumps(meta)]
        for entry in entries:
            sample: dict = {
                "seq": entry[0],
                "t_s": round(anchor + entry[1], 6),
            }
            for name, value in zip(SAMPLE_FIELDS, entry[2:]):
                sample[name] = value
            lines.append(json.dumps(sample))
        return lines

    def dump(self, *, reason: str = "manual", path: "str | None" = None) -> str:
        """Write the JSONL dump next to flight-recorder dumps; returns
        the file path.  Telemetry: callers on fault rails must guard."""
        if path is None:
            directory = flightrec.default_dump_dir()
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S")
            name = self.label or "engine"
            path = os.path.join(
                directory,
                f"capacity-{name}-{os.getpid()}-{stamp}-{id(self):x}.jsonl",
            )
        lines = self.dump_lines(reason=reason)
        # blocking-ok: dumps run on operator rails (/capacity, shutdown,
        # explicit CLI asks) — a human asked; stalling here is accepted
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        self.dumped += 1
        return path


# ----------------------------------------------------- process-wide dumps
def samplers() -> "list[CapacitySampler]":
    with _REGISTRY_LOCK:
        return list(_SAMPLERS)


def dump_all_text(*, reason: str = "http") -> str:
    """Concatenated JSONL of every registered sampler (the ``/capacity``
    endpoint body); empty string when none are registered."""
    lines: list[str] = []
    for sampler in samplers():
        try:
            lines.extend(sampler.dump_lines(reason=reason))
            sampler.dumped += 1
        except Exception:  # noqa: BLE001 - telemetry never faults the caller
            continue
    return "\n".join(lines) + ("\n" if lines else "")


def parse_dump(lines: "Iterable[str]") -> "tuple[dict | None, list[dict]]":
    """Parse a capacity JSONL dump into ``(meta, samples)``, skipping
    undecodable lines (a truncated dump should still mostly read).
    ``meta`` is the first header's ``capacity`` object, or None."""
    meta: "dict | None" = None
    samples: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if not isinstance(obj, dict):
            continue
        if "capacity" in obj and isinstance(obj["capacity"], dict):
            if meta is None:
                meta = obj["capacity"]
            continue
        if isinstance(obj.get("seq"), int) and SAMPLE_FIELDS[0] in obj:
            samples.append(obj)
    samples.sort(key=lambda s: s["seq"])
    return meta, samples


# --------------------------------------------------- analytic HBM roofline
@no_wallclock
def hbm_constants(model: Any, quantization: "str | None" = None) -> "tuple[float, float]":
    """``(weight_bytes, kv_bytes_per_context_token)`` — bench's
    ``_perf_model`` roofline constants, precomputed once so the
    per-dispatch sample pays two multiply-adds, not a model walk.
    Weight stream: params x dtype width (int8 halves it, int4 quarters);
    KV read: 2 (K+V) x layers x kv-heads x head_dim x 2 bytes."""
    weight_bytes = float(model.param_count) * {
        "int8": 1.0, "int4": 0.5,
    }.get(quantization, 2.0)
    kv_per_token = (
        2.0 * model.n_layers * model.n_kv_heads * model.head_dim * 2.0
    )
    return weight_bytes, kv_per_token


@no_wallclock
def hbm_bytes_per_token(
    constants: "tuple[float, float]", ctx: float, effective_bs: float
) -> float:
    """Analytic decode HBM traffic per token at mean context ``ctx``:
    the weight stream amortized over the effective batch plus the
    sequence's own KV read — the same formula bench's ``_perf_model``
    reports, so sampler timelines and bench verdicts agree."""
    weight_bytes, kv_per_token = constants
    return weight_bytes / max(float(effective_bs), 1e-9) + kv_per_token * ctx
