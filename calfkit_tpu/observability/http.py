"""A tiny asyncio HTTP endpoint for the metrics exposition.

No aiohttp, no framework: ``asyncio.start_server`` + a minimal HTTP/1.0
responder serving:

- ``GET /metrics`` — Prometheus text v0;
- ``GET /healthz`` — pure LIVENESS: ``200 ok`` from the moment the server
  listens, unconditionally.  It answers "is the process alive?", nothing
  more — an orchestrator restarts on its failure;
- ``GET /readyz`` — READINESS, backed by a registerable probe
  (:meth:`MetricsServer.set_readiness`): ``200`` only once the probe says
  the node can serve (engine weights loaded, dispatch lanes running),
  ``503`` with a reason otherwise.  A load balancer routes on this.  With
  no probe registered it reports ``503`` — "unknown" must never read as
  "ready";
- ``GET /flightrec`` — on-demand JSONL dump of every registered engine
  flight recorder (:mod:`calfkit_tpu.observability.flightrec`);
- ``GET /capacity`` — on-demand JSONL dump of every registered capacity
  sampler (:mod:`calfkit_tpu.observability.capacity`): the occupancy
  timeline ring plus the live page-attribution breakdown in the meta
  header.

This is an OPTIONAL operator convenience — nothing in the serving path
depends on it — so every failure mode closes the offending connection and
keeps listening.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable

from calfkit_tpu.observability.metrics import MetricsRegistry, metrics_text

logger = logging.getLogger(__name__)

_MAX_REQUEST_BYTES = 8192

# a probe returns bool, or (bool, reason)
ReadinessProbe = Callable[[], Any]


class MetricsServer:
    """``async with MetricsServer(port=9100): ...`` or start()/stop()."""

    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: MetricsRegistry | None = None,
        readiness: ReadinessProbe | None = None,
    ):
        self.host = host
        self.port = port  # 0 = OS-assigned; read back after start()
        self._registry = registry
        self._readiness = readiness
        self._server: asyncio.Server | None = None

    def set_readiness(self, probe: ReadinessProbe | None) -> None:
        """Register (or clear) the readiness probe behind ``/readyz``.
        The probe returns ``bool`` or ``(bool, reason)``; it is called per
        scrape, so keep it cheap.  Compose multiple conditions in the
        probe itself, e.g. ``lambda: (model.ready()[0] and worker.ready()[0],
        "engine + worker")``."""
        self._readiness = probe

    def _ready_state(self) -> "tuple[bool, str]":
        probe = self._readiness
        if probe is None:
            # fail-unready: a /readyz nobody wired must not pass traffic
            return False, "no readiness probe registered"
        try:
            result = probe()
            # normalize INSIDE the guard: a malformed probe return (e.g. a
            # 1-tuple) must degrade to a reasoned 503, not kill the request
            if isinstance(result, tuple):
                ok, reason = bool(result[0]), str(result[1])
            else:
                ok, reason = bool(result), ""
        except Exception as exc:  # noqa: BLE001 - a broken probe is unready
            return False, f"probe error: {exc!r}"
        return ok, reason

    async def start(self) -> None:
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        sockets = self._server.sockets or []
        if sockets:
            self.port = sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is None:
            return
        self._server.close()
        try:
            await self._server.wait_closed()
        except Exception:  # noqa: BLE001
            pass
        self._server = None

    async def __aenter__(self) -> "MetricsServer":
        await self.start()
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.stop()

    def _respond(self, path: str) -> "tuple[bytes, str, str]":
        """(body, status, content-type) for one GET path."""
        if path == "/metrics":
            return (
                metrics_text(self._registry).encode("utf-8"),
                "200 OK",
                "text/plain; version=0.0.4",
            )
        if path == "/healthz":
            # liveness ONLY: true from listen to shutdown, even before any
            # engine exists — readiness questions go to /readyz
            return b"ok\n", "200 OK", "text/plain"
        if path == "/readyz":
            ok, reason = self._ready_state()
            if ok:
                body = f"ready{': ' + reason if reason else ''}\n"
                return body.encode("utf-8"), "200 OK", "text/plain"
            body = f"unready{': ' + reason if reason else ''}\n"
            return body.encode("utf-8"), "503 Service Unavailable", "text/plain"
        if path == "/flightrec":
            from calfkit_tpu.observability import flightrec

            text = flightrec.dump_all_text(reason="http")
            if not text:
                return (
                    b"no flight recorders registered\n",
                    "404 Not Found",
                    "text/plain",
                )
            return text.encode("utf-8"), "200 OK", "application/x-ndjson"
        if path == "/capacity":
            from calfkit_tpu.observability import capacity

            text = capacity.dump_all_text(reason="http")
            if not text:
                return (
                    b"no capacity samplers registered\n",
                    "404 Not Found",
                    "text/plain",
                )
            return text.encode("utf-8"), "200 OK", "application/x-ndjson"
        return b"not found\n", "404 Not Found", "text/plain"

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request = await asyncio.wait_for(
                reader.readline(), timeout=5.0
            )
            if len(request) > _MAX_REQUEST_BYTES:
                raise ValueError("request line too long")
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # drain headers (bounded) so keep-alive clients see a clean close
            drained = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                drained += len(line)
                if line in (b"\r\n", b"\n", b"") or drained > _MAX_REQUEST_BYTES:
                    break
            body, status, ctype = self._respond(path.split("?", 1)[0])
            writer.write(
                (
                    f"HTTP/1.0 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode("latin-1")
                + body
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 - a bad client never kills the server
            logger.debug("metrics endpoint request failed", exc_info=True)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
