"""A tiny asyncio HTTP endpoint for the metrics exposition.

No aiohttp, no framework: ``asyncio.start_server`` + a minimal HTTP/1.0
responder serving ``GET /metrics`` (Prometheus text v0) and ``GET
/healthz``.  This is an OPTIONAL operator convenience — nothing in the
serving path depends on it — so every failure mode closes the offending
connection and keeps listening.
"""

from __future__ import annotations

import asyncio
import logging

from calfkit_tpu.observability.metrics import MetricsRegistry, metrics_text

logger = logging.getLogger(__name__)

_MAX_REQUEST_BYTES = 8192


class MetricsServer:
    """``async with MetricsServer(port=9100): ...`` or start()/stop()."""

    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: MetricsRegistry | None = None,
    ):
        self.host = host
        self.port = port  # 0 = OS-assigned; read back after start()
        self._registry = registry
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        sockets = self._server.sockets or []
        if sockets:
            self.port = sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is None:
            return
        self._server.close()
        try:
            await self._server.wait_closed()
        except Exception:  # noqa: BLE001
            pass
        self._server = None

    async def __aenter__(self) -> "MetricsServer":
        await self.start()
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.stop()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request = await asyncio.wait_for(
                reader.readline(), timeout=5.0
            )
            if len(request) > _MAX_REQUEST_BYTES:
                raise ValueError("request line too long")
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # drain headers (bounded) so keep-alive clients see a clean close
            drained = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                drained += len(line)
                if line in (b"\r\n", b"\n", b"") or drained > _MAX_REQUEST_BYTES:
                    break
            if path.split("?", 1)[0] == "/metrics":
                body = metrics_text(self._registry).encode("utf-8")
                status, ctype = "200 OK", "text/plain; version=0.0.4"
            elif path.split("?", 1)[0] == "/healthz":
                body, status, ctype = b"ok\n", "200 OK", "text/plain"
            else:
                body, status, ctype = b"not found\n", "404 Not Found", "text/plain"
            writer.write(
                (
                    f"HTTP/1.0 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode("latin-1")
                + body
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 - a bad client never kills the server
            logger.debug("metrics endpoint request failed", exc_info=True)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
