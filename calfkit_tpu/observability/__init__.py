"""Observability: tracing, metrics, and the engine flight recorder.

Four pieces:

- :mod:`~calfkit_tpu.observability.trace` — ``TraceContext`` propagation
  over Kafka record headers, spans, the process tracer with its bounded
  ring buffer (zero-broker fallback), and the ``mesh.traces`` export seam.
- :mod:`~calfkit_tpu.observability.metrics` — the dependency-free
  counter/gauge/histogram registry and Prometheus text exposition
  (``metrics_text``).
- :mod:`~calfkit_tpu.observability.flightrec` — the engine flight
  recorder: a bounded ring journal of scheduler events, dumped to JSONL
  on engine fault / SIGUSR2 / ``GET /flightrec`` and reconstructed per
  request by ``ck timeline``.
- :mod:`~calfkit_tpu.observability.http` — the optional asyncio endpoint:
  ``/metrics``, ``/healthz`` (liveness), ``/readyz`` (readiness probe),
  ``/flightrec``.
- :mod:`~calfkit_tpu.observability.runledger` — run-scoped observability
  (ISSUE 17): the client-side per-run attempt ledger behind
  ``handle.run_report()`` and the compacted ``mesh.runs`` export, plus
  the pure SLO rollup fold behind ``mesh.slo`` / ``ck slo``.

Everything here is fail-open: telemetry errors never fault serving.
"""

from calfkit_tpu.observability.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_text,
)
from calfkit_tpu.observability.trace import (
    TRACER,
    Span,
    TraceContext,
    Tracer,
    current_context,
)
from calfkit_tpu.observability.flightrec import FlightRecorder
from calfkit_tpu.observability.http import MetricsServer
from calfkit_tpu.observability.runledger import (
    RunLedger,
    RunWindowStore,
    rollup_window,
    run_window_store,
)

__all__ = [
    "FlightRecorder",
    "RunLedger",
    "RunWindowStore",
    "rollup_window",
    "run_window_store",
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "metrics_text",
    "TRACER",
    "Span",
    "TraceContext",
    "Tracer",
    "current_context",
]
