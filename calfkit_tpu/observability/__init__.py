"""Observability: mesh-wide distributed tracing + engine latency telemetry.

Three pieces (ISSUE 2 tentpole):

- :mod:`~calfkit_tpu.observability.trace` — ``TraceContext`` propagation
  over Kafka record headers, spans, the process tracer with its bounded
  ring buffer (zero-broker fallback), and the ``mesh.traces`` export seam.
- :mod:`~calfkit_tpu.observability.metrics` — the dependency-free
  counter/gauge/histogram registry and Prometheus text exposition
  (``metrics_text``).
- :mod:`~calfkit_tpu.observability.http` — the optional asyncio
  ``/metrics`` endpoint.

Everything here is fail-open: telemetry errors never fault serving.
"""

from calfkit_tpu.observability.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_text,
)
from calfkit_tpu.observability.trace import (
    TRACER,
    Span,
    TraceContext,
    Tracer,
    current_context,
)
from calfkit_tpu.observability.http import MetricsServer

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "metrics_text",
    "TRACER",
    "Span",
    "TraceContext",
    "Tracer",
    "current_context",
]
