"""The one-model-turn runner.

Semantics carried from the reference's use of the vendored loop with
``output_type=[final, DeferredToolRequests]`` (calfkit/nodes/agent.py:189,
662-689): a turn is exactly ONE model request; any tool calls in the response
are *deferred* — returned to the caller for dispatch over the mesh — never
executed in-process.  Structured output rides an output tool
(``final_result``); malformed structured output triggers bounded in-turn
retries before surfacing a validation fault.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from pydantic import TypeAdapter, ValidationError

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
)
from calfkit_tpu.engine.schema import output_tool_def
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.exceptions import NodeFaultError, error_type_for
from calfkit_tpu.models.error_report import ErrorReport, FaultTypes, safe_str
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    ToolCallOutput,
    Usage,
)
from calfkit_tpu.models.node_result import extract_lenient

FINAL_RESULT_TOOL = "final_result"

# vendor/in-tree phrasings of "the prompt does not fit the model":
# JaxLocalModelClient ("exceeds max_seq_len"/"exceeds long_max_prompt"),
# OpenAI ("maximum context length"), Anthropic ("prompt is too long"),
# generic "context window"
_CONTEXT_OVERFLOW_MARKERS = (
    "context window", "context length", "context_length",
    "prompt is too long", "exceeds max_seq_len", "exceeds long_max_prompt",
)

# providers' STRUCTURED error codes for "prompt does not fit"
_CONTEXT_OVERFLOW_CODES = frozenset({
    "context_length_exceeded",        # OpenAI error.code
    "context_window_exceeded",
})

# GENERIC request-rejected spellings (Anthropic, OpenAI-compatible proxies/
# SGLang/vLLM) that say nothing about WHY — these fall through to the
# message heuristic.  Any other specific code is authoritative non-overflow.
_GENERIC_ERROR_CODES = frozenset({
    "invalid_request_error", "badrequesterror", "bad_request",
    "invalid_request", "bad_request_error",
})


def _is_context_overflow(exc: BaseException, message: str) -> bool:
    """Classify by the provider's structured error fields first; substring
    matching is only a fallback (the raw text can include an echoed HTTP
    body, and user text saying 'context window' must not flip the fault
    type).  A SPECIFIC structured code that is not an overflow code is
    authoritative non-overflow; generic request-rejected codes (Anthropic's
    overflow spelling carries no dedicated code; compat backends use bare
    BadRequestError) fall through to the provider's own message field."""
    code = getattr(exc, "error_code", None)
    if isinstance(code, str):
        lc = code.lower()
        # exact overflow codes, plus proxy class-name spellings like
        # ContextWindowExceededError
        if lc in _CONTEXT_OVERFLOW_CODES or (
            "context" in lc and ("exceed" in lc or "length" in lc)
        ):
            return True
        if lc not in _GENERIC_ERROR_CODES:
            return False
    api_message = getattr(exc, "error_message", None)
    if isinstance(api_message, str):
        lowered = api_message.lower()
    else:
        lowered = message.lower()
    return any(marker in lowered for marker in _CONTEXT_OVERFLOW_MARKERS)


class TurnError(Exception):
    def __init__(self, report: ErrorReport):
        self.report = report
        super().__init__(report.describe())


@dataclass
class TurnOutcome:
    """What one model turn produced.

    Exactly one of:
    - ``tool_calls`` non-empty → the caller dispatches them over the mesh;
    - otherwise ``output`` is the final (possibly structured) result.
    ``new_messages`` are the wire-state messages to commit either way.
    """

    new_messages: list[ModelMessage]
    response: ModelResponse
    usage: Usage
    tool_calls: list[ToolCallOutput] = field(default_factory=list)
    output: Any = None

    @property
    def is_final(self) -> bool:
        return not self.tool_calls


async def run_turn(
    model: ModelClient,
    messages: list[ModelMessage],
    *,
    tool_defs: list[ToolDef] | None = None,
    output_type: type = str,
    settings: ModelSettings | None = None,
    author: str | None = None,
    max_output_retries: int = 2,
) -> TurnOutcome:
    """Run one model turn against ``messages`` (already including any staged
    user prompt / tool returns as the final request)."""
    structured = output_type is not str
    params = ModelRequestParameters(
        tool_defs=list(tool_defs or []),
        output_tool=output_tool_def(output_type) if structured else None,
        allow_text_output=not structured,
    )
    adapter: TypeAdapter[Any] | None = TypeAdapter(output_type) if structured else None

    working = list(messages)
    new_messages: list[ModelMessage] = []
    usage = Usage()
    last_error: Exception | None = None

    for _attempt in range(max_output_retries + 1):
        try:
            response = await model.request(working, settings, params)
        except NodeFaultError:
            raise
        except Exception as exc:
            # a backend failure is a MODEL fault, not a generic node error:
            # the typed report lets callers/seams match on mesh.model_error
            # (context-window overflows keep their own narrower type, and
            # exceptions in the authoritative x-mesh-error-type table —
            # EngineOverloadedError above all — keep THEIR code: an engine
            # shed crossing this wrap as mesh.model_error would hide a
            # retriable overload as a model bug).
            # safe_str: a hostile __str__ must not defeat the typed mint.
            message = safe_str(exc)
            error_type = (
                FaultTypes.CONTEXT_WINDOW_EXCEEDED
                if _is_context_overflow(exc, message)
                else error_type_for(exc) or FaultTypes.MODEL_ERROR
            )
            raise NodeFaultError(
                ErrorReport.build_safe(
                    error_type,
                    f"model request failed ({model.model_name}): "
                    f"{type(exc).__name__}: {message}",
                    exc=exc,
                )
            ) from exc
        if author and response.author is None:
            response = response.model_copy(update={"author": author})
        usage = usage + response.usage
        new_messages.append(response)

        calls = response.tool_calls()
        final_calls = [c for c in calls if c.tool_name == FINAL_RESULT_TOOL]
        dispatch_calls = [c for c in calls if c.tool_name != FINAL_RESULT_TOOL]

        if dispatch_calls:
            # tool calls defer to the mesh; a stray final_result alongside
            # them is ignored this turn (the model will be re-asked)
            return TurnOutcome(
                new_messages=new_messages,
                response=response,
                usage=usage,
                tool_calls=dispatch_calls,
            )

        retry: RetryPart | None = None
        if structured:
            assert adapter is not None
            if final_calls:
                call = final_calls[0]
                try:
                    output = adapter.validate_python(call.args_dict())
                    return TurnOutcome(
                        new_messages=new_messages,
                        response=response,
                        usage=usage,
                        output=output,
                    )
                except (ValidationError, ValueError) as exc:
                    last_error = exc
                    retry = RetryPart(
                        content=f"Invalid {FINAL_RESULT_TOOL} arguments: {exc}. "
                        "Call it again with arguments matching the schema.",
                        tool_call_id=call.tool_call_id,
                        tool_name=FINAL_RESULT_TOOL,
                    )
            else:
                text = response.text()
                try:
                    output = extract_lenient(text, adapter)
                    return TurnOutcome(
                        new_messages=new_messages,
                        response=response,
                        usage=usage,
                        output=output,
                    )
                except (ValidationError, ValueError) as exc:
                    last_error = exc
                    retry = RetryPart(
                        content="Your reply must be the final structured result: "
                        f"call the {FINAL_RESULT_TOOL} tool with arguments matching "
                        f"the schema (error: {exc})."
                    )
        else:
            return TurnOutcome(
                new_messages=new_messages,
                response=response,
                usage=usage,
                output=response.text(),
            )

        retry_request = ModelRequest(parts=[retry])
        new_messages.append(retry_request)
        working = working + [response, retry_request]

    raise TurnError(
        ErrorReport.build_safe(
            FaultTypes.VALIDATION_ERROR,
            f"model failed to produce valid structured output after "
            f"{max_output_retries + 1} attempts: {last_error}",
        )
    )
