"""The agent-turn engine: model-client contract + turn runner.

This is the owned equivalent of the load-bearing subset of the reference's
vendored pydantic-ai (SURVEY.md §2.2 "Rebuild note"): a model-client ABC,
function-signature → JSON-schema extraction, deterministic test models, and
the one-model-turn runner with structured output and deferred tool calls.
"""

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    StreamEvent,
    TextDelta,
)
from calfkit_tpu.engine.schema import FunctionSchema, function_schema
from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL, TurnOutcome, run_turn
from calfkit_tpu.engine.testing import (
    EchoModelClient,
    FunctionModelClient,
    TestModelClient,
)

__all__ = [
    "EchoModelClient",
    "FINAL_RESULT_TOOL",
    "FunctionModelClient",
    "FunctionSchema",
    "ModelClient",
    "ModelRequestParameters",
    "ModelSettings",
    "ResponseDone",
    "StreamEvent",
    "TestModelClient",
    "TextDelta",
    "TurnOutcome",
    "function_schema",
    "run_turn",
]
