"""Function-signature → JSON-schema extraction and validated invocation.

The owned equivalent of the vendored ``function_schema`` machinery the
reference's ToolNode leans on (reference: calfkit/nodes/tool.py:12,67,153
importing Tool/function_schema from the vendor tree).

- parameters come from the signature's annotations via pydantic;
- descriptions come from a Google/NumPy/Sphinx-tolerant docstring scan;
- a leading ``ctx`` parameter (by name, or annotated with a type whose name
  ends in ``RunContext``/``Context``) receives the node's run context and is
  excluded from the model-facing schema;
- ``call()`` validates args, injects ctx, and awaits coroutine functions.
"""

from __future__ import annotations

import asyncio
import inspect
import re
from dataclasses import dataclass
from typing import Any, Callable, get_type_hints

from pydantic import ConfigDict, TypeAdapter, create_model

from calfkit_tpu.models.capability import ToolDef


class ToolSchemaError(TypeError):
    pass


_DOC_ARG = re.compile(
    r"^\s*(?:Args?|Arguments|Parameters)\s*:?\s*$", re.IGNORECASE
)
_DOC_PARAM = re.compile(r"^\s{2,}(\*{0,2}\w+)\s*(?:\(([^)]*)\))?\s*:\s*(.+)$")
_SPHINX_PARAM = re.compile(r"^\s*:param\s+(\w+)\s*:\s*(.+)$")


def _docstring_info(fn: Callable[..., Any]) -> tuple[str, dict[str, str]]:
    """(summary, {param: description}) from the docstring, best-effort."""
    doc = inspect.getdoc(fn) or ""
    lines = doc.splitlines()
    summary_lines: list[str] = []
    for line in lines:
        if not line.strip():
            break
        summary_lines.append(line.strip())
    params: dict[str, str] = {}
    in_args = False
    for line in lines:
        sphinx = _SPHINX_PARAM.match(line)
        if sphinx:
            params[sphinx.group(1)] = sphinx.group(2).strip()
            continue
        if _DOC_ARG.match(line):
            in_args = True
            continue
        if in_args:
            if line.strip() and not line.startswith(" "):
                in_args = False
                continue
            m = _DOC_PARAM.match(line)
            if m:
                params[m.group(1).lstrip("*")] = m.group(3).strip()
    return " ".join(summary_lines), params


def _is_context_param(name: str, annotation: Any) -> bool:
    if name in ("ctx", "context"):
        return True
    ann_name = getattr(annotation, "__name__", "")
    return ann_name.endswith(("RunContext", "Context"))


@dataclass
class FunctionSchema:
    tool_def: ToolDef
    fn: Callable[..., Any]
    takes_ctx: bool
    _adapter: TypeAdapter[Any]
    _param_names: list[str]

    def validate_args(self, args: dict[str, Any]) -> dict[str, Any]:
        """Validate/coerce raw args against the signature; raises
        pydantic.ValidationError on mismatch (the model-retry trigger)."""
        validated = self._adapter.validate_python(args)
        return {name: getattr(validated, name) for name in self._param_names}

    async def call(self, args: dict[str, Any], ctx: Any = None) -> Any:
        kwargs = self.validate_args(args)
        if self.takes_ctx:
            result = self.fn(ctx, **kwargs)
        else:
            result = self.fn(**kwargs)
        if asyncio.iscoroutine(result):
            result = await result
        return result


def function_schema(
    fn: Callable[..., Any],
    *,
    name: str | None = None,
    description: str | None = None,
) -> FunctionSchema:
    sig = inspect.signature(fn)
    try:
        hints = get_type_hints(fn)
    except Exception:  # noqa: BLE001 - unresolvable annotations degrade to Any
        hints = {}
    summary, param_docs = _docstring_info(fn)

    fields: dict[str, Any] = {}
    takes_ctx = False
    param_names: list[str] = []
    for i, (pname, param) in enumerate(sig.parameters.items()):
        if param.kind in (param.VAR_POSITIONAL, param.VAR_KEYWORD):
            raise ToolSchemaError(
                f"tool {fn.__name__!r}: *args/**kwargs are not schema-expressible"
            )
        annotation = hints.get(pname, param.annotation)
        if i == 0 and _is_context_param(pname, annotation):
            takes_ctx = True
            continue
        if annotation is inspect.Parameter.empty:
            annotation = Any
        default = ... if param.default is inspect.Parameter.empty else param.default
        fields[pname] = (annotation, default)
        param_names.append(pname)

    # forbid extras: a model hallucinating an argument name must get a
    # ValidationError (the retry trigger), not have it silently dropped
    model = create_model(
        f"{fn.__name__}_args",
        __config__=ConfigDict(extra="forbid"),
        **fields,
    )
    adapter: TypeAdapter[Any] = TypeAdapter(model)
    schema = adapter.json_schema()
    schema.pop("title", None)
    for prop_name, prop in schema.get("properties", {}).items():
        prop.pop("title", None)
        if prop_name in param_docs:
            prop.setdefault("description", param_docs[prop_name])

    return FunctionSchema(
        tool_def=ToolDef(
            name=name or fn.__name__,
            description=description if description is not None else summary,
            parameters_schema=schema,
        ),
        fn=fn,
        takes_ctx=takes_ctx,
        _adapter=adapter,
        _param_names=param_names,
    )


def output_tool_def(output_type: type, *, name: str = "final_result") -> ToolDef:
    """The structured-output tool: the model 'calls' it with the final answer.

    Deliberately does NOT force ``extra="forbid"`` the way tool-args models
    do: args models are framework-synthesized from a signature (no user
    config exists, so strictness is ours to choose), while the output type
    is USER-owned — their model's own ``extra`` policy is law here.
    """
    adapter: TypeAdapter[Any] = TypeAdapter(output_type)
    schema = adapter.json_schema()
    schema.pop("title", None)
    return ToolDef(
        name=name,
        description="Submit the final result of this conversation.",
        parameters_schema=schema,
    )
