"""The provider interface: what a model backend must implement.

This is the seam the TPU backend plugs into (reference: the vendored `Model`
ABC at calfkit/_vendor/pydantic_ai/models/__init__.py:621, ``request()``
:648, ``request_stream()`` :671 — SURVEY.md §1 layer 4 calls it "the seam
the TPU backend replaces").  Implementations in-tree:

- :class:`calfkit_tpu.inference.JaxLocalModelClient` — the local TPU path;
- :mod:`calfkit_tpu.engine.testing` — deterministic models for tests;
- remote-API fallbacks can be added the same way.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, AsyncIterator, Union

from pydantic import BaseModel, Field

from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.models.messages import ModelMessage, ModelResponse


class ModelSettings(BaseModel):
    """Per-request generation knobs (all optional; backends ignore unknowns)."""

    max_tokens: int | None = None
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    stop_sequences: list[str] = Field(default_factory=list)
    seed: int | None = None
    # decode-from-offset resume (ISSUE 10): text of THIS answer already
    # delivered to the caller by a failed-over attempt.  A backend that
    # honors it admits the prefix via prefill (the survivor's prefix
    # cache absorbs the shared prompt pages), decodes only the remaining
    # tokens, yields a ResumeOffset stream event first, and returns the
    # FULL answer (prefix + continuation) in its terminal response.
    # Backends that ignore it simply re-generate — the caller-side
    # StreamLedger dedupes either way.
    resume_text: str | None = None
    extra: dict[str, Any] = Field(default_factory=dict)


class ModelRequestParameters(BaseModel):
    """What the agent loop hands the model besides messages."""

    tool_defs: list[ToolDef] = Field(default_factory=list)
    # structured output via an output tool (the model "calls" this tool with
    # the final answer); None means plain-text output
    output_tool: ToolDef | None = None
    allow_text_output: bool = True

    def all_tools(self) -> list[ToolDef]:
        return self.tool_defs + ([self.output_tool] if self.output_tool else [])


@dataclass(frozen=True)
class TextDelta:
    """Incremental generated text."""

    text: str


@dataclass(frozen=True)
class ResumeOffset:
    """First event of a RESUMED stream (ISSUE 10): the backend honored
    ``ModelSettings.resume_text`` and this attempt's TextDeltas begin at
    character ``chars`` of the answer — nothing before that offset is
    re-generated.  Consumers that ignore it see only the fresh text."""

    chars: int


@dataclass(frozen=True)
class ResponseDone:
    """Terminal stream event carrying the complete response."""

    response: ModelResponse


StreamEvent = Union[TextDelta, ResumeOffset, ResponseDone]


class ModelClient(abc.ABC):
    """A model backend.  Implementations must be safe for concurrent
    ``request`` calls (the worker batches them)."""

    @property
    @abc.abstractmethod
    def model_name(self) -> str: ...

    @abc.abstractmethod
    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse: ...

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> AsyncIterator[StreamEvent]:
        """Streaming generation; the default adapter degrades to one shot."""
        response = await self.request(messages, settings, params)
        text = response.text()
        if text:
            yield TextDelta(text)
        yield ResponseDone(response)
