"""Deterministic model clients for the offline test lane.

Equivalents of the vendored ``FunctionModel`` / ``TestModel`` the reference's
tests lean on everywhere (SURVEY.md §4: "this is how agent turns are tested
without any model API"), plus an ``EchoModelClient`` used by the quickstart's
no-weights mode.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Awaitable, Callable, Union

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
)
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.models.payload import ContentPart, render_parts_as_text

ModelFunction = Callable[
    [list[ModelMessage], ModelRequestParameters],
    Union[ModelResponse, Awaitable[ModelResponse]],
]


def _estimate_tokens(messages: list[ModelMessage]) -> int:
    return sum(len(str(m)) // 4 for m in messages)


class FunctionModelClient(ModelClient):
    """A Python function as the model (reference analog: FunctionModel)."""

    def __init__(self, fn: ModelFunction, *, name: str = "function-model"):
        self._fn = fn
        self._name = name

    @property
    def model_name(self) -> str:
        return self._name

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        result = self._fn(messages, params or ModelRequestParameters())
        if hasattr(result, "__await__"):
            result = await result  # type: ignore[assignment]
        response: ModelResponse = result  # type: ignore[assignment]
        if not response.usage.input_tokens:
            response = response.model_copy(
                update={
                    "usage": Usage(
                        input_tokens=_estimate_tokens(messages),
                        output_tokens=_estimate_tokens([response]),
                    )
                }
            )
        if response.model_name is None:
            response = response.model_copy(update={"model_name": self._name})
        return response


def _last_user_text(messages: list[ModelMessage]) -> str:
    for message in reversed(messages):
        if isinstance(message, ModelRequest):
            for part in reversed(message.parts):
                if isinstance(part, UserPart):
                    if isinstance(part.content, str):
                        return part.content
                    return render_parts_as_text(part.content)
    return ""


class EchoModelClient(ModelClient):
    """Echoes the latest user prompt — the zero-weights quickstart model."""

    def __init__(self, *, prefix: str = "echo: ", name: str = "echo-model"):
        self._prefix = prefix
        self._name = name

    @property
    def model_name(self) -> str:
        return self._name

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        return ModelResponse(
            parts=[TextOutput(text=f"{self._prefix}{_last_user_text(messages)}")],
            usage=Usage(input_tokens=_estimate_tokens(messages), output_tokens=8),
            model_name=self._name,
        )


class TestModelClient(ModelClient):
    """Calls every available tool once (with schema-derived stub args), then
    produces a final text or structured output (reference analog: TestModel).
    """

    __test__ = False  # not a pytest collectible despite the name

    def __init__(
        self,
        *,
        custom_output_text: str | None = None,
        custom_output_args: dict[str, Any] | None = None,
        call_tools: str = "all",  # "all" | "none"
        name: str = "test-model",
    ):
        self._text = custom_output_text
        self._output_args = custom_output_args
        self._call_tools = call_tools
        self._name = name

    @property
    def model_name(self) -> str:
        return self._name

    # ---------------------------------------------------------------- stubs
    @staticmethod
    def _stub_value(schema: dict[str, Any]) -> Any:
        t = schema.get("type")
        if "default" in schema:
            return schema["default"]
        if t == "string":
            return "a"
        if t == "integer":
            return 0
        if t == "number":
            return 0.0
        if t == "boolean":
            return False
        if t == "array":
            return []
        if t == "object" or "properties" in schema:
            return {
                k: TestModelClient._stub_value(v)
                for k, v in schema.get("properties", {}).items()
                if k in schema.get("required", [])
            }
        return None

    def _stub_args(self, schema: dict[str, Any]) -> dict[str, Any]:
        props = schema.get("properties", {})
        required = schema.get("required", list(props))
        return {k: self._stub_value(v) for k, v in props.items() if k in required}

    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        params = params or ModelRequestParameters()
        returned_ids = {
            part.tool_call_id
            for message in messages
            if isinstance(message, ModelRequest)
            for part in message.parts
            if isinstance(part, ToolReturnPart)
        }
        called: set[str] = set()
        for message in messages:
            if isinstance(message, ModelResponse):
                called |= {c.tool_name for c in message.tool_calls()}

        if self._call_tools == "all":
            pending = [t for t in params.tool_defs if t.name not in called]
            if pending:
                return ModelResponse(
                    parts=[
                        ToolCallOutput(
                            tool_call_id=f"tc_{uuid.uuid4().hex[:8]}",
                            tool_name=t.name,
                            args=self._stub_args(t.parameters_schema),
                        )
                        for t in pending
                    ],
                    usage=Usage(input_tokens=_estimate_tokens(messages), output_tokens=8),
                    model_name=self._name,
                )

        if params.output_tool is not None:
            args = self._output_args
            if args is None:
                args = self._stub_args(params.output_tool.parameters_schema)
            return ModelResponse(
                parts=[
                    ToolCallOutput(
                        tool_call_id=f"tc_{uuid.uuid4().hex[:8]}",
                        tool_name=params.output_tool.name,
                        args=args,
                    )
                ],
                usage=Usage(input_tokens=_estimate_tokens(messages), output_tokens=8),
                model_name=self._name,
            )

        text = self._text
        if text is None:
            summary = {"tools_called": sorted(called), "replies": len(returned_ids)}
            text = json.dumps(summary)
        return ModelResponse(
            parts=[TextOutput(text=text)],
            usage=Usage(input_tokens=_estimate_tokens(messages), output_tokens=8),
            model_name=self._name,
        )
