"""Consumer nodes: observers with no seams, no fault rail, no replies.

Reference: calfkit/nodes/consumer.py:42-164 — a consumer projects deliveries
into a read-only context and floors every error at a single ERROR log.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Callable

from pydantic import ValidationError

from calfkit_tpu import protocol
from calfkit_tpu.mesh.transport import Record
from calfkit_tpu.models.session_context import Envelope
from calfkit_tpu.nodes.base import BaseNodeDef

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ConsumerContext:
    """Read-only projection of one observed delivery."""

    topic: str
    headers: dict[str, str]
    envelope: Envelope | None
    raw: bytes
    correlation_id: str | None
    task_id: str | None
    emitter: str | None


class ConsumerNode(BaseNodeDef):
    kind = "consumer"

    def __init__(
        self,
        fn: Callable[[ConsumerContext], Any],
        *,
        name: str,
        topics: list[str],
    ):
        super().__init__(name)
        self.fn = fn
        self._topics = [protocol.require_topic_safe(t) for t in topics]

    def input_topics(self) -> list[str]:
        return list(self._topics)

    def return_topic(self) -> str:
        return protocol.require_topic_safe(f"consumer.{self.name}.private.return")

    # overriding the whole pipeline: consumers have no kernel stages
    async def _handle_delivery(self, record: Record) -> None:
        if record.headers.get(protocol.HDR_KIND) == "cancel":
            # control record, not observable traffic: fan out to the
            # in-process cancellation targets exactly like the kernel
            # path.  Without this short-circuit the dispatcher's EXPRESS
            # cancel delivery would run the user's consumer fn INLINE on
            # the intake pull task — head-of-line blocking the very path
            # built to avoid it, with a spurious envelope=None delivery.
            self._handle_cancel(record.headers)
            return
        envelope: Envelope | None = None
        if protocol.is_envelope(record.headers):
            try:
                envelope = Envelope.from_wire(record.value)
            except (ValidationError, ValueError):
                envelope = None  # consumers also observe undecodable traffic
        ctx = ConsumerContext(
            topic=record.topic,
            headers=dict(record.headers),
            envelope=envelope,
            raw=record.value,
            correlation_id=record.headers.get(protocol.HDR_CORRELATION),
            task_id=record.headers.get(protocol.HDR_TASK),
            emitter=record.headers.get(protocol.HDR_EMITTER),
        )
        try:
            result = self.fn(ctx)
            if hasattr(result, "__await__"):
                await result
        except Exception:  # noqa: BLE001 - the single ERROR floor
            logger.exception(
                "[%s] consumer body failed on %s", self.node_id, record.topic
            )


def consumer(
    *, topics: list[str], name: str | None = None
) -> Callable[[Callable[[ConsumerContext], Any]], ConsumerNode]:
    """Decorator: ``@consumer(topics=[...])`` → a deployable observer node."""

    def build(fn: Callable[[ConsumerContext], Any]) -> ConsumerNode:
        return ConsumerNode(fn, name=name or fn.__name__, topics=topics)

    return build
