"""The node kernel: the staged per-delivery pipeline every node kind shares.

Pipeline (reference: calfkit/nodes/base.py:244-2094, restructured, not
ported):

    stage 0   decode floor + classify (call / return / fault / reentry)
    stage 1   aggregation — returns & faults resolve against the pending
              call: on_callee_error seams, durable fan-out fold/close
    stage 2   before_node seam chain
    stage 3   routed body (chain-of-responsibility over @handler patterns)
    stage 4   after_node seam chain
    stage 5   publish chokepoint (Call push / ReturnCall unwind / TailCall
              retarget) + fan-out OPEN
    exit      step-ledger flush (once) + broadcast mirror

Fault rail invariants preserved from the reference:

- **No silent drops**: every failure lands a typed FaultMessage to the
  caller, or a floor log when there is no caller; a reply-owing delivery
  declined by every handler auto-faults (``mesh.declined``).
- **Mint rule**: user code raises :class:`NodeFaultError` to emit a typed
  fault; any other exception is harvested into a ``mesh.node_error`` report
  after the ``on_node_error`` chain gets a recovery chance.
- **Escalation ladder**: an oversized fault degrades full → no-tracebacks →
  minimal+state-elided rather than dropping (base.py:838-905 analog).
- **Single-writer**: every publish is keyed by ``partition_key(task_id)``.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, ClassVar, Sequence

from pydantic import ValidationError

from calfkit_tpu import cancellation, leases, protocol, qos
from calfkit_tpu.exceptions import NodeFaultError, error_type_for
from calfkit_tpu.keying import partition_key
from calfkit_tpu.mesh.transport import MeshTransport, Record
from calfkit_tpu.models.actions import Call, Next, NodeResult, ReturnCall, TailCall
from calfkit_tpu.models.error_report import ErrorReport, FaultTypes
from calfkit_tpu.models.fanout import (
    EnvelopeSnapshot,
    FanoutOpen,
    FanoutOutcome,
    SlotRef,
)
from calfkit_tpu.models.marker import CallMarker, ToolCallMarker
from calfkit_tpu.models.messages import RetryPart, ToolReturnPart
from calfkit_tpu.models.payload import ContentPart, is_retry, render_parts_as_text
from calfkit_tpu.models.reply import FaultMessage, ReturnMessage
from calfkit_tpu.models.session_context import CallFrame, Envelope, new_id
from calfkit_tpu.models.state import State
from calfkit_tpu.nodes.fanout_store import (
    FANOUT_STORE_KEY,
    FanoutBatchStore,
    classify_sibling,
    record_outcome,
)
from calfkit_tpu.nodes.registry import RegistryMixin, handler  # noqa: F401 (re-export)
from calfkit_tpu.nodes.seams import (
    MintedFault,
    run_chain,
    run_chain_guarded,
    validate_seam_arity,
)
from calfkit_tpu.nodes.steps import HopStepLedger, Observed

logger = logging.getLogger(__name__)

# mirror of controlplane.plane.CALLER_LIVENESS_FEED_KEY (the
# capability_view/agents_view mirrored-constant pattern — no import
# cycle): truthy once the worker's caller-liveness feed is consuming;
# the kernel only ENFORCES leases where beats can actually arrive
CALLER_LIVENESS_FEED_KEY = "caller_liveness_feed"

# node-resource key for the per-tenant admission token bucket
# (ISSUE 20): a qos.TenantRateLimiter; absent or disabled = no limiting
QOS_LIMITER_KEY = "qos_limiter"

_REENTRY_KEY = "fanout_reentry"

# aggregation outcomes
_HANDLED = "handled"
_RESUME = "resume"


def _as_recovery_parts(recovery: Any) -> list:
    """Coerce an on_callee_error/on_tool_error recovery into content parts.

    The documented sugar accepts a plain string ('answer from memory'), a
    part, or a list of parts (reference: nodes/_tool_error.py — seam
    returns become the slot's substitute value the model sees)."""
    from calfkit_tpu.models.payload import DataPart, TextPart

    def one(p: Any) -> Any:
        if isinstance(p, str):
            return TextPart(text=p)
        if isinstance(p, dict):
            return DataPart(data=p)
        return p

    if isinstance(recovery, list):
        return [one(p) for p in recovery]
    return [one(recovery)]


def _as_action(value: Any) -> NodeResult:
    """Coerce a seam-returned value into a publishable action.

    Bodies return NodeResults; seams may return plain values (a canned
    string, a dict) — wrap those in a ReturnCall so a short-circuit or an
    after_node replacement can never silently fall through the publish
    chokepoint."""
    if isinstance(value, (Call, TailCall, ReturnCall, Next, list)):
        return value
    from calfkit_tpu.models.payload import DataPart, TextPart

    if isinstance(value, str):
        return ReturnCall(parts=[TextPart(text=value)])
    if isinstance(value, dict):
        return ReturnCall(parts=[DataPart(data=value)])
    # anything else is almost certainly an accidental return from a seam
    # written for observe-only semantics (e.g. a trailing setdefault) —
    # fail loudly instead of publishing its repr as the agent's answer
    raise TypeError(
        "a seam returned an unpublishable value "
        f"({type(value).__name__}); return a NodeResult, str, dict, or None"
    )


@dataclass
class NodeRunContext:
    """What the body and seams see for one delivery."""

    node: "BaseNodeDef"
    envelope: Envelope
    route: str
    delivery_kind: str
    correlation_id: str | None
    task_id: str
    ledger: HopStepLedger
    headers: dict[str, str] = field(default_factory=dict)
    # the resolved callee outcome for return/fault resumptions
    folded: FanoutOutcome | None = None
    # the marker of the reply currently being resolved (set during stage-1
    # aggregation so on_callee_error sugar can see which call faulted)
    folding_marker: Any = None
    # the broadcast mirror fires at most once per hop
    mirrored: bool = False
    # captured at stage 0: the run's step-stream destination survives the
    # frame unwind that a ReturnCall performs before flush time
    root_topic: str | None = None
    # this hop's trace context (the HOP SPAN's id): forwarded in every
    # outgoing record's headers so downstream hops parent to this hop
    trace: Any = None  # TraceContext | None
    # set by _publish_fault so the hop span can record error status
    fault_error_type: str | None = None

    @property
    def state(self) -> State:
        return self.envelope.context.state

    @property
    def deps(self) -> dict[str, Any]:
        return self.envelope.context.deps

    @property
    def frame(self) -> CallFrame | None:
        return self.envelope.workflow.current()

    @property
    def payload(self) -> list[ContentPart]:
        frame = self.frame
        return frame.payload if frame else []

    def resource(self, key: str) -> Any:
        return self.node.resources.get(key)


class BaseNodeDef(RegistryMixin):
    kind: ClassVar[str] = "node"

    def __init__(
        self,
        name: str,
        *,
        before_node: Sequence[Any] = (),
        after_node: Sequence[Any] = (),
        on_node_error: Sequence[Any] = (),
        on_callee_error: Sequence[Any] = (),
        instance_id: "str | None" = None,
    ):
        protocol.require_topic_safe(name, what="node name")
        self.name = name
        # per-boot random by default.  Operators deploying replica fleets
        # on clusters where topics must PRE-exist (provisioning disabled,
        # ACL-restricted admin) pin a stable id per replica ("r0", "r1",
        # …) so the replica-addressed topic is knowable ahead of boot and
        # survives restarts; the control-plane key stays <name>@<id>.
        if instance_id is not None:
            protocol.require_topic_safe(instance_id, what="instance_id")
        self.instance_id = instance_id or uuid.uuid4().hex[:12]
        for seam in before_node:
            validate_seam_arity(seam, 1, name="before_node")
        for seam in after_node:
            validate_seam_arity(seam, 2, name="after_node")
        for seam in on_node_error:
            validate_seam_arity(seam, 2, name="on_node_error")
        for seam in on_callee_error:
            validate_seam_arity(seam, 2, name="on_callee_error")
        self.before_node = list(before_node)
        self.after_node = list(after_node)
        self.on_node_error = list(on_node_error)
        self.on_callee_error = list(on_callee_error)
        self.resources: dict[str, Any] = {}
        self._transport: MeshTransport | None = None
        # in-flight background publishes (span exports, cancel forwards)
        self._span_tasks: "set[Any]" = set()
        # cancel forwarding (ISSUE 5): topics this kernel published CALLS
        # to, per correlation id, so _handle_cancel can re-publish the
        # cancel along the run's path — an engine in ANOTHER process
        # (behind a downstream topic) is unreachable through the
        # in-process registry alone.  Bounded LRU; entries are advisory
        # (a stale forward to a finished child fans out to nothing), so
        # eviction never costs correctness.
        self._downstream_calls: "OrderedDict[str, set[str]]" = OrderedDict()

    # ------------------------------------------------------------ identity
    @property
    def node_id(self) -> str:
        return f"{self.kind}.{self.name}"

    @property
    def emitter(self) -> str:
        return protocol.emitter_header(self.kind, self.name)

    def input_topics(self) -> list[str]:
        raise NotImplementedError

    def return_topic(self) -> str:
        raise NotImplementedError

    def publish_topic(self) -> str | None:
        return None

    def all_topics(self) -> list[str]:
        topics = list(self.input_topics()) + [self.return_topic()]
        pub = self.publish_topic()
        if pub:
            topics.append(pub)
        return topics

    # ------------------------------------------------------------- binding
    def bind(self, transport: MeshTransport) -> None:
        self._transport = transport

    @property
    def transport(self) -> MeshTransport:
        if self._transport is None:
            raise RuntimeError(f"node {self.node_id} is not bound to a transport")
        return self._transport

    @property
    def fanout_store(self) -> FanoutBatchStore | None:
        return self.resources.get(FANOUT_STORE_KEY)

    # =====================================================================
    # entrypoint
    # =====================================================================
    async def handler(self, record: Record) -> None:
        """The transport-facing entrypoint (one delivery, one hop)."""
        try:
            await self._handle_delivery(record)
        except Exception:  # noqa: BLE001 - absolute floor: never kill the lane
            logger.exception(
                "[%s] delivery pipeline escaped its fault rail on %s",
                self.node_id,
                record.topic,
            )

    async def _handle_delivery(self, record: Record) -> None:
        headers = record.headers
        if headers.get(protocol.HDR_KIND) == "cancel":
            # a cancel record is pure headers (no envelope body): fan it
            # out to every in-process cancellation target (engines) so a
            # dead caller's in-flight work is abandoned, then stop — there
            # is nothing to execute and no reply owed
            self._handle_cancel(headers)
            return
        if not protocol.is_envelope(headers):
            return  # step/other wire kinds are not for the kernel
        try:
            envelope = Envelope.from_wire(record.value)
        except (ValidationError, ValueError):
            # decode floor: no frame to fault against — loud, then drop
            logger.error(
                "[%s] undecodable envelope on %s (%d bytes): dropped",
                self.node_id,
                record.topic,
                len(record.value),
            )
            return

        correlation_id = headers.get(protocol.HDR_CORRELATION)
        task_id = headers.get(protocol.HDR_TASK) or new_id()  # ingress mint
        kind = headers.get(protocol.HDR_KIND)
        if kind not in protocol.MESSAGE_KINDS:
            kind = "return" if envelope.reply is not None else "call"

        frame = envelope.workflow.current()
        route = frame.route if frame else headers.get(protocol.HDR_ROUTE, "run")
        ctx = NodeRunContext(
            node=self,
            envelope=envelope,
            route=route,
            delivery_kind=kind,
            correlation_id=correlation_id,
            task_id=task_id,
            ledger=HopStepLedger(self.emitter),
            headers=dict(headers),
            root_topic=envelope.workflow.root_callback_topic(),
        )
        log_id = (correlation_id or task_id)[:8]

        # ---- deadline: the delivery's absolute budget rides a contextvar
        # (same channel shape as the trace) so in-process children — the
        # inference engine above all — enforce the caller's deadline
        # without per-layer budget arithmetic.  Reset in the finally.
        deadline = protocol.parse_deadline(headers.get(protocol.HDR_DEADLINE))
        deadline_token = (
            cancellation.current_deadline.set(deadline)
            if deadline is not None
            else None
        )

        # ---- caller liveness lease (ISSUE 10): recorded at admission —
        # a CLIENT-emitted call is proof the caller was alive at publish,
        # an implicit beat that grants a full TTL of grace even before
        # the liveness feed catches up (forwarded calls prove only the
        # forwarding NODE's liveness, so they don't beat).  The lease
        # rides a contextvar like the deadline, so the in-process engine
        # registers this delivery's runs for the orphan reaper.  Only
        # ENFORCED where the worker's caller-liveness feed is consuming
        # (the control plane sets the resource flag): a worker that
        # cannot receive beats must not orphan a LIVE caller's run one
        # TTL after admission — fail-safe, the pre-lease behavior.
        # ---- run identity (ISSUE 19): the x-mesh-run header rides a
        # contextvar like the deadline/lease, so the in-process engine's
        # capacity ledger attributes HBM pages to the logical RUN this
        # delivery serves (not just the per-attempt correlation id)
        from calfkit_tpu.observability import capacity as _capacity

        parsed_run = protocol.parse_run(headers.get(protocol.HDR_RUN))
        run_token = (
            _capacity.current_run.set(parsed_run[0])
            if parsed_run is not None
            else None
        )

        lease = protocol.parse_lease(headers.get(protocol.HDR_LEASE))
        lease_token = None
        if lease is not None and self.resources.get(CALLER_LIVENESS_FEED_KEY):
            if kind == "call":
                emitter_kind, _ = protocol.parse_emitter(
                    headers.get(protocol.HDR_EMITTER)
                )
                if emitter_kind == "client":
                    leases.note_admission(*lease)
            lease_token = leases.current_lease.set(lease)

        # ---- priority class (ISSUE 20): rides a contextvar like the
        # deadline/lease, so the in-process engine's class-aware shed and
        # reap ordering see the caller's class with no per-layer
        # plumbing.  A corrupt header parses to None and the contextvar
        # stays at its default — readers resolve that to the DEFAULT
        # class; delivery never faults (the PR 5 law).
        priority = protocol.parse_priority(headers.get(protocol.HDR_PRIORITY))
        priority_token = (
            qos.current_priority.set(priority)
            if priority is not None
            else None
        )

        # ---- tracing: one HOP SPAN per traced delivery.  A missing trace
        # header is legal (pre-trace emitters, external producers) — the
        # hop simply runs untraced.  Everything here is fail-open.
        from calfkit_tpu.observability import trace as _trace

        hop_span = None
        sink: list[Any] = []
        sink_token = ctx_token = None
        remote = _trace.TraceContext.from_headers(headers)
        if remote is not None:
            hop_span = _trace.TRACER.start_span(
                f"{self.kind}.hop",
                parent=remote,
                kind=self.kind,
                emitter=self.emitter,
                attrs={
                    "node": self.node_id,
                    "topic": record.topic,
                    "route": route,
                    "delivery": kind,
                },
            )
            ctx.trace = hop_span.context
            ctx_token = _trace.current_context.set(hop_span.context)
            # in-process children (the inference engine's spans) land in
            # this hop's sink so they ride the same topic publish below
            sink, sink_token = _trace.collect_spans()

        try:
            if kind == "call":
                # expired-on-arrival + drain gate: record the typed fault
                # FAST instead of executing for a caller that is gone (or
                # a worker that is leaving) — raising here lands in the
                # NodeFaultError arm below, so the fault rail, step flush
                # and span bookkeeping all run normally
                self._check_admission(ctx, deadline)
            await self._execute(ctx)
        except MintedFault as minted:
            await self._publish_fault(ctx, minted.error.report)
        except NodeFaultError as fault:
            await self._publish_fault(ctx, fault.report)
        except Exception as exc:  # noqa: BLE001 - the fault rail
            # typed exceptions (EngineOverloadedError, DeadlineExceeded…)
            # keep their wire code from the authoritative table in
            # calfkit_tpu.exceptions; everything else harvests as this
            # node kind's generic fault
            report = ErrorReport.build_safe(
                error_type_for(exc) or self._own_fault_type(),
                exc=exc,
                node=self.node_id,
                route=ctx.route,
            )
            recovered = False
            try:
                recovery = await run_chain_guarded(self.on_node_error, ctx, report)
            except MintedFault as minted:
                await self._publish_fault(ctx, minted.error.report)
                recovery, recovered = None, True
            except Exception:  # noqa: BLE001 - seam crash joins the fault
                logger.exception("[%s] on_node_error seam crashed", log_id)
                recovery = None
            if recovery is not None and not recovered:
                try:
                    await self._publish_action(ctx, recovery)
                    recovered = True
                except Exception:  # noqa: BLE001
                    logger.exception("[%s] recovery action publish failed", log_id)
            if not recovered:
                # a failed recovery must not swallow the original fault
                await self._publish_fault(ctx, report)
        except BaseException as exc:
            # cancellation (lane force-cancel, loop teardown) and other
            # non-Exception escapes: record the truth on the hop span NOW
            # (end() is idempotent — the finally's end() becomes a no-op),
            # then propagate.  Captured locally, not via sys.exc_info() in
            # the finally, which also reports outer HANDLED exceptions.
            if hop_span is not None:
                import asyncio as _asyncio

                hop_span.end(
                    status="cancelled"
                    if isinstance(exc, _asyncio.CancelledError)
                    else "error"
                )
            raise
        finally:
            if deadline_token is not None:
                cancellation.current_deadline.reset(deadline_token)
            if run_token is not None:
                _capacity.current_run.reset(run_token)
            if lease_token is not None:
                leases.current_lease.reset(lease_token)
            if priority_token is not None:
                qos.current_priority.reset(priority_token)
            await self._flush_steps(ctx)
            if hop_span is not None:
                if ctx.fault_error_type is not None:
                    hop_span.end(
                        status="error", error_type=ctx.fault_error_type
                    )
                else:
                    hop_span.end()
                if ctx_token is not None:
                    _trace.current_context.reset(ctx_token)
                if sink_token is not None:
                    _trace.release_spans(sink_token)
                self._publish_spans_soon(sink)

    def _own_fault_type(self) -> str:
        return FaultTypes.NODE_ERROR

    # --------------------------------------------- overload protection
    # LRU cap on the per-kernel corr -> downstream-call-topics map: sized
    # for every plausible concurrent-run count; eviction only degrades a
    # cancel back to single-hop for the evicted (oldest) run
    _DOWNSTREAM_CALLS_CAP = 2048

    def _note_downstream_call(self, correlation_id: str, topic: str) -> None:
        """Remember that this run published a call to ``topic`` so a later
        cancel can follow it (``_handle_cancel``)."""
        calls = self._downstream_calls
        entry = calls.get(correlation_id)
        if entry is None:
            entry = calls[correlation_id] = set()
        entry.add(topic)
        calls.move_to_end(correlation_id)
        while len(calls) > self._DOWNSTREAM_CALLS_CAP:
            calls.popitem(last=False)

    # per-topic bound on one forwarded publish, mirroring the client's
    # _CANCEL_PUBLISH_TIMEOUT rationale: an unreachable broker is the
    # LIKELY state when cancels storm in, and must not wedge the task
    _CANCEL_FORWARD_TIMEOUT = 5.0

    def _handle_cancel(self, headers: dict[str, str]) -> None:
        """Route a ``cancel``-kind record to in-process abandonment AND
        forward it along the run's path: every registered cancellation
        target (the inference engines) drops its requests for the record's
        correlation id, and every topic this kernel published one of the
        run's calls to gets the cancel re-published — an engine in another
        worker process is only reachable through its topic.  The pop makes
        forwarding idempotent (a duplicate cancel delivery forwards
        nothing); the forwards run as a retained, time-bounded background
        task because this runs INLINE on the dispatcher's express intake
        path — awaiting an unreachable broker here would head-of-line
        block all record intake, the exact failure the express path
        exists to avoid.  Fail-open — a cancel is advisory; a target or
        hop that cannot honor it changes nothing."""
        correlation_id = headers.get(protocol.HDR_CORRELATION)
        if not correlation_id:
            return
        topics = self._downstream_calls.pop(correlation_id, None)
        if topics:
            task = asyncio.get_running_loop().create_task(
                self._forward_cancel(
                    sorted(topics), correlation_id,
                    headers.get(protocol.HDR_TASK),
                )
            )
            self._span_tasks.add(task)
            task.add_done_callback(self._span_tasks.discard)
        matched = cancellation.propagate_cancel(correlation_id)
        if matched:
            logger.info(
                "[%s] cancel for %s abandoned %d in-flight request(s)",
                self.node_id, correlation_id[:8], matched,
            )

    async def _forward_cancel(
        self,
        topics: "list[str]",
        correlation_id: str,
        task_id: "str | None",
    ) -> None:
        for topic in topics:
            fwd = {
                protocol.HDR_EMITTER: self.emitter,
                protocol.HDR_KIND: "cancel",
                protocol.HDR_CORRELATION: correlation_id,
            }
            if task_id:
                fwd[protocol.HDR_TASK] = task_id
            try:
                await asyncio.wait_for(
                    self.transport.publish(
                        topic,
                        b"",
                        key=partition_key(task_id) if task_id else None,
                        headers=fwd,
                    ),
                    self._CANCEL_FORWARD_TIMEOUT,
                )
            except Exception:  # noqa: BLE001 - advisory, never faults the hop
                logger.warning(
                    "[%s] cancel forward to %s failed for %s",
                    self.node_id, topic, correlation_id[:8],
                    exc_info=True,
                )

    # emitter kinds whose calls CONTINUE a run already admitted to the
    # mesh (an agent's tool call, a tail call): a draining worker must let
    # these finish — "in-flight work runs to completion" — and only refuse
    # runs ENTERING the mesh (client-emitted, or unattributed external)
    _CONTINUATION_EMITTERS = ("agent", "tool", "toolbox", "consumer", "worker")

    def _check_admission(
        self, ctx: NodeRunContext, deadline: "float | None"
    ) -> None:
        """The call-delivery gate (ISSUE 5): an already-expired call
        records a typed ``mesh.deadline_exceeded`` fault instead of
        executing, and a draining worker refuses NEW runs with a typed,
        retriable ``mesh.overloaded`` fault while in-flight deliveries —
        returns, faults, and node-emitted continuation calls belonging to
        runs already executing — keep flowing to completion.  A call whose
        run was already cancelled (tombstone hit: the cancel rode EXPRESS
        past the lane this call was still queued in) faults fast instead
        of executing for a caller that left."""
        if cancellation.was_cancelled(ctx.correlation_id):
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.CANCELLED,
                    f"run was cancelled before this call reached "
                    f"{self.node_id}",
                    node=self.node_id,
                    route=ctx.route,
                )
            )
        if deadline is not None:
            overdue = cancellation.wall_clock() - deadline
            if overdue >= 0:
                raise NodeFaultError(
                    ErrorReport.build_safe(
                        FaultTypes.DEADLINE_EXCEEDED,
                        f"call expired {overdue:.3f}s before reaching "
                        f"{self.node_id}",
                        node=self.node_id,
                        route=ctx.route,
                    )
                )
        worker = self.resources.get("worker")
        if worker is not None and getattr(worker, "draining", False):
            emitter = ctx.headers.get(protocol.HDR_EMITTER, "")
            if emitter.split("/", 1)[0] in self._CONTINUATION_EMITTERS:
                return  # a sub-call of an in-flight run: let it finish
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.OVERLOADED,
                    f"{self.node_id} is draining for shutdown; "
                    "retry against another instance",
                    node=self.node_id,
                    route=ctx.route,
                )
            )
        # per-tenant admission budget (ISSUE 20): only runs ENTERING the
        # mesh spend a token — continuation calls are the tail of an
        # already-admitted run, and rate-limiting them mid-run would
        # strand slots and pages the run already holds (same exemption
        # as the drain gate above).  Tenant identity is the caller's
        # lease id where present (one lease per caller process — the
        # natural tenant grain), else the caller's emitter id.
        limiter = self.resources.get(QOS_LIMITER_KEY)
        if limiter is not None and getattr(limiter, "enabled", False):
            emitter = ctx.headers.get(protocol.HDR_EMITTER, "")
            if emitter.split("/", 1)[0] not in self._CONTINUATION_EMITTERS:
                lease = protocol.parse_lease(
                    ctx.headers.get(protocol.HDR_LEASE)
                )
                if lease is not None:
                    tenant = lease[0]
                else:
                    _, emitter_id = protocol.parse_emitter(emitter)
                    tenant = emitter_id or emitter
                retry_after = limiter.admit(tenant)
                if retry_after is not None:
                    raise NodeFaultError(
                        ErrorReport.build_safe(
                            FaultTypes.RATE_LIMITED,
                            f"tenant {tenant!r} exceeded its admission "
                            f"budget at {self.node_id}; retry after "
                            f"{retry_after:.3f}s",
                            node=self.node_id,
                            route=ctx.route,
                            data={
                                "tenant_id": tenant,
                                "retry_after_s": f"{retry_after:.3f}",
                            },
                        )
                    )

    # =====================================================================
    # stages
    # =====================================================================
    async def _execute(self, ctx: NodeRunContext) -> None:
        if ctx.delivery_kind in ("return", "fault"):
            outcome = await self._aggregate(ctx)
            if outcome != _RESUME:
                return
        short_circuit = await run_chain(self.before_node, ctx)
        if short_circuit is not None:
            # a before_node seam answered the delivery: the body never runs
            # (caching / canned responses / maintenance mode); after_node
            # still sees the result like any other
            action = _as_action(short_circuit)
        else:
            action = await self._dispatch_routed(ctx)
        if isinstance(action, Observed):
            ctx.ledger.absorb(action.facts)
            action = action.action
        transformed = await run_chain(self.after_node, ctx, action)
        if transformed is not None:
            action = _as_action(transformed)
        await self._publish_action(ctx, action)

    async def _dispatch_routed(self, ctx: NodeRunContext) -> NodeResult | Observed:
        chain = self.handlers_for(ctx.route)
        if not chain:
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.DECLINED,
                    f"no handler for route {ctx.route!r} on {self.node_id}",
                    node=self.node_id,
                    route=ctx.route,
                )
            )
        for body in chain:
            result = body(ctx)
            if hasattr(result, "__await__"):
                result = await result
            if not isinstance(result, Next):
                return result
        # every handler declined
        return Next()

    # ------------------------------------------------------------ aggregate
    async def _aggregate(self, ctx: NodeRunContext) -> str:
        envelope = ctx.envelope
        reply = envelope.reply
        envelope.reply = None
        if reply is None:
            logger.warning(
                "[%s] %s delivery with empty reply slot: stray, dropped",
                self.node_id,
                ctx.delivery_kind,
            )
            return _HANDLED

        # fan-out close reentry?
        if (
            isinstance(reply.marker, CallMarker)
            and _REENTRY_KEY in reply.marker.data
        ):
            return await self._close_fanout_batch(
                ctx, reply.marker.data[_REENTRY_KEY]
            )

        frame = envelope.workflow.current()
        if frame is not None and frame.fanout_id:
            return await self._fold_sibling_reply(ctx, frame.fanout_id, reply)

        # single pending call: resolve (seams on faults), then resume body
        outcome = await self._resolve_callee(
            ctx, reply, slot_id=reply.frame_id or ""
        )
        if outcome.fault is not None:
            # unrecovered callee fault escalates one hop up the stack
            escalated = ErrorReport.build_safe(
                FaultTypes.CALLEE_FAULT,
                f"callee fault reached {self.node_id}",
                node=self.node_id,
                route=ctx.route,
                cause=outcome.fault,
                frame_id=frame.frame_id if frame else None,
            )
            await self._publish_fault(ctx, escalated)
            return _HANDLED
        self.materialize_outcome(ctx, outcome)
        ctx.folded = outcome
        return _RESUME

    async def _resolve_callee(
        self, ctx: NodeRunContext, reply: Any, *, slot_id: str
    ) -> FanoutOutcome:
        """Stage-1 resolution: returns pass through; faults get the
        on_callee_error chain (parts = recovery, None = stays a fault)."""
        ctx.folding_marker = getattr(reply, "marker", None)
        if isinstance(reply, ReturnMessage):
            outcome = FanoutOutcome(
                slot_id=slot_id, parts=list(reply.parts), marker=reply.marker
            )
            self._note_fold(ctx, outcome)
            return outcome
        assert isinstance(reply, FaultMessage)
        report = reply.report
        recovery = await run_chain_guarded(self.on_callee_error, ctx, report)
        if recovery is not None:
            outcome = FanoutOutcome(
                slot_id=slot_id,
                parts=_as_recovery_parts(recovery),
                marker=reply.marker,
            )
            self._note_fold(ctx, outcome, recovered_fault=True)
            return outcome
        outcome = FanoutOutcome(slot_id=slot_id, fault=report, marker=reply.marker)
        self._note_fold(ctx, outcome)
        return outcome

    def _note_fold(
        self, ctx: NodeRunContext, outcome: FanoutOutcome, *,
        recovered_fault: bool = False,
    ) -> None:
        """Pair law: the result step for a marked call mints at the fold."""
        marker = outcome.marker
        if isinstance(marker, ToolCallMarker):
            if outcome.fault is not None:
                ctx.ledger.fold_failed(
                    marker.tool_call_id, marker.tool_name, outcome.fault
                )
            else:
                ctx.ledger.folded(
                    marker.tool_call_id,
                    marker.tool_name,
                    render_parts_as_text(outcome.parts or []),
                    ok=not recovered_fault,
                )

    def materialize_outcome(self, ctx: NodeRunContext, outcome: FanoutOutcome) -> None:
        """Default slot materialization: marked tool results land in
        ``state.tool_results`` (retry-marked parts become RetryPart)."""
        marker = outcome.marker
        if not isinstance(marker, ToolCallMarker):
            return
        parts = outcome.parts or []
        if any(is_retry(p) for p in parts):
            ctx.state.tool_results[marker.tool_call_id] = RetryPart(
                content=render_parts_as_text(parts),
                tool_call_id=marker.tool_call_id,
                tool_name=marker.tool_name,
            )
        else:
            ctx.state.tool_results[marker.tool_call_id] = ToolReturnPart(
                tool_call_id=marker.tool_call_id,
                tool_name=marker.tool_name,
                content=render_parts_as_text(parts),
            )

    # -------------------------------------------------------------- fan-out
    def _require_store(self) -> FanoutBatchStore:
        store = self.fanout_store
        if store is None:
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.LIFECYCLE_ERROR,
                    f"{self.node_id}: parallel calls need a fanout store "
                    f"resource ({FANOUT_STORE_KEY!r})",
                    node=self.node_id,
                )
            )
        return store

    async def _handle_fanout_open(self, ctx: NodeRunContext, calls: list[Call]) -> None:
        """OPEN: snapshot + pre-minted slots + marked own frame + dispatch."""
        store = self._require_store()
        envelope = ctx.envelope
        fanout_id = new_id()
        slots = [
            SlotRef(
                slot_id=new_id(),
                tag=call.tag,
                tool_name=(
                    call.marker.tool_name
                    if isinstance(call.marker, ToolCallMarker)
                    else None
                ),
            )
            for call in calls
        ]
        envelope.workflow.mark_fanout(fanout_id)
        snapshot = EnvelopeSnapshot(
            context=envelope.context.model_copy(deep=True),
            workflow=envelope.workflow.model_copy(deep=True),
        )
        await store.open(
            fanout_id, FanoutOpen(fanout_id=fanout_id, slots=slots), snapshot
        )
        for call, slot in zip(calls, slots):
            sibling = Envelope(
                context=envelope.context.model_copy(deep=True),
                workflow=envelope.workflow.model_copy(deep=True),
            )
            if call.isolate_state:
                sibling.context.state = call.state_override or State()
            await self._dispatch_call(ctx, sibling, call, frame_id=slot.slot_id)

    async def _dispatch_call(
        self,
        ctx: NodeRunContext,
        envelope: Envelope,
        call: Call,
        *,
        frame_id: str | None = None,
    ) -> None:
        """The one push-frame/publish/note-dispatch sequence for outgoing
        calls (single and fan-out siblings)."""
        frame = CallFrame(
            target_topic=call.target_topic,
            callback_topic=self.return_topic(),
            route=call.route,
            payload=call.parts,
            tag=call.tag,
            marker=call.marker,
            caller_kind=self.kind,
            caller_name=self.name,
        )
        if frame_id is not None:
            frame.frame_id = frame_id
        envelope.workflow.invoke_frame(frame)
        await self._publish_envelope(
            ctx, call.target_topic, envelope, kind="call", route=call.route
        )
        if isinstance(call.marker, ToolCallMarker):
            args: dict[str, Any] = {}
            if call.parts:
                data = getattr(call.parts[0], "data", None)
                if isinstance(data, dict):
                    args = data.get("args", data)
                    if not isinstance(args, dict):
                        args = {}
            ctx.ledger.note_dispatch(
                call.marker.tool_call_id, call.marker.tool_name, args
            )

    async def _fold_sibling_reply(
        self, ctx: NodeRunContext, fanout_id: str, reply: Any
    ) -> str:
        store = self._require_store()
        slot_id = reply.frame_id or ""
        state = await store.load(fanout_id)
        classification = classify_sibling(state, slot_id)
        if classification != "expected":
            logger.warning(
                "[%s] sibling reply %s classified %s for batch %s: dropped",
                self.node_id,
                slot_id[:8],
                classification,
                fanout_id[:8],
            )
            return _HANDLED
        assert state is not None
        outcome = await self._resolve_callee(ctx, reply, slot_id=slot_id)
        state = record_outcome(state, outcome)
        if state.is_complete() and not state.closing:
            state = state.model_copy(update={"closing": True})
            await store.save(state)
            await self._publish_reentry(ctx, fanout_id)
        else:
            await store.save(state)
        return _HANDLED

    async def _publish_reentry(self, ctx: NodeRunContext, fanout_id: str) -> None:
        """Self-published close trigger, through the same key-ordered lane."""
        envelope = Envelope(
            reply=ReturnMessage(marker=CallMarker(data={_REENTRY_KEY: fanout_id}))
        )
        await self._publish_envelope(
            ctx,
            self.return_topic(),
            envelope,
            kind="return",
            route="fanout.close",
            mirror=False,  # internal control record: never on the events tap
        )

    async def _close_fanout_batch(self, ctx: NodeRunContext, fanout_id: str) -> str:
        store = self._require_store()
        state = await store.load(fanout_id)
        if state is None:
            logger.warning(
                "[%s] duplicate close for batch %s: dropped",
                self.node_id,
                fanout_id[:8],
            )
            return _HANDLED
        snapshot = await store.load_snapshot(fanout_id)
        await store.close(fanout_id)  # tombstone-first, exactly-once close
        if snapshot is None:
            logger.error(
                "[%s] batch %s registered without snapshot: write-order "
                "invariant broken; run stranded",
                self.node_id,
                fanout_id[:8],
            )
            return _HANDLED
        # restore the caller's continuation (incl. the step-stream root,
        # which the reentry envelope's empty workflow couldn't provide)
        ctx.envelope.context = snapshot.context
        ctx.envelope.workflow = snapshot.workflow
        ctx.envelope.workflow.mark_fanout(None)
        ctx.root_topic = ctx.envelope.workflow.root_callback_topic()
        ctx.route = (
            ctx.envelope.workflow.current().route
            if ctx.envelope.workflow.current()
            else ctx.route
        )

        faults = [o for o in state.outcomes.values() if o.fault is not None]
        if faults:
            group = ErrorReport.build_safe(
                FaultTypes.FANOUT_ABORTED,
                f"{len(faults)} of {len(state.open.slots)} parallel calls "
                f"faulted on {self.node_id}",
                node=self.node_id,
                route=ctx.route,
                cause=faults[0].fault,
                data={"faulted_slots": str(len(faults))},
            )
            await self._publish_fault(ctx, group)
            return _HANDLED
        for slot in state.open.slots:
            outcome = state.outcomes[slot.slot_id]
            self.materialize_outcome(ctx, outcome)
        return _RESUME

    # =====================================================================
    # publish chokepoint
    # =====================================================================
    async def _publish_action(self, ctx: NodeRunContext, action: NodeResult) -> None:
        envelope = ctx.envelope
        if isinstance(action, list):
            if not all(isinstance(c, Call) for c in action):
                raise NodeFaultError(
                    ErrorReport.build_safe(
                        FaultTypes.NODE_ERROR,
                        "a list action must contain only Calls",
                        node=self.node_id,
                    )
                )
            if not action:
                action = None  # empty batch = no action; decline check below
            elif len(action) == 1 and not action[0].isolate_state:
                action = action[0]  # degenerate list: plain call
            else:
                await self._handle_fanout_open(ctx, action)
                return

        if isinstance(action, Call):
            if action.isolate_state:
                # isolated single call = degenerate durable batch (the
                # caller's state must survive outside the wire)
                await self._handle_fanout_open(ctx, [action])
                return
            envelope.reply = None
            await self._dispatch_call(ctx, envelope, action)
            return

        if isinstance(action, TailCall):
            frame = envelope.workflow.require_current()
            frame.target_topic = action.target_topic
            frame.route = action.route
            # the retargeted frame carries ONLY what the TailCall specifies:
            # keeping the old payload would re-stage the original prompt at
            # the handoff target (duplicate user turns per hop)
            frame.payload = action.parts
            envelope.reply = None
            await self._publish_envelope(
                ctx, action.target_topic, envelope, kind="call", route=action.route
            )
            return

        if isinstance(action, ReturnCall):
            frame = envelope.workflow.unwind_frame()
            envelope.reply = ReturnMessage(
                parts=action.parts,
                frame_id=frame.frame_id,
                tag=frame.tag,
                marker=frame.marker,
            )
            # steps flush BEFORE the terminal reply: both land on the same
            # topic+key, so per-key ordering guarantees stream consumers see
            # every step before the result on any broker (reference order:
            # base.py:1982 flush precedes the action publish)
            await self._flush_steps(ctx)
            await self._publish_envelope(
                ctx, frame.callback_topic, envelope, kind="return", route=frame.route
            )
            return

        # None / Next: a reply-owing delivery must not be silently dropped
        if envelope.workflow.depth > 0:
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.DECLINED,
                    f"{self.node_id} declined a reply-owing delivery "
                    f"(route {ctx.route!r})",
                    node=self.node_id,
                    route=ctx.route,
                )
            )

    # ---------------------------------------------------------------- fault
    async def _publish_fault(self, ctx: NodeRunContext, report: ErrorReport) -> None:
        ctx.fault_error_type = report.error_type  # hop span → status=error
        envelope = ctx.envelope
        if envelope.workflow.depth == 0:
            # no caller: the fault rail's floor
            logger.error(
                "[%s] unroutable fault (no caller frame): %s",
                self.node_id,
                report.model_dump_json(),
            )
            return
        frame = envelope.workflow.unwind_frame()
        report = report.model_copy(
            update={"frame_chain": ([frame.frame_id] + report.frame_chain)[:32]}
        )
        await self._flush_steps(ctx)  # steps precede the fault (same key)
        # the state-elision ladder: full -> no tracebacks -> minimal+elide
        budget = self.transport.max_message_bytes
        attempts = [
            (report, False),
            (report.without_tracebacks(), False),
            (report.to_minimal(), True),
        ]
        for attempt, elide_state in attempts:
            candidate = envelope
            if elide_state:
                candidate = envelope.model_copy(deep=True)
                candidate.context.state = State()
                candidate.state_elided = True
            candidate.reply = FaultMessage(
                report=attempt,
                frame_id=frame.frame_id,
                tag=frame.tag,
                marker=frame.marker,
            )
            wire = candidate.to_wire()
            if len(wire) <= budget:
                try:
                    await self._publish_envelope(
                        ctx,
                        frame.callback_topic,
                        candidate,
                        kind="fault",
                        route=frame.route,
                        error_type=attempt.error_type,
                    )
                    if elide_state or attempt is not report:
                        logger.warning(
                            "[%s] fault degraded to fit wire budget "
                            "(state_elided=%s)",
                            self.node_id,
                            elide_state,
                        )
                    return
                except Exception:  # noqa: BLE001 - try the next rung
                    logger.exception(
                        "[%s] fault publish attempt failed; degrading",
                        self.node_id,
                    )
        logger.error(
            "[%s] fault could not be published at any elision rung: %s",
            self.node_id,
            report.to_minimal().model_dump_json(),
        )

    # ------------------------------------------------------------ transport
    async def _publish_envelope(
        self,
        ctx: NodeRunContext,
        topic: str,
        envelope: Envelope,
        *,
        kind: str,
        route: str,
        error_type: str | None = None,
        mirror: bool = True,
    ) -> None:
        headers = {
            protocol.HDR_EMITTER: self.emitter,
            protocol.HDR_KIND: kind,
            protocol.HDR_WIRE: "envelope",
            protocol.HDR_ROUTE: route,
            protocol.HDR_TASK: ctx.task_id,
        }
        if ctx.correlation_id:
            headers[protocol.HDR_CORRELATION] = ctx.correlation_id
        if error_type:
            headers[protocol.HDR_ERROR_TYPE] = error_type
        # deadline propagation: every hop forwards the caller's absolute
        # deadline unchanged (next to the trace headers) so downstream
        # hops and engines enforce the SAME budget
        incoming_deadline = ctx.headers.get(protocol.HDR_DEADLINE)
        if incoming_deadline:
            headers[protocol.HDR_DEADLINE] = incoming_deadline
        # lease propagation (ISSUE 10): like the deadline — downstream
        # work runs on the ORIGINAL caller's behalf; engines several
        # hops deep still register against the one caller lease
        incoming_lease = ctx.headers.get(protocol.HDR_LEASE)
        if incoming_lease:
            headers[protocol.HDR_LEASE] = incoming_lease
        # priority-class propagation (ISSUE 20): forwarded VERBATIM like
        # the deadline/lease — downstream tool calls run on the ORIGINAL
        # caller's behalf, so they degrade as the caller's class, not as
        # the forwarding node's
        incoming_priority = ctx.headers.get(protocol.HDR_PRIORITY)
        if incoming_priority:
            headers[protocol.HDR_PRIORITY] = incoming_priority
        # run-identity propagation (ISSUE 17): forwarded VERBATIM like
        # the deadline/lease — downstream hops serve the same logical
        # run, so their spans stitch into its `ck run` timeline.  Note
        # the contrast with x-mesh-attempt, which is this-placement-only
        # and deliberately NOT forwarded
        incoming_run = ctx.headers.get(protocol.HDR_RUN)
        if incoming_run:
            headers[protocol.HDR_RUN] = incoming_run
        if ctx.trace is not None:
            # downstream hops parent to THIS hop's span
            headers.update(ctx.trace.headers())
        if kind == "call" and ctx.correlation_id:
            self._note_downstream_call(ctx.correlation_id, topic)
        await self.transport.publish(
            topic,
            envelope.to_wire(),
            key=partition_key(ctx.task_id),
            headers=headers,
        )
        # broadcast mirror: the hop's outcome re-published for broker-level
        # taps (reference: base.py:580-701,919) — best-effort, once per hop
        mirror_topic = self.publish_topic() if mirror else None
        if mirror_topic and mirror_topic != topic and not ctx.mirrored:
            ctx.mirrored = True
            try:
                await self.transport.publish(
                    mirror_topic,
                    envelope.to_wire(),
                    key=partition_key(ctx.task_id),
                    headers=headers,
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "[%s] broadcast mirror failed (run unaffected)",
                    self.node_id,
                    exc_info=True,
                )

    def _publish_spans_soon(self, records: "list[Any]") -> None:
        """Export the hop's finished spans off the delivery critical path
        (the dispatcher lane permit is still held here) via the shared
        fire-and-forget helper; the tracer's ring buffer already holds
        every record, so a failed publish degrades to in-process
        visibility."""
        if self._transport is None:
            return
        from calfkit_tpu.observability.trace import publish_spans_soon

        publish_spans_soon(
            self._transport.publish,
            records,
            self._span_tasks,
            on_error=lambda exc: logger.debug(
                "[%s] span publish failed (run unaffected): %s",
                self.node_id, exc,
            ),
        )

    async def _flush_steps(self, ctx: NodeRunContext) -> None:
        if not ctx.ledger.has_steps:
            return
        root = ctx.root_topic or ctx.envelope.workflow.root_callback_topic()
        try:
            await ctx.ledger.flush(
                self.transport,
                root,
                correlation_id=ctx.correlation_id,
                task_id=ctx.task_id,
            )
        except Exception:  # noqa: BLE001 - steps never fault the run
            logger.warning(
                "[%s] step flush failed (run unaffected)", self.node_id, exc_info=True
            )
