"""The durable fan-out batch store and its pure fold/close state machine.

Exactly-once *semantics* over at-least-once delivery (reference:
calfkit/nodes/_fanout_store.py:50-363):

- the state machine is pure functions over :class:`FanoutState` so every
  transition is unit-testable without a broker;
- **write order invariant**: ``open()`` writes basestate (the resume
  snapshot) BEFORE state (the registration), both acked — observing a
  registered batch implies its snapshot is restorable;
- folds are idempotent per slot (duplicate sibling replies classify as
  ``duplicate`` against durable state *before* any user code runs);
- close is tombstone-first: the batch unregisters before the caller resumes,
  so a crash between the two re-delivers nothing.

Storage is two compacted tables per node: ``mesh.fanout.<node_id>.state`` and
``.basestate``, keyed by fanout_id.  The ktables-backed impl below works over
any MeshTransport; the dict-backed offline fake lives in tests.
"""

from __future__ import annotations

from typing import Literal, Protocol

from calfkit_tpu import protocol
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.models.fanout import (
    EnvelopeSnapshot,
    FanoutOpen,
    FanoutOutcome,
    FanoutState,
)

SiblingClass = Literal["expected", "duplicate", "stray", "closed"]
FoldDecision = Literal["parked", "complete", "duplicate", "stray"]


# --------------------------------------------------------------------------- #
# pure state machine
# --------------------------------------------------------------------------- #


def classify_sibling(state: FanoutState | None, slot_id: str) -> SiblingClass:
    """Classify an arriving sibling reply against durable state — BEFORE any
    seams run (reference: _fanout_store.py:164)."""
    if state is None:
        return "closed"  # batch already closed (or never opened): stray-late
    if slot_id not in state.open.slot_ids():
        return "stray"
    if slot_id in state.outcomes:
        return "duplicate"
    return "expected"


def record_outcome(state: FanoutState, outcome: FanoutOutcome) -> FanoutState:
    """Fold one sibling outcome (pure; caller persists)."""
    new_outcomes = dict(state.outcomes)
    new_outcomes[outcome.slot_id] = outcome
    return state.model_copy(update={"outcomes": new_outcomes})


def fold_decision(state: FanoutState) -> FoldDecision:
    return "complete" if state.is_complete() else "parked"


# --------------------------------------------------------------------------- #
# store protocol + ktables implementation
# --------------------------------------------------------------------------- #


class FanoutBatchStore(Protocol):
    """Durable batch storage seam (swap for a fake in the offline lane)."""

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def open(
        self, fanout_id: str, opened: FanoutOpen, snapshot: EnvelopeSnapshot
    ) -> None: ...

    async def load(self, fanout_id: str) -> FanoutState | None: ...

    async def load_snapshot(self, fanout_id: str) -> EnvelopeSnapshot | None: ...

    async def save(self, state: FanoutState) -> None: ...

    async def close(self, fanout_id: str) -> None: ...


FANOUT_STORE_KEY = "fanout_store"


class KtablesFanoutBatchStore:
    """The production store over two compacted mesh tables."""

    def __init__(
        self,
        transport: MeshTransport,
        node_id: str,
        config: "FanoutConfig | None" = None,
    ):
        from calfkit_tpu.tuning import FanoutConfig

        self._transport = transport
        self._config = config or FanoutConfig()
        self._state_topic = protocol.fanout_state_topic(node_id)
        self._base_topic = protocol.fanout_basestate_topic(node_id)
        self._state_reader = transport.table_reader(self._state_topic)
        self._state_writer = transport.table_writer(self._state_topic)
        self._base_reader = transport.table_reader(self._base_topic)
        self._base_writer = transport.table_writer(self._base_topic)

    async def start(self, *, ensure: bool = True) -> None:
        # ensure=False when the caller already provisioned the framework
        # tables (Worker boots through the classifying provisioner; paying
        # another admin round-trip per node would be pure overhead)
        if ensure:
            await self._transport.ensure_topics(
                [self._state_topic, self._base_topic], compacted=True
            )
        timeout = self._config.table.catchup_timeout_s
        await self._base_reader.start(timeout=timeout)
        await self._state_reader.start(timeout=timeout)

    async def stop(self) -> None:
        await self._state_reader.stop()
        await self._base_reader.stop()

    async def open(
        self, fanout_id: str, opened: FanoutOpen, snapshot: EnvelopeSnapshot
    ) -> None:
        # WRITE ORDER INVARIANT: basestate first, then state, both acked
        await self._base_writer.put(
            fanout_id, snapshot.model_dump_json().encode("utf-8")
        )
        await self._state_writer.put(
            fanout_id, FanoutState(open=opened).model_dump_json().encode("utf-8")
        )

    async def load(self, fanout_id: str) -> FanoutState | None:
        await self._state_reader.barrier(
            timeout=self._config.table.barrier_timeout_s
        )
        raw = self._state_reader.get(fanout_id)
        return FanoutState.model_validate_json(raw) if raw else None

    async def load_snapshot(self, fanout_id: str) -> EnvelopeSnapshot | None:
        await self._base_reader.barrier(
            timeout=self._config.table.barrier_timeout_s
        )
        raw = self._base_reader.get(fanout_id)
        return EnvelopeSnapshot.model_validate_json(raw) if raw else None

    async def save(self, state: FanoutState) -> None:
        await self._state_writer.put(
            state.open.fanout_id, state.model_dump_json().encode("utf-8")
        )

    async def close(self, fanout_id: str) -> None:
        # tombstone-first: state (the registration) before basestate, so a
        # crash mid-close leaves no registered-but-snapshotless batch
        await self._state_writer.tombstone(fanout_id)
        await self._base_writer.tombstone(fanout_id)
