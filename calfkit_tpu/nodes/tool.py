"""Tool nodes: ``@agent_tool`` turns a function into a deployable mesh node.

Reference: calfkit/nodes/tool.py:95-260 — signature-derived schema +
validator, ``ModelRetry`` → retry-marked TextPart, eager wire-safety before
return, and the ``Tools`` call-side selector (curated names XOR discover).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Sequence

from pydantic import ValidationError
from pydantic_core import to_jsonable_python

from calfkit_tpu import protocol
from calfkit_tpu.engine.schema import FunctionSchema, function_schema
from calfkit_tpu.models.actions import ReturnCall
from calfkit_tpu.models.capability import CapabilityRecord, ToolDef
from calfkit_tpu.models.error_report import FaultTypes
from calfkit_tpu.models.payload import DataPart, TextPart, retry_text_part
from calfkit_tpu.models.tool_dispatch import ToolBinding
from calfkit_tpu.nodes.base import BaseNodeDef, NodeRunContext, handler


class ModelRetry(Exception):
    """Raised by a tool to send the model a retry prompt instead of a result
    (reference: the vendored ModelRetry honored at nodes/tool.py:123)."""


class ToolNodeDef(BaseNodeDef):
    kind = "tool"

    def __init__(
        self,
        fn: Callable[..., Any] | FunctionSchema,
        *,
        name: str | None = None,
        description: str | None = None,
        **seams: Any,
    ):
        self.schema = (
            fn
            if isinstance(fn, FunctionSchema)
            else function_schema(fn, name=name, description=description)
        )
        super().__init__(name or self.schema.tool_def.name, **seams)

    def _own_fault_type(self) -> str:
        return FaultTypes.TOOL_ERROR

    # ------------------------------------------------------------- topics
    def input_topics(self) -> list[str]:
        return [protocol.tool_input_topic(self.name)]

    def return_topic(self) -> str:
        return protocol.require_topic_safe(f"tool.{self.name}.private.return")

    def publish_topic(self) -> str | None:
        return protocol.tool_publish_topic(self.name)

    # -------------------------------------------------------- control plane
    def capability_record(self) -> CapabilityRecord:
        """The advert this node publishes (reference: tool.py:69)."""
        return CapabilityRecord(
            node_id=self.node_id,
            node_kind=self.kind,
            dispatch_topic=protocol.tool_input_topic(self.name),
            tools=[self.schema.tool_def],
        )

    # ---------------------------------------------------------------- body
    @staticmethod
    def _args_from_payload(ctx: NodeRunContext) -> dict[str, Any]:
        for part in ctx.payload:
            if isinstance(part, DataPart) and isinstance(part.data, dict):
                # either a ToolCallRef-shaped body or bare args
                if "args" in part.data and "tool_name" in part.data:
                    args = part.data.get("args")
                    return args if isinstance(args, dict) else {}
                return part.data
        return {}

    @handler("run")
    async def run(self, ctx: NodeRunContext) -> ReturnCall:
        args = self._args_from_payload(ctx)
        try:
            result = await self.schema.call(args, ctx)
        except ModelRetry as retry:
            return ReturnCall(parts=[retry_text_part(str(retry))])
        except ValidationError as exc:
            # bad arguments: ask the model to try again, don't fault the run
            return ReturnCall(
                parts=[retry_text_part(f"Invalid arguments for {self.name}: {exc}")]
            )
        # eager wire-safety: a result that can't serialize fails HERE, inside
        # this node's fault rail, not at the caller (reference: tool.py:158)
        try:
            jsonable = to_jsonable_python(result)
            json.dumps(jsonable)
        except (TypeError, ValueError) as exc:
            raise TypeError(
                f"tool {self.name!r} returned a non-wire-safe value "
                f"({type(result).__name__}): {exc}"
            ) from exc
        if isinstance(jsonable, str):
            return ReturnCall(parts=[TextPart(text=jsonable)])
        return ReturnCall(parts=[DataPart(data=jsonable)])


def agent_tool(
    fn: Callable[..., Any] | None = None,
    *,
    name: str | None = None,
    description: str | None = None,
    **seams: Any,
) -> Any:
    """Decorator: ``@agent_tool`` → a deployable :class:`ToolNodeDef`."""

    def build(f: Callable[..., Any]) -> ToolNodeDef:
        return ToolNodeDef(f, name=name, description=description, **seams)

    return build(fn) if fn is not None else build


class Tools:
    """Call-side tool selector: curated names XOR discover-all.

    Resolves against the live capability view at model-turn time
    (reference: nodes/tool.py:207 ``Tools``).
    """

    def __init__(
        self, *names: str, discover: bool = False, exclude: Sequence[str] = ()
    ):
        from calfkit_tpu.utils_names import validate_curated_or_discover

        validate_curated_or_discover("Tools", names, discover)
        self.names = list(names)
        self.discover = discover
        self.exclude = set(exclude)

    def resolve(self, records: list[CapabilityRecord]) -> list[ToolBinding]:
        from calfkit_tpu.models.capability import (
            resolve_all_capabilities,
            resolve_capability,
        )

        if self.discover:
            return [
                ToolBinding(tool=r.tool, dispatch_topic=r.dispatch_topic)
                for r in resolve_all_capabilities(records)
                if r.tool.name not in self.exclude
            ]
        bindings: list[ToolBinding] = []
        for tool_name in self.names:
            resolved = resolve_capability(records, tool_name)
            bindings.append(
                ToolBinding(tool=resolved.tool, dispatch_topic=resolved.dispatch_topic)
            )
        return bindings


def eager_tools(*defs: ToolNodeDef) -> list[ToolBinding]:
    """Bind tool defs directly (no discovery): the quickstart path where the
    agent and tools deploy in one worker."""
    return [
        ToolBinding(
            tool=d.schema.tool_def,
            dispatch_topic=protocol.tool_input_topic(d.name),
        )
        for d in defs
    ]
