"""The per-hop step ledger: the SOLE minting authority for wire step events.

Bodies return *facts* (:class:`Said`, :class:`HandedOff`, :class:`DeniedCall`)
wrapped in :class:`Observed`; the ledger turns facts into wire steps and
flushes them exactly once per hop to the run's root callback topic
(reference: calfkit/nodes/_steps.py:100-212; the single-mint rule is
construction-sealed there and enforced by an AST sweep — here it is enforced
by convention: only this module constructs wire ``*Step`` objects inside the
node kernel).

The pair law (reference SURVEY.md §5): every dispatched marked Call mints its
``tool_call`` step at the publish chokepoint and its ``tool_result`` step at
the fold; calls denied before dispatch are born-closed pairs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from calfkit_tpu import protocol
from calfkit_tpu.keying import partition_key
from calfkit_tpu.models.actions import NodeResult
from calfkit_tpu.models.error_report import ErrorReport, safe_str
from calfkit_tpu.models.step import (
    AgentMessageStep,
    HandoffStep,
    InferenceStep,
    Step,
    StepMessage,
    TokenStep,
    ToolCallStep,
    ToolResultStep,
)

# --------------------------------------------------------------------------- #
# facts: what a body may report having observed
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class Said:
    text: str
    author: str | None = None


@dataclass(frozen=True)
class HandedOff:
    to_agent: str
    from_agent: str | None = None


@dataclass(frozen=True)
class DeniedCall:
    """A model tool call rejected before dispatch: a born-closed step pair."""

    tool_call_id: str
    tool_name: str
    reason: str
    args: dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class InferenceFact:
    model_name: str
    prefill_ms: float = 0.0
    decode_ms: float = 0.0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    batch_occupancy: float = 0.0
    tokens_per_second: float = 0.0


Fact = Said | HandedOff | DeniedCall | InferenceFact


@dataclass
class Observed:
    """A body's widened return: the action plus telemetry facts."""

    action: NodeResult
    facts: list[Fact] = field(default_factory=list)


# --------------------------------------------------------------------------- #
# the ledger
# --------------------------------------------------------------------------- #


class HopStepLedger:
    """Created per delivery; flushed once at hop exit, best-effort."""

    def __init__(self, emitter: str):
        self._emitter = emitter
        self._steps: list[Step] = []
        self._flushed = False

    # ------------------------------------------------------------- absorb
    def absorb(self, facts: list[Fact]) -> None:
        for fact in facts:
            if isinstance(fact, Said):
                self._steps.append(AgentMessageStep(text=fact.text, author=fact.author))
            elif isinstance(fact, HandedOff):
                self._steps.append(
                    HandoffStep(to_agent=fact.to_agent, from_agent=fact.from_agent)
                )
            elif isinstance(fact, DeniedCall):
                self._steps.append(
                    ToolCallStep(
                        tool_call_id=fact.tool_call_id,
                        tool_name=fact.tool_name,
                        args=fact.args,
                        denied=True,
                    )
                )
                self._steps.append(
                    ToolResultStep(
                        tool_call_id=fact.tool_call_id,
                        tool_name=fact.tool_name,
                        ok=False,
                        content=fact.reason,
                    )
                )
            elif isinstance(fact, InferenceFact):
                self._steps.append(
                    InferenceStep(
                        model_name=fact.model_name,
                        prefill_ms=fact.prefill_ms,
                        decode_ms=fact.decode_ms,
                        prompt_tokens=fact.prompt_tokens,
                        generated_tokens=fact.generated_tokens,
                        batch_occupancy=fact.batch_occupancy,
                        tokens_per_second=fact.tokens_per_second,
                    )
                )

    def note_dispatch(
        self, tool_call_id: str, tool_name: str, args: dict[str, Any]
    ) -> None:
        """Minted at the publish chokepoint for every marked outgoing Call."""
        self._steps.append(
            ToolCallStep(tool_call_id=tool_call_id, tool_name=tool_name, args=args)
        )

    def folded(
        self, tool_call_id: str, tool_name: str, content: Any, *,
        ok: bool = True,
    ) -> None:
        """``ok=False`` with content: the callee faulted but a recovery seam
        substituted a value — honest telemetry shows the failure AND what
        the model will see instead."""
        self._steps.append(
            ToolResultStep(
                tool_call_id=tool_call_id,
                tool_name=tool_name,
                ok=ok,
                content=safe_str(content, 2048),
            )
        )

    def fold_failed(
        self, tool_call_id: str, tool_name: str, report: ErrorReport
    ) -> None:
        self._steps.append(
            ToolResultStep(
                tool_call_id=tool_call_id,
                tool_name=tool_name,
                ok=False,
                content=report.describe(),
            )
        )

    def token(self, text: str, author: str | None = None) -> None:
        self._steps.append(TokenStep(text=text, author=author))

    # -------------------------------------------------------------- flush
    @property
    def has_steps(self) -> bool:
        return bool(self._steps)

    def drain(self) -> StepMessage | None:
        """Take the batch (idempotent: second call returns None)."""
        if self._flushed or not self._steps:
            return None
        self._flushed = True
        return StepMessage(steps=self._steps, emitter=self._emitter)

    async def flush(
        self,
        transport: Any,
        root_topic: str | None,
        *,
        correlation_id: str | None,
        task_id: str | None,
    ) -> None:
        """Publish the hop's steps to the run's root callback topic.

        Best-effort: failure is floor-logged by the caller, never faults the
        run (reference: base.py:530-570).
        """
        message = self.drain()
        if message is None or root_topic is None:
            return
        await publish_step_message(
            transport,
            root_topic,
            message,
            correlation_id=correlation_id,
            task_id=task_id,
        )


async def publish_step_message(
    transport: Any,
    root_topic: str,
    message: StepMessage,
    *,
    correlation_id: str | None,
    task_id: str | None,
) -> None:
    """The ONE way a wire StepMessage reaches the step stream — used by the
    hop ledger's flush and by live token streaming, so headers/keying can
    never diverge."""
    headers = {protocol.HDR_WIRE: "step", protocol.HDR_EMITTER: message.emitter}
    if correlation_id:
        headers[protocol.HDR_CORRELATION] = correlation_id
    if task_id:
        headers[protocol.HDR_TASK] = task_id
    await transport.publish(
        root_topic,
        message.to_wire(),
        key=partition_key(task_id) if task_id else None,
        headers=headers,
    )
