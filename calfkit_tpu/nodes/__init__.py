"""Node kinds over the shared kernel (SURVEY.md §1 layers 2-3)."""

from calfkit_tpu.nodes.agent import (
    Agent,
    BaseAgentNodeDef,
    StatelessAgent,
    render_fault_for_model,
    surface_to_model,
)

from calfkit_tpu.nodes.base import BaseNodeDef, NodeRunContext, handler
from calfkit_tpu.nodes.consumer import ConsumerContext, ConsumerNode, consumer
from calfkit_tpu.nodes.fanout_store import (
    FANOUT_STORE_KEY,
    FanoutBatchStore,
    KtablesFanoutBatchStore,
)
from calfkit_tpu.nodes.registry import RegistryMixin
from calfkit_tpu.nodes.steps import (
    DeniedCall,
    HandedOff,
    HopStepLedger,
    InferenceFact,
    Observed,
    Said,
)
from calfkit_tpu.nodes.tool import (
    ModelRetry,
    ToolNodeDef,
    Tools,
    agent_tool,
    eager_tools,
)

__all__ = [
    "surface_to_model",
    "render_fault_for_model",
    "Agent",
    "BaseAgentNodeDef",
    "BaseNodeDef",
    "ConsumerContext",
    "ConsumerNode",
    "DeniedCall",
    "FANOUT_STORE_KEY",
    "FanoutBatchStore",
    "HandedOff",
    "HopStepLedger",
    "InferenceFact",
    "KtablesFanoutBatchStore",
    "ModelRetry",
    "NodeRunContext",
    "Observed",
    "RegistryMixin",
    "Said",
    "StatelessAgent",
    "ToolNodeDef",
    "Tools",
    "agent_tool",
    "consumer",
    "eager_tools",
    "handler",
]
