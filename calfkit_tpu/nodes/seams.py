"""Policy-seam chain runner.

The four seams (reference: calfkit/nodes/_seams.py:23-136 and the seam table
in nodes/base.py):

- ``before_node(ctx)`` — guard/mutate before the body; a non-``None``
  return SHORT-CIRCUITS the body and is published as the hop's action
  (plain strings/dicts are coerced to a reply — see base._as_action).
- ``after_node(ctx, action)`` — transform the body's action; a
  non-``None`` return replaces it (same coercion).
- ``on_node_error(ctx, report)`` — recover the node's own raise; returns a
  substitute action, or ``None`` to pass down the chain (fault escalates if
  no seam recovers).
- ``on_callee_error(ctx, report)`` — recover a downstream fault; returns
  substitute content parts, or ``None`` to escalate.

Chains run in registration order; the first non-``None`` return wins.  A seam
raising :class:`NodeFaultError` *mints* a typed fault (it is not treated as a
seam crash); any other raise is itself a node error.
"""

from __future__ import annotations

import inspect
from typing import Any, Awaitable, Callable, Sequence

from calfkit_tpu.exceptions import NodeFaultError, SeamContractError

Seam = Callable[..., Any]


def validate_seam_arity(seam: Seam, expected: int, *, name: str) -> None:
    try:
        sig = inspect.signature(seam)
    except (TypeError, ValueError):
        return  # builtins / partials without introspection: trust the caller
    positional = [
        p
        for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        and p.default is p.empty
    ]
    has_var = any(p.kind == p.VAR_POSITIONAL for p in sig.parameters.values())
    if not has_var and len(positional) != expected:
        raise SeamContractError(
            f"{name} seam {getattr(seam, '__name__', seam)!r} must take "
            f"{expected} positional argument(s), found {len(positional)}"
        )


async def _call(seam: Seam, *args: Any) -> Any:
    result = seam(*args)
    if inspect.isawaitable(result):
        result = await result
    return result


async def run_chain(seams: Sequence[Seam], *args: Any) -> Any:
    """First non-None result wins; ``None`` falls through the chain."""
    for seam in seams:
        result = await _call(seam, *args)
        if result is not None:
            return result
    return None


class MintedFault(Exception):
    """Internal: a seam raised NodeFaultError — carry it out of the chain
    without confusing it with a seam crash (reference: the ``_Minted``
    sentinel, _seams.py:53)."""

    def __init__(self, error: NodeFaultError):
        self.error = error
        super().__init__(str(error))


async def run_chain_guarded(seams: Sequence[Seam], *args: Any) -> Any:
    """Like :func:`run_chain` but distinguishes a deliberate typed-fault mint
    (NodeFaultError) from an accidental seam crash."""
    for seam in seams:
        try:
            result = await _call(seam, *args)
        except NodeFaultError as exc:
            raise MintedFault(exc) from exc
        if result is not None:
            return result
    return None
