"""POV projection of multi-agent history.

Each agent sees its OWN turns natively; other agents' turns appear as
attributed user-visible text, and foreign tool calls/returns are stripped
(a model must never see tool-call ids it didn't mint).  Reference:
calfkit/nodes/_projection.py:88-139.
"""

from __future__ import annotations

from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    ToolReturnPart,
    UserPart,
)


def project(history: list[ModelMessage], self_name: str) -> list[ModelMessage]:
    """Re-render ``history`` from ``self_name``'s point of view."""
    projected: list[ModelMessage] = []
    own_call_ids: set[str] = set()
    for message in history:
        if isinstance(message, ModelResponse):
            author = message.author
            if author is None or author == self_name:
                own_call_ids |= {c.tool_call_id for c in message.tool_calls()}
                projected.append(message)
                continue
            text = message.text()
            if text:
                projected.append(
                    ModelRequest(
                        parts=[UserPart(content=f"[{author}] {text}", author=author)]
                    )
                )
            # foreign tool calls are stripped entirely
            continue
        # ModelRequest: keep own-tool returns/retries, user and system parts
        kept = []
        for part in message.parts:
            if isinstance(part, (ToolReturnPart, RetryPart)):
                if part.tool_call_id and part.tool_call_id not in own_call_ids:
                    continue
            kept.append(part)
        if kept or message.instructions:
            projected.append(
                ModelRequest(parts=kept, instructions=message.instructions)
            )
    return projected


def structured_output_preamble(schema_name: str) -> str:
    """Reference: _projection.py:116."""
    return (
        f"When you have the final answer, return it as a {schema_name} "
        "structured result rather than prose."
    )
