"""POV projection of multi-agent history + the two message-aware preambles.

Semantics (reference: calfkit/nodes/_projection.py:88-326):

- **Transparent mode** — when the history has no participants other than the
  viewer (no foreign agent turns, at most one named human), pass messages
  through with attribution stripped.  No prefixes ⇒ the prompt prefix (and
  any provider prompt cache) stays stable for single-agent conversations.
- **Multi-participant mode** — the viewer sees its OWN turns verbatim
  (tool-call ids intact: the deferred-results re-entry depends on them);
  other agents' turns appear as attributed user-visible text built from
  their public *surface* (text + final_result / handoff briefing args);
  ordinary foreign tool calls and thinking are internal and dropped.
- Tool returns / retry prompts are kept only when the viewer owns the
  tool_call_id — ownership resolved over the WHOLE history, so a retry
  part referencing a foreign agent's call is stripped even when it arrives
  before/after interleaved turns.
- Human turns are attributed ``<user>`` / ``<user:name>``.

``structured_output_preamble`` / ``step_preamble`` extract the text a hop
*said* alongside what it did (reference: _projection.py:116,139) — from the
hop's FINAL response only, so internal output-retry chatter never surfaces.
"""

from __future__ import annotations

import json
import logging

from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)

logger = logging.getLogger(__name__)

_UNKNOWN_AUTHOR = "unknown"


def _is_surfaced_tool(tool_name: str) -> bool:
    """Tools whose ARGS are another agent's public surface: the structured
    final answer and the handoff briefing (its args are the peer's only
    briefing channel)."""
    from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL
    from calfkit_tpu.peers.handoff import HANDOFF_TOOL

    return tool_name in (FINAL_RESULT_TOOL, HANDOFF_TOOL)


def project(history: list[ModelMessage], self_name: str) -> list[ModelMessage]:
    """Re-render ``history`` from ``self_name``'s point of view.

    Pure: returns fresh messages, never mutates the input.
    """
    foreign_agents = {
        m.author
        for m in history
        if isinstance(m, ModelResponse) and m.author and m.author != self_name
    }
    named_humans = {
        p.author
        for m in history
        if isinstance(m, ModelRequest)
        for p in m.parts
        if isinstance(p, UserPart) and p.author
    }
    if not foreign_agents and len(named_humans) < 2:
        return _transparent(history)
    logger.debug(
        "projecting multi-participant POV for %s (%d foreign agents, "
        "%d named humans)",
        self_name, len(foreign_agents), len(named_humans),
    )
    owners = _tool_call_owners(history)
    out: list[ModelMessage] = []
    for message in history:
        if isinstance(message, ModelResponse):
            out.extend(_project_response(message, self_name))
        else:
            out.extend(_project_request(message, self_name, owners))
    return out


# --------------------------------------------------------------------------- #
# transparent pass-through
# --------------------------------------------------------------------------- #


def _transparent(history: list[ModelMessage]) -> list[ModelMessage]:
    out: list[ModelMessage] = []
    for message in history:
        if isinstance(message, ModelResponse):
            out.append(
                message.model_copy(update={"author": None})
                if message.author
                else message
            )
            continue
        if any(isinstance(p, UserPart) and p.author for p in message.parts):
            parts = [
                p.model_copy(update={"author": None})
                if isinstance(p, UserPart) and p.author
                else p
                for p in message.parts
            ]
            out.append(message.model_copy(update={"parts": parts}))
        else:
            out.append(message)
    return out


# --------------------------------------------------------------------------- #
# multi-participant projection
# --------------------------------------------------------------------------- #


def _tool_call_owners(history: list[ModelMessage]) -> dict[str, str]:
    """tool_call_id → authoring agent, resolved over the WHOLE history (a
    foreign retry/return is foreign wherever it appears)."""
    owners: dict[str, str] = {}
    for message in history:
        if isinstance(message, ModelResponse):
            author = message.author or _UNKNOWN_AUTHOR
            for call in message.tool_calls():
                owners[call.tool_call_id] = author
    return owners


def _project_response(
    message: ModelResponse, self_name: str
) -> list[ModelMessage]:
    author = message.author or _UNKNOWN_AUTHOR
    if author == self_name:
        # verbatim (author stripped): in-flight tool-call ids must survive
        # for the deferred-results re-entry
        return [message.model_copy(update={"author": None})]
    surface = _surface(message)
    if not surface:
        return []  # nothing public (e.g. a pure dispatch turn): omitted
    return [
        ModelRequest(
            parts=[UserPart(content=f"<{author}>\n{surface}", author=author)]
        )
    ]


def _surface(message: ModelResponse) -> str:
    """A foreign response's public face: its text plus the canonical JSON of
    surfaced tool args (final answers and handoff briefings)."""
    components: list[str] = []
    for part in message.parts:
        if isinstance(part, TextOutput):
            if part.text:
                components.append(part.text)
        elif isinstance(part, ToolCallOutput) and _is_surfaced_tool(
            part.tool_name
        ):
            if part.args:
                try:
                    components.append(
                        json.dumps(
                            part.args_dict(),
                            separators=(",", ":"),
                            sort_keys=True,
                        )
                    )
                except Exception:  # noqa: BLE001 - degrade, never raise
                    logger.warning(
                        "could not render surfaced args of %s; omitting",
                        part.tool_name, exc_info=True,
                    )
    return "\n".join(components)


def _project_request(
    message: ModelRequest, self_name: str, owners: dict[str, str]
) -> list[ModelMessage]:
    kept: list = []
    for part in message.parts:
        if isinstance(part, (ToolReturnPart, RetryPart)):
            owner = owners.get(part.tool_call_id or "")
            if part.tool_call_id and owner != self_name:
                continue  # a foreign exchange — never show foreign ids
            kept.append(part)
        elif isinstance(part, UserPart):
            kept.append(_attribute_user(part))
        elif isinstance(part, SystemPart):
            kept.append(part)
        else:
            kept.append(part)
    if not kept and not message.instructions:
        return []
    return [message.model_copy(update={"parts": kept})]


def _attribute_user(part: UserPart) -> UserPart:
    prefix = f"<user:{part.author}>" if part.author else "<user>"
    content = part.content
    if isinstance(content, str):
        return UserPart(content=f"{prefix} {content}")
    return part  # structured content: leave verbatim


# --------------------------------------------------------------------------- #
# the two hop preambles
# --------------------------------------------------------------------------- #


def _final_response(messages: list[ModelMessage]) -> ModelResponse | None:
    for message in reversed(messages):
        if isinstance(message, ModelResponse):
            return message
    return None


def structured_output_preamble(new_messages: list[ModelMessage]) -> str:
    """The text the hop said ALONGSIDE its structured final answer.

    Non-empty only when the final response also carries a ``final_result``
    call — i.e. the text is a genuine preamble, not the answer itself
    (reference: _projection.py:116)."""
    from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL

    response = _final_response(new_messages)
    if response is None:
        return ""
    if not any(
        c.tool_name == FINAL_RESULT_TOOL for c in response.tool_calls()
    ):
        return ""  # prompted/native mode: the text IS the answer
    return response.text()


def step_preamble(new_messages: list[ModelMessage]) -> str:
    """The text of the hop's FINAL response — what a non-terminal
    (dispatch/handoff) hop said while acting.  Final-response-only is
    load-bearing: earlier responses in the hop are internal retry chatter
    (reference: _projection.py:139)."""
    response = _final_response(new_messages)
    return response.text() if response is not None else ""
