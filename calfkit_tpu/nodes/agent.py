"""The Agent node: one model turn per delivery, tools dispatched as mesh
calls.

Reference: calfkit/nodes/agent.py:80-1031.  The hot loop (SURVEY.md §3.3):

    delivery(call)   → stage user prompt → model turn
    model turn       → tool calls?  dispatch as Call/fan-out (tag =
                       tool_call_id, marker-stamped) and suspend
                     → final?      ReturnCall with text/structured parts
    delivery(return) → materialized tool_results → next model turn

State discipline: the staged request (user prompt or tool-returns) is
committed to ``message_history`` only after a successful model turn, so a
redelivered hop cannot double-commit; in-flight ``tool_calls`` /
``tool_results`` live in :class:`State` and ride the wire.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Sequence

from pydantic_core import to_jsonable_python

from calfkit_tpu import protocol
from calfkit_tpu.engine.model_client import ModelClient, ModelSettings
from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL, TurnOutcome, run_turn
from calfkit_tpu.exceptions import NodeFaultError
from calfkit_tpu.models.actions import Call, NodeResult, ReturnCall, TailCall
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.capability import CapabilityRecord
from calfkit_tpu.models.error_report import ErrorReport, FaultTypes
from calfkit_tpu.models.marker import ToolCallMarker
from calfkit_tpu.models.messages import (
    ModelRequest,
    RetryPart,
    ToolReturnPart,
    UserPart,
)
from calfkit_tpu.models.payload import (
    DataPart,
    TextPart,
    render_parts_as_text,
    retry_text_part,
)
from calfkit_tpu.models.tool_dispatch import ToolBinding, ToolCallRef
from calfkit_tpu.nodes.base import BaseNodeDef, NodeRunContext, handler
from calfkit_tpu.nodes.projection import (
    project,
    step_preamble,
    structured_output_preamble,
)
from calfkit_tpu.nodes.steps import (
    DeniedCall,
    Fact,
    HandedOff,
    InferenceFact,
    Observed,
    Said,
)
from calfkit_tpu.nodes.tool import ToolNodeDef, eager_tools
from calfkit_tpu.peers.handoff import HANDOFF_TOOL, arbitrate_handoff
from calfkit_tpu.peers.messaging import MESSAGE_AGENT_TOOL

logger = logging.getLogger(__name__)

Instructions = str | Callable[[NodeRunContext], str]
ToolsSpec = Any  # ToolNodeDef list | ToolBinding list | selector with .resolve()

CAPABILITY_VIEW_KEY = "capability_view"
AGENTS_VIEW_KEY = "agents_view"


def render_fault_for_model(report: ErrorReport) -> Any:
    """A callee fault rendered as a model-visible retry part (the
    ``surface_to_model`` prebuilt, reference: nodes/_tool_error.py:116)."""
    return retry_text_part(
        f"The tool call failed: {report.describe()}. "
        "You may retry, use another tool, or answer without it."
    )


def surface_to_model(ctx: NodeRunContext, report: ErrorReport) -> list[Any]:
    return [render_fault_for_model(report)]


def _adapt_on_tool_error(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Adapt ``on_tool_error(tool_call_marker, ctx, report)`` onto the
    kernel's 2-arg ``on_callee_error`` seam."""

    async def seam(ctx: NodeRunContext, report: ErrorReport) -> Any:
        marker = ctx.folding_marker
        if not isinstance(marker, ToolCallMarker):
            return None  # not a tool-call reply: fall through the chain
        result = fn(marker, ctx, report)
        if hasattr(result, "__await__"):
            result = await result
        return result

    return seam


class BaseAgentNodeDef(BaseNodeDef):
    kind = "agent"

    def __init__(
        self,
        name: str,
        *,
        model: ModelClient,
        instructions: Instructions | None = None,
        tools: ToolsSpec = (),
        peers: Sequence[Any] = (),  # Messaging / Handoff selectors
        output_type: type = str,
        description: str = "",
        model_settings: ModelSettings | None = None,
        max_output_retries: int = 2,
        on_tool_error: Callable[..., Any] | None = None,
        stream_tokens: bool = False,
        **seams: Any,
    ):
        super().__init__(name, **seams)
        self.model = model
        self.instructions = instructions
        self.tools = tools
        self.peers = list(peers)
        kinds = [getattr(p, "kind", "?") for p in self.peers]
        if len(kinds) != len(set(kinds)):
            from calfkit_tpu.exceptions import LifecycleConfigError

            raise LifecycleConfigError(
                f"agent {name!r}: one peer selector per kind (got {kinds}); "
                "list multiple names inside one selector instead"
            )
        self.output_type = output_type
        self.description = description
        self.model_settings = model_settings
        self.max_output_retries = max_output_retries
        self.stream_tokens = stream_tokens
        if on_tool_error is not None:
            # sugar: (tool_call_marker, ctx, report) -> parts | None, adapted
            # onto the kernel's on_callee_error seam (reference:
            # nodes/_tool_error.py:42-150)
            self.on_callee_error.append(_adapt_on_tool_error(on_tool_error))
        # failure recovery (ISSUE 9): arrivals marked as failover
        # re-dispatches / hedge duplicates (the caller's x-mesh-attempt
        # marker), folded into the engine-stats advert so `ck stats` /
        # `ck fleet` show which replicas are absorbing recovered work
        self._failover_requests = 0
        self._hedge_requests = 0
        # run-scoped observability (ISSUE 17): arrivals counted from the
        # x-mesh-run header — runs (attempt_no == 0) vs every linked
        # placement, so ATTEMPTS/RUNS in `ck stats` is the amplification
        # failover/hedge re-dispatches add per replica.  Corrupt or
        # missing headers count in NEITHER (un-linked degrade, PR 5 law)
        self._run_requests = 0
        self._attempt_requests = 0

    # --------------------------------------------------------- decorators
    def instructions_fn(self, fn: Callable[[NodeRunContext], str]) -> Callable:
        """Decorator: dynamic instructions rendered per turn.

        ``@weather_agent.instructions_fn`` (reference: the instructions
        decorator on the agent, SURVEY.md capability checklist)."""
        self.instructions = fn
        return fn

    # ------------------------------------------------------------- topics
    def input_topics(self) -> list[str]:
        topics = [protocol.agent_input_topic(self.name)]
        replica = self.replica_topic()
        if replica is not None:
            topics.append(replica)
        return topics

    def replica_topic(self) -> "str | None":
        """The replica-ADDRESSED input topic (ISSUE 7), for agents whose
        model exposes serving stats (the engine-backed ones the fleet
        router places): consumed only by THIS instance, advertised in
        the engine-stats heartbeat so routing policies can pick a
        specific replica.  None for plain agents — they stay
        shared-topic only and never enter the replica registry."""
        if getattr(self.model, "stats_snapshot", None) is None:
            return None
        return protocol.agent_replica_topic(self.name, self.instance_id)

    def return_topic(self) -> str:
        return protocol.agent_return_topic(self.name)

    def publish_topic(self) -> str | None:
        return protocol.agent_publish_topic(self.name)

    # -------------------------------------------------------- control plane
    def agent_card(self) -> AgentCard:
        return AgentCard(
            name=self.name,
            description=self.description,
            structured_output=self.output_type is not str,
        )

    def engine_stats_record(self) -> "dict | None":
        """Serving metrics for the engine-stats advert, when this agent's
        model exposes them (the local TPU backend does); None otherwise."""
        snapshot_fn = getattr(self.model, "stats_snapshot", None)
        if snapshot_fn is None:
            return None
        from calfkit_tpu.models.records import EngineStatsRecord

        try:
            try:
                # the heartbeat is THE designated consumer of the
                # per-interval window (single-consumer delta semantics)
                snapshot = snapshot_fn(window=True)
            except TypeError:
                snapshot = snapshot_fn()  # third-party snapshot: no kwarg
            # fleet identity + routability (ISSUE 7): which instance this
            # is, where to address it, and whether the hosting worker
            # would admit a NEW run right now — re-derived per heartbeat
            # tick, so a drain() flips the advert on the next beat and
            # the router stops picking this replica
            worker = self.resources.get("worker")
            ready, _ = (
                worker.ready() if hasattr(worker, "ready") else (True, "")
            )
            # a wedged engine advertises unready WITHOUT draining (ISSUE
            # 9): routers stop placing new runs here, and the dead-
            # placement law declares outstanding placements dead so their
            # callers fail over instead of timing out
            if snapshot.get("wedged"):
                ready = False
            return EngineStatsRecord(
                node_id=self.node_id,
                instance_id=self.instance_id,
                replica_topic=self.replica_topic() or "",
                ready=bool(ready),
                draining=bool(getattr(worker, "draining", False)),
                failover_requests=self._failover_requests,
                hedge_requests=self._hedge_requests,
                run_requests=self._run_requests,
                attempt_requests=self._attempt_requests,
                **snapshot,
            ).model_dump()
        except Exception:  # noqa: BLE001 - metrics must never fault serving
            logger.debug("engine stats snapshot failed", exc_info=True)
            return None

    # ------------------------------------------------------ tool resolution
    def _resolve_tools(self, ctx: NodeRunContext) -> list[ToolBinding]:
        """Per-turn resolution (reference: agent.py:621 — selectors resolve
        against the live capability view each turn)."""
        spec = self.tools
        if not spec:
            return []
        if isinstance(spec, (list, tuple)):
            bindings: list[ToolBinding] = []
            node_defs = [t for t in spec if isinstance(t, ToolNodeDef)]
            bindings.extend(eager_tools(*node_defs))
            bindings.extend(t for t in spec if isinstance(t, ToolBinding))
            return bindings
        if hasattr(spec, "resolve"):
            records = self._capability_records(ctx)
            return spec.resolve(records)
        raise NodeFaultError(
            ErrorReport.build_safe(
                FaultTypes.LIFECYCLE_ERROR,
                f"unsupported tools spec {type(spec).__name__}",
                node=self.node_id,
            )
        )

    def _capability_records(self, ctx: NodeRunContext) -> list[CapabilityRecord]:
        view = ctx.resource(CAPABILITY_VIEW_KEY)
        if view is None:
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.CAPABILITY_UNAVAILABLE,
                    f"{self.node_id} uses a discovery selector but no "
                    "capability view is attached (control plane not running?)",
                    node=self.node_id,
                )
            )
        return view.records()

    # ---------------------------------------------------------------- body
    _MAX_REJECTED_LOOPS = 3

    @handler("run")
    async def run(self, ctx: NodeRunContext) -> NodeResult | Observed:
        if ctx.delivery_kind == "call":
            # recovery accounting (ISSUE 9): count failover/hedge arrivals
            # once per placed call (not per tool-return resumption)
            attempt = ctx.headers.get(protocol.HDR_ATTEMPT)
            if attempt == "failover":
                self._failover_requests += 1
            elif attempt == "hedge":
                self._hedge_requests += 1
            # run accounting (ISSUE 17): parse_run returns None for a
            # corrupt/missing header — such arrivals count in neither
            # bucket (they are un-linked, not a shared bogus run id)
            parsed_run = protocol.parse_run(
                ctx.headers.get(protocol.HDR_RUN)
            )
            if parsed_run is not None:
                self._attempt_requests += 1
                if parsed_run[1] == 0:
                    self._run_requests += 1
        for _ in range(self._MAX_REJECTED_LOOPS):
            try:
                return await self._run_one_turn(ctx)
            except _AllCallsRejected:
                # tool_results already hold retry parts; loop = next model
                # turn within this same hop
                ctx.delivery_kind = "return"
                continue
        raise NodeFaultError(
            ErrorReport.build_safe(
                FaultTypes.VALIDATION_ERROR,
                f"{self.node_id}: model repeated invalid tool calls "
                f"{self._MAX_REJECTED_LOOPS} times",
                node=self.node_id,
            )
        )

    async def _run_one_turn(self, ctx: NodeRunContext) -> NodeResult | Observed:
        state = ctx.state
        facts: list[Fact] = []

        # ---- build the staged request for this hop
        staged: ModelRequest | None
        if ctx.delivery_kind == "call":
            if state.uncommitted_message is not None:
                # a client-staged prompt (or a redelivered hop) already rides
                # in the state; reuse it instead of double-staging
                staged = state.uncommitted_message
            elif not ctx.payload and state.message_history:
                # a handoff continuation: the history is the conversation;
                # nothing new to stage
                staged = None
            else:
                parts = ctx.payload
                content = render_parts_as_text(parts) if parts else ""
                staged = ModelRequest(parts=[UserPart(content=content)])
                state.uncommitted_message = staged
            state.clear_inflight()
        else:
            staged = self._tool_results_request(ctx)

        # ---- resolve tools, peers & instructions
        bindings = self._resolve_tools(ctx)
        self._guard_reserved_names(bindings)
        peer_defs, peer_targets = self._resolve_peers(ctx)
        instructions = self._render_instructions(ctx)
        # history is POV-projected: foreign turns render as attributed text
        history = project(list(state.message_history), self.name)
        if staged is not None:
            request = staged.model_copy(update={"instructions": instructions})
            messages = history + [request]
        elif history and instructions:
            messages = history[:-1] + [
                history[-1].model_copy(update={"instructions": instructions})
                if isinstance(history[-1], ModelRequest)
                else history[-1]
            ]
        else:
            messages = history

        # ---- ONE model turn (optionally with live token streaming to the
        # run's step stream — BASELINE config 3's downstream-topic tokens)
        model: ModelClient = self.model
        if self.stream_tokens and ctx.root_topic:
            model = _TokenTap(self.model, self, ctx)
        # the turn span: child of the hop span, parent of the engine's
        # prefill/decode spans (propagated via the trace contextvar so the
        # inference client needs no plumbing).  Untraced hops skip it.
        from calfkit_tpu.observability.trace import TRACER, current_context

        turn_span = None
        turn_token = None
        parent_ctx = current_context.get()
        if parent_ctx is not None:
            turn_span = TRACER.start_span(
                "agent.turn",
                parent=parent_ctx,
                kind="agent",
                emitter=self.emitter,
                attrs={"model": self.model.model_name},
            )
            turn_token = current_context.set(turn_span.context)
        # decode-from-offset resume (ISSUE 10): a failover re-dispatch
        # carries the already-delivered answer text in
        # deps["calfkit.resume_text"]; this model turn CONSUMES it —
        # backends that honor ModelSettings.resume_text prefill the
        # delivered prefix (riding the survivor's prefix cache) and
        # decode only the remainder, instead of silently re-generating
        # the whole answer.  Only the RE-DISPATCHED call's first turn
        # resumes — gated on the x-mesh-attempt: failover marker, which
        # hops never forward: deps ride the whole run's envelope, and
        # without the gate a downstream peer-agent call would consume
        # the TOP agent's delivered prefix as its own answer.  Tool-
        # return re-entries are later turns of a different answer.
        settings = self.model_settings
        resume_text = (
            ctx.deps.get("calfkit.resume_text")
            if (
                ctx.delivery_kind == "call"
                and ctx.headers.get(protocol.HDR_ATTEMPT) == "failover"
            )
            else None
        )
        if isinstance(resume_text, str) and resume_text:
            from calfkit_tpu.engine.model_client import ModelSettings

            settings = (settings or ModelSettings()).model_copy(
                update={"resume_text": resume_text}
            )
        started = time.perf_counter()
        try:
            outcome: TurnOutcome = await run_turn(
                model,
                messages,
                tool_defs=[b.tool for b in bindings] + peer_defs,
                output_type=self.output_type,
                settings=settings,
                author=self.name,
                max_output_retries=self.max_output_retries,
            )
        except BaseException as exc:
            if turn_span is not None:
                import asyncio as _asyncio

                turn_span.end(
                    status="cancelled"
                    if isinstance(exc, _asyncio.CancelledError)
                    else "error"
                )
                current_context.reset(turn_token)
            raise
        elapsed_ms = (time.perf_counter() - started) * 1000.0
        if turn_span is not None:
            turn_span.end(
                decode_ms=round(elapsed_ms, 3),
                prompt_tokens=outcome.usage.input_tokens,
                generated_tokens=outcome.usage.output_tokens,
                tool_calls=len(outcome.tool_calls),
            )
            current_context.reset(turn_token)
        facts.append(
            InferenceFact(
                model_name=self.model.model_name,
                decode_ms=elapsed_ms,
                prompt_tokens=outcome.usage.input_tokens,
                generated_tokens=outcome.usage.output_tokens,
            )
        )

        # ---- commit the hop's messages (staged request + model output)
        if staged is not None:
            state.message_history.append(staged)
        state.message_history.extend(outcome.new_messages)
        state.uncommitted_message = None
        state.clear_inflight()

        # what the hop SAID: final-response text only (internal output-retry
        # chatter never surfaces as a step)
        text = step_preamble(outcome.new_messages)
        if text:
            facts.append(Said(text=text, author=self.name))

        # ---- handoff arbitration (whole-response: first valid wins)
        if any(c.tool_name == HANDOFF_TOOL for c in outcome.tool_calls):
            action = self._arbitrate_handoff(ctx, outcome, peer_targets, facts)
            if action is not None:
                return Observed(action=action, facts=facts)
            # no valid handoff: rejections already materialized as retries

        # ---- dispatch or finalize
        if outcome.tool_calls:
            action = self._dispatch_tool_calls(
                ctx, bindings, outcome, facts, peer_targets
            )
            return Observed(action=action, facts=facts)
        return Observed(action=self._final_action(outcome), facts=facts)

    # ------------------------------------------------------------- helpers
    def _tool_results_request(self, ctx: NodeRunContext) -> ModelRequest:
        """The re-entry request: every in-flight call's materialized result,
        in dispatch order (reference: agent.py:662 DeferredToolResults)."""
        state = ctx.state
        parts: list[Any] = []
        for call_id in state.tool_calls:
            result = state.tool_results.get(call_id)
            if result is None:
                call = state.tool_calls[call_id]
                result = RetryPart(
                    content="No result was produced for this tool call.",
                    tool_call_id=call_id,
                    tool_name=call.tool_name,
                )
            parts.append(result)
        if not parts:
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.STRAY_REPLY,
                    f"{self.node_id} re-entered with no in-flight tool calls",
                    node=self.node_id,
                    route=ctx.route,
                )
            )
        return ModelRequest(parts=parts)

    def _render_instructions(self, ctx: NodeRunContext) -> str | None:
        base = self.instructions
        rendered = base(ctx) if callable(base) else base
        temp = ctx.state.temp_instructions
        if temp:
            rendered = f"{rendered}\n\n{temp}" if rendered else temp
        return rendered

    def _guard_reserved_names(self, bindings: list[ToolBinding]) -> None:
        reserved = {MESSAGE_AGENT_TOOL, HANDOFF_TOOL}
        if self.output_type is not str:
            reserved.add(FINAL_RESULT_TOOL)
        for binding in bindings:
            if binding.tool.name in reserved:
                raise NodeFaultError(
                    ErrorReport.build_safe(
                        FaultTypes.LIFECYCLE_ERROR,
                        f"tool name {binding.tool.name!r} is reserved (peer "
                        "capabilities / structured output)",
                        node=self.node_id,
                    )
                )

    def _resolve_peers(
        self, ctx: NodeRunContext
    ) -> tuple[list[Any], dict[str, set[str]]]:
        """Per-turn peer resolution → (tool defs, kind -> allowed names)."""
        if not self.peers:
            return [], {}
        cards = self._agent_cards(ctx)
        defs: list[Any] = []
        targets: dict[str, set[str]] = {}
        for peer in self.peers:
            allowed = {c.name for c in peer.allowed(cards, self.name)}
            if not allowed:
                continue  # no live targets: don't lure the model into a
                # tool that can only be rejected
            defs.append(peer.tool_def(cards, self.name))
            targets.setdefault(peer.kind, set()).update(allowed)
        return defs, targets

    def _agent_cards(self, ctx: NodeRunContext) -> list[AgentCard]:
        view = ctx.resource(AGENTS_VIEW_KEY)
        if view is not None:
            return view.records()
        # no control plane: curated peer names resolve blindly by topic
        # derivation; discover-mode peers need the live view
        if any(getattr(p, "discover", False) for p in self.peers):
            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.CAPABILITY_UNAVAILABLE,
                    f"{self.node_id} uses discover-mode peers but no agents "
                    "view is attached (control plane not running?)",
                    node=self.node_id,
                )
            )
        names = {n for p in self.peers for n in getattr(p, "names", [])}
        return [AgentCard(name=n) for n in sorted(names)]

    def _arbitrate_handoff(
        self,
        ctx: NodeRunContext,
        outcome: TurnOutcome,
        peer_targets: dict[str, set[str]],
        facts: list[Fact],
    ) -> NodeResult | None:
        state = ctx.state
        decision = arbitrate_handoff(
            outcome.tool_calls, peer_targets.get("handoff", set())
        )
        for call in outcome.tool_calls:
            state.tool_calls[call.tool_call_id] = call
        closing: list[Any] = []
        for call_id, stub in decision.stubbed.items():
            call = state.tool_calls[call_id]
            closing.append(
                ToolReturnPart(
                    tool_call_id=call_id, tool_name=call.tool_name, content=stub
                )
            )
            facts.append(
                DeniedCall(
                    tool_call_id=call_id,
                    tool_name=call.tool_name,
                    reason="superseded by handoff",
                )
            )
        for call_id, reason in decision.rejected.items():
            if decision.winner is not None:
                # a later handoff won: close the rejected call in-history so
                # no tool call is left unanswered after the TailCall (real
                # model APIs reject dangling tool_use)
                closing.append(
                    ToolReturnPart(
                        tool_call_id=call_id,
                        tool_name=HANDOFF_TOOL,
                        content=reason,
                    )
                )
            else:
                state.tool_results[call_id] = RetryPart(
                    content=reason,
                    tool_call_id=call_id,
                    tool_name=HANDOFF_TOOL,
                )
            facts.append(
                DeniedCall(
                    tool_call_id=call_id,
                    tool_name=HANDOFF_TOOL,
                    reason="invalid handoff target",
                )
            )
        if decision.winner is None:
            return None  # fall through: rejections loop another model turn
        closing.append(
            ToolReturnPart(
                tool_call_id=decision.winner.tool_call_id,
                tool_name=HANDOFF_TOOL,
                content=f"Handing off to {decision.target}.",
            )
        )
        state.message_history.append(ModelRequest(parts=closing))
        state.clear_inflight()
        facts.append(HandedOff(to_agent=decision.target, from_agent=self.name))
        return TailCall(
            target_topic=protocol.agent_input_topic(decision.target), route="run"
        )

    def _dispatch_tool_calls(
        self,
        ctx: NodeRunContext,
        bindings: list[ToolBinding],
        outcome: TurnOutcome,
        facts: list[Fact],
        peer_targets: dict[str, set[str]] | None = None,
    ) -> NodeResult:
        """Validate each model call and build the Call batch; invalid calls
        become immediate retry results instead of dispatches (reference:
        agent.py:733-932)."""
        state = ctx.state
        peer_targets = peer_targets or {}
        by_name = {b.tool.name: b for b in bindings}
        calls: list[Call] = []
        for tool_call in outcome.tool_calls:
            if tool_call.tool_call_id in state.tool_results:
                continue  # already closed (e.g. rejected handoff)
            state.tool_calls[tool_call.tool_call_id] = tool_call
            if tool_call.tool_name == MESSAGE_AGENT_TOOL:
                peer_call = self._message_agent_call(
                    ctx, tool_call, peer_targets.get("messaging", set()), facts
                )
                if peer_call is not None:
                    calls.append(peer_call)
                continue
            binding = by_name.get(tool_call.tool_name)
            if binding is None:
                state.tool_results[tool_call.tool_call_id] = RetryPart(
                    content=f"Unknown tool {tool_call.tool_name!r}. Available: "
                    f"{sorted(by_name)}",
                    tool_call_id=tool_call.tool_call_id,
                    tool_name=tool_call.tool_name,
                )
                facts.append(
                    DeniedCall(
                        tool_call_id=tool_call.tool_call_id,
                        tool_name=tool_call.tool_name,
                        reason="unknown tool",
                    )
                )
                continue
            try:
                args = tool_call.args_dict()
            except ValueError as exc:
                state.tool_results[tool_call.tool_call_id] = RetryPart(
                    content=f"Malformed arguments for {tool_call.tool_name}: {exc}",
                    tool_call_id=tool_call.tool_call_id,
                    tool_name=tool_call.tool_name,
                )
                facts.append(
                    DeniedCall(
                        tool_call_id=tool_call.tool_call_id,
                        tool_name=tool_call.tool_name,
                        reason=f"malformed arguments: {exc}",
                    )
                )
                continue
            ref = ToolCallRef(
                tool_call_id=tool_call.tool_call_id,
                tool_name=tool_call.tool_name,
                args=args,
            )
            calls.append(
                Call(
                    target_topic=binding.dispatch_topic,
                    route="run",
                    parts=[DataPart(data=ref.model_dump())],
                    tag=tool_call.tool_call_id,
                    marker=ToolCallMarker(
                        tool_call_id=tool_call.tool_call_id,
                        tool_name=tool_call.tool_name,
                    ),
                )
            )
        if not calls:
            # every call was rejected pre-dispatch: absorb this pass's facts
            # (DeniedCall pairs, inference metrics) so they aren't lost, then
            # loop into another model turn on this same hop (bounded)
            ctx.ledger.absorb(facts)
            facts.clear()
            raise _AllCallsRejected()
        return calls if len(calls) > 1 else calls[0]

    def _message_agent_call(
        self,
        ctx: NodeRunContext,
        tool_call: Any,
        allowed: set[str],
        facts: list[Fact],
    ) -> Call | None:
        """Build the isolated-state Call for a model ``message_agent`` call
        (reference: agent.py:540 — isolate_state + degenerate durable
        batch); invalid targets become retries."""
        state = ctx.state
        try:
            args = tool_call.args_dict()
        except ValueError as exc:
            args = None
            reason = f"malformed arguments: {exc}"
        if args is not None:
            target = args.get("agent_name")
            message = args.get("message", "")
            if isinstance(target, str) and target in allowed:
                return Call(
                    target_topic=protocol.agent_input_topic(target),
                    route="run",
                    parts=[TextPart(text=str(message))],
                    tag=tool_call.tool_call_id,
                    marker=ToolCallMarker(
                        tool_call_id=tool_call.tool_call_id,
                        tool_name=MESSAGE_AGENT_TOOL,
                    ),
                    isolate_state=True,
                )
            reason = f"{target!r} is not an available agent"
        state.tool_results[tool_call.tool_call_id] = RetryPart(
            content=f"message_agent failed: {reason}",
            tool_call_id=tool_call.tool_call_id,
            tool_name=MESSAGE_AGENT_TOOL,
        )
        facts.append(
            DeniedCall(
                tool_call_id=tool_call.tool_call_id,
                tool_name=MESSAGE_AGENT_TOOL,
                reason=reason,
            )
        )
        return None

    def _final_action(self, outcome: TurnOutcome) -> ReturnCall:
        output = outcome.output
        if self.output_type is str:
            return ReturnCall(parts=[TextPart(text=output or "")])
        # a structured result keeps the text said alongside it (message-
        # aware preamble: only when the answer rode a final_result call)
        parts: list[Any] = []
        preamble = structured_output_preamble(outcome.new_messages)
        if preamble:
            parts.append(TextPart(text=preamble))
        parts.append(DataPart(data=to_jsonable_python(output)))
        return ReturnCall(parts=parts)


class _AllCallsRejected(Exception):
    """Internal: every model tool call was denied pre-dispatch; the base
    run() loop catches this and runs another turn on the same hop."""


class _TokenTap(ModelClient):
    """Wraps the agent's model so each request streams internally and
    publishes TokenStep batches to the run's root callback topic WHILE the
    turn generates (the per-hop ledger still carries the terminal steps).

    The FIRST delta of each attempt flushes immediately (true TTFT on the
    wire); later deltas batch up to ``_FLUSH_CHARS``.  When the turn runner
    retries (invalid structured output), a retry-boundary token separates
    the attempts so stream consumers don't see two concatenated answers.
    """

    _FLUSH_CHARS = 24
    RETRY_BOUNDARY = "\n[retrying]\n"

    def __init__(self, inner: ModelClient, node: "BaseAgentNodeDef", ctx: Any):
        self._inner = inner
        self._node = node
        self._ctx = ctx
        self._attempts = 0
        # absolute-offset stamping (ISSUE 10): ONLY a RESUMED turn (the
        # backend yielded ResumeOffset) stamps its chunks — the ledger's
        # offset space is run-wide, and a non-resumed turn stamping from
        # 0 would make a multi-turn agent's SECOND turn read as a replay
        # of the first (suppressed as duplicate).  Non-resumed turns
        # emit offset=None and ride the ledger's cumulative law, which
        # carries across turns — the pre-ISSUE-10 behavior.
        self._offset = 0
        self._stamp = False

    @property
    def model_name(self) -> str:
        return self._inner.model_name

    async def _flush(self, buffer: list[str]) -> None:
        if not buffer:
            return
        text = "".join(buffer)
        buffer.clear()
        offset = self._offset if self._stamp else None
        if offset is not None:
            self._offset += len(text)
        from calfkit_tpu.models.step import StepMessage, TokenStep
        from calfkit_tpu.nodes.steps import publish_step_message

        try:
            await publish_step_message(
                self._node.transport,
                self._ctx.root_topic,
                StepMessage(
                    steps=[
                        TokenStep(
                            text=text, author=self._node.name, offset=offset
                        )
                    ],
                    emitter=self._node.emitter,
                ),
                correlation_id=self._ctx.correlation_id,
                task_id=self._ctx.task_id,
            )
        except Exception:  # noqa: BLE001 - token telemetry never faults a run
            pass

    async def request(self, messages, settings=None, params=None):
        from calfkit_tpu.engine.model_client import (
            ResponseDone,
            ResumeOffset,
            TextDelta,
        )

        self._attempts += 1
        buffer: list[str] = []
        self._stamp = False
        self._offset = 0
        if self._attempts > 1:
            await self._flush([self.RETRY_BOUNDARY])
        first = True
        async for event in self._inner.request_stream(messages, settings, params):
            if isinstance(event, ResumeOffset):
                # the backend resumed decode-from-offset: this turn's
                # deltas begin past the already-delivered prefix — only
                # NOW does offset stamping engage (see __init__), and
                # only on the FIRST attempt: an internal output-retry
                # restarts the answer while the ledger already holds
                # attempt 1's deltas, so a re-stamped retry would read
                # as a partial replay and get suppressed mid-text
                if self._attempts == 1:
                    self._stamp = True
                    self._offset = event.chars
            elif isinstance(event, TextDelta):
                buffer.append(event.text)
                if first or sum(len(b) for b in buffer) >= self._FLUSH_CHARS:
                    first = False
                    await self._flush(buffer)
            elif isinstance(event, ResponseDone):
                await self._flush(buffer)
                return event.response
        raise RuntimeError("model stream ended without a terminal response")


class Agent(BaseAgentNodeDef):
    """The durable-conversation agent (per-run state rides the wire)."""


class StatelessAgent(Agent):
    """Alias reserved for the future durable-thread-memory split
    (reference: agent.py:1023-1031 naming)."""
