"""Handler registry: ``@handler(route)`` + per-subclass collection.

Reference: calfkit/_registry.py:64-194 (decorator + ``__init_subclass__``
collection + route-uniqueness enforcement).
"""

from __future__ import annotations

from typing import Any, Callable

from calfkit_tpu.exceptions import RegistryConfigError
from calfkit_tpu.routing import match_chain, validate_route_pattern

_HANDLER_ATTR = "__calfkit_route__"


def handler(route: str) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
    """Mark a method as the body for deliveries whose route matches."""
    validate_route_pattern(route)

    def mark(fn: Callable[..., Any]) -> Callable[..., Any]:
        setattr(fn, _HANDLER_ATTR, route)
        return fn

    return mark


class RegistryMixin:
    """Collects ``@handler`` methods across the subclass MRO.

    A subclass redefining a route overrides its parent's handler for that
    route; two *different* methods on one class claiming the same route is a
    configuration error.
    """

    _route_handlers: dict[str, str]  # route pattern -> method name

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        routes: dict[str, str] = {}
        # walk MRO base-first so subclasses override parents
        for klass in reversed(cls.__mro__):
            own: dict[str, str] = {}
            for attr_name, attr in vars(klass).items():
                route = getattr(attr, _HANDLER_ATTR, None)
                if route is None:
                    continue
                if route in own and own[route] != attr_name:
                    raise RegistryConfigError(
                        f"{klass.__name__}: route {route!r} claimed by both "
                        f"{own[route]!r} and {attr_name!r}"
                    )
                own[route] = attr_name
            routes.update(own)
        cls._route_handlers = routes

    def handlers_for(self, route: str) -> list[Callable[..., Any]]:
        """Bound handler methods matching ``route``, most-specific first —
        the chain-of-responsibility order."""
        chain = match_chain(list(self._route_handlers), route)
        return [getattr(self, self._route_handlers[p]) for p in chain]
