"""Caller liveness leases (ISSUE 10) — the server-side half of failure
recovery.

PR 8 made replica death survivable from the CLIENT side (the gateway
supervises placements and fails over); this module makes CALLER death
survivable from the SERVER side.  Without it an engine keeps decoding for
a caller that died — burning TPU dispatches and HBM pages that live
callers need — and fire-and-forget ``send()`` runs have NO supervisor at
all.  Liveness must be symmetric (DeServe, arXiv:2501.14784: node death
is the normal case, on both ends of a call).

The pieces:

- the **lease**: a caller-minted ``(lease_id, ttl_s)`` pair riding every
  call as the ``x-mesh-lease`` header.  One lease per caller process,
  NOT per run — a caller with 50 outstanding runs beats once, not 50
  times.
- **caller heartbeats**: while any run is outstanding, the client
  publishes compact beats (key = lease id) to the compacted
  ``mesh.caller_liveness`` table (``protocol.CALLER_LIVENESS_TOPIC``),
  reusing the control plane's table machinery.  Stamps ride THE deadline
  clock (:func:`calfkit_tpu.cancellation.wall_clock`), so the chaos
  virtual clock drives lease lapse deterministically.
- the **process-wide beat store** (this module): workers fold the
  liveness table into it (``ControlPlane.attach`` starts the feed); the
  node kernel records each leased call's admission as an implicit beat
  (a delivered call is proof the caller was alive at publish); the
  engine's orphan reaper asks :func:`lease_lapsed` per sweep.
- :data:`current_lease` — a contextvar the node kernel sets from the
  delivery's header, mirroring ``cancellation.current_deadline``, so the
  in-process inference engine registers its runs against the caller's
  lease with no per-layer plumbing.

The lapse law (one copy, shared by the reaper and ``ck leases``):

- a lease we have NEVER seen a beat for is **alive** — fail-safe: the
  store may be cold (liveness feed catching up, no control plane), and
  orphaning a live caller's run is strictly worse than burning a dead
  caller's dispatches for one more TTL;
- a lease is **lapsed** once ``now - last_beat > ttl`` (last_beat is the
  freshest of table beats and admission stamps);
- a **released** lease (the caller tombstoned it on clean close) is
  lapsed immediately: a caller that deliberately left wants its
  outstanding leased runs reaped NOW, not after a TTL of grace.

Everything here is fail-open advisory state, like the cancel tombstones:
a broken feed or an evicted entry only costs wasted work for a dead
caller (or one TTL of grace for a live one), never correctness.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import json
import threading
from collections import OrderedDict
from contextvars import ContextVar

from calfkit_tpu import cancellation

__all__ = [
    "DEFAULT_LEASE_TTL",
    "current_lease",
    "note_beat",
    "note_admission",
    "release_lease",
    "release_generation",
    "lease_lapsed",
    "lease_expiry",
    "lease_age",
    "active_leases",
    "fold_liveness_record",
    "beat_payload",
]

DEFAULT_LEASE_TTL = 15.0  # matches the fleet's heartbeat staleness scale

# the current delivery's caller lease (lease_id, ttl_s), set by the node
# kernel from the x-mesh-lease header for the duration of one delivery —
# None outside any leased delivery (same channel shape as the deadline)
current_lease: "ContextVar[tuple[str, float] | None]" = ContextVar(
    "calfkit_caller_lease", default=None
)

# lease_id -> (last_beat_at, ttl_s), capped.  Eviction is NOT free: an
# evicted lease reads as "never seen = alive" (the fail-safe default),
# which would permanently disable reaping for its runs — so at the cap,
# LONG-LAPSED entries are pruned first (their runs were reaped within a
# TTL of the lapse; entries lapsed for many TTLs carry no live runs to
# protect), and only then does LRU eviction touch entries that may still
# matter.  Dead callers' final beats otherwise accumulate one entry
# each forever — production clusters should also give the
# mesh.caller_liveness topic compact+delete retention, like mesh.traces
# (docs/robustness.md).
_BEAT_CAP = 4096
# a lease lapsed longer than PRUNE_TTLS × its ttl is historical record,
# not live state: every run registered against it was reaped long ago
_PRUNE_TTLS = 32.0
# the stale-prune scan is O(store); running it on EVERY insert while the
# store sits at its cap is O(store²) under fleet-scale caller churn (the
# sim's lease_churn scenario spends 80% of its time there).  Amortize:
# scan at most once per this many over-cap inserts; between scans the
# cap is held by O(1) LRU pops — beats move entries to the end, so the
# LRU front is the oldest-beat (≈ most-lapsed) entry anyway.
_PRUNE_SCAN_EVERY = 256
_scan_countdown = 0
_RELEASED = float("-inf")
_beats: "OrderedDict[str, tuple[float, float]]" = OrderedDict()
_LOCK = threading.Lock()
# bumped on every release: a released lease must reap IMMEDIATELY, but
# the engine's orphan heap only re-checks entries at their registered
# expiry — a generation mismatch (one int compare per scheduler pass)
# tells it to sweep registered runs against the lapse law now
_release_gen = 0


@hotpath
def note_beat(
    lease_id: str, ttl_s: float, at: "float | None" = None
) -> None:
    """Record a caller heartbeat (table fold or admission stamp).  Beats
    only move the lease FORWARD — a stale table record replayed behind a
    fresh admission stamp must not age the lease backward — and a
    RELEASED lease is terminal: the liveness feed is unordered, so the
    caller's final heartbeat may fold AFTER its close() tombstone, and
    resurrecting the lease would un-orphan a deliberately departed
    caller's runs (lease ids are minted fresh per client, never
    reused)."""
    if not lease_id or ttl_s <= 0:
        return
    if at is None:
        at = cancellation.wall_clock()
    with _LOCK:
        prev = _beats.get(lease_id)
        if prev is not None:
            if prev[0] == _RELEASED:
                return  # released is terminal
            if prev[0] > at:
                at = prev[0]
        _beats[lease_id] = (at, ttl_s)
        _beats.move_to_end(lease_id)
        if len(_beats) > _BEAT_CAP:
            # prune the historical dead first (released, or lapsed many
            # TTLs ago): evicting a FRESH entry would read as
            # never-seen = alive and permanently un-reap its runs.  The
            # scan is amortized (see _PRUNE_SCAN_EVERY): between scans
            # the O(1) LRU pop below holds the cap.
            global _scan_countdown
            if _scan_countdown <= 0:
                _scan_countdown = _PRUNE_SCAN_EVERY
                now = cancellation.wall_clock()
                stale = [
                    key
                    for key, (beat, ttl) in _beats.items()
                    if beat == _RELEASED or now - beat > ttl * _PRUNE_TTLS
                ]
                for key in stale:
                    if len(_beats) <= _BEAT_CAP:
                        break
                    del _beats[key]
            else:
                _scan_countdown -= 1
        while len(_beats) > _BEAT_CAP:
            _beats.popitem(last=False)


@hotpath
def note_admission(lease_id: str, ttl_s: float) -> None:
    """A leased call was just delivered: the caller was alive when it
    PUBLISHED — an implicit beat, so a run admitted before the liveness
    feed caught up still gets its full TTL of grace.  But delivery lags
    publish by an unknown delay: a call surfacing from a backlog AFTER
    its caller's lease already lapsed (or was released) must NOT
    resurrect the lease — the publish was at least one TTL ago, which
    is no evidence of life now."""
    if lease_lapsed(lease_id):
        return
    note_beat(lease_id, ttl_s)


def release_lease(lease_id: str) -> None:
    """The caller tombstoned its lease (clean close): outstanding leased
    runs are orphans NOW — no TTL of grace for a deliberate departure."""
    global _release_gen
    if not lease_id:
        return
    with _LOCK:
        ttl = _beats.get(lease_id, (0.0, DEFAULT_LEASE_TTL))[1]
        _beats[lease_id] = (_RELEASED, ttl)
        # released = historical record: park it at the LRU FRONT so the
        # cap's O(1) eviction backstop consumes corpses before it can
        # ever touch a live lease (an evicted LIVE lease reads
        # never-seen = alive forever and permanently un-reaps its runs)
        _beats.move_to_end(lease_id, last=False)
        _release_gen += 1


def release_generation() -> int:
    """Monotonic count of lease releases — the orphan reaper's
    sweep-now signal (one bare int read per scheduler pass)."""
    return _release_gen


@hotpath
def lease_expiry(lease_id: "str | None") -> "float | None":
    """Absolute epoch at which the lease lapses (last_beat + ttl), or
    None for a lease the store has never seen (= alive, fail-safe).  The
    engine's orphan heap keys on this."""
    if not lease_id:
        return None
    with _LOCK:
        entry = _beats.get(lease_id)
    if entry is None:
        return None
    beat_at, ttl = entry
    return beat_at + ttl


@hotpath
def lease_lapsed(lease_id: "str | None", now: "float | None" = None) -> bool:
    """THE lapse law (see module docstring): True only with positive
    evidence — a known lease whose last beat is older than its TTL (or
    was released).  Unknown leases are alive."""
    expiry = lease_expiry(lease_id)
    if expiry is None:
        return False
    if now is None:
        now = cancellation.wall_clock()
    return now > expiry


@hotpath
def lease_age(lease_id: "str | None", now: "float | None" = None) -> "float | None":
    """Seconds since the lease's last beat (None = never seen).  The
    ``ck leases`` rendering read AND the engine's lease-aware shed
    ordering signal (ISSUE 20): under overload, the batch victim with
    the OLDEST beat sheds first — leased-but-silent callers give way
    before actively-beating ones."""
    if not lease_id:
        return None
    with _LOCK:
        entry = _beats.get(lease_id)
    if entry is None:
        return None
    if now is None:
        now = cancellation.wall_clock()
    return max(0.0, now - entry[0])


def active_leases() -> "dict[str, tuple[float, float]]":
    """Snapshot of the beat store: lease_id -> (last_beat_at, ttl_s);
    released leases carry beat_at = -inf."""
    with _LOCK:
        return dict(_beats)


# ------------------------------------------------------------ wire fold
# Beats travel as compact JSON table values keyed by lease id; the
# liveness feed (ControlPlane.attach) folds every record through here.


def beat_payload(lease_id: str, ttl_s: float) -> bytes:
    """The wire form of one caller heartbeat (client side)."""
    return json.dumps(
        {
            "lease_id": lease_id,
            "ttl_s": round(ttl_s, 3),
            "beat_at": cancellation.wall_clock(),
        }
    ).encode("utf-8")


def fold_liveness_record(key: "bytes | str | None", value: bytes) -> None:
    """Fold one ``mesh.caller_liveness`` record into the beat store.
    Tombstones (empty value) release the lease; undecodable records are
    dropped (fail-open — a corrupt beat must never fault the feed)."""
    lease_id = (
        key.decode("utf-8", "replace") if isinstance(key, bytes) else key
    )
    if not value:
        if lease_id:
            release_lease(lease_id)
        return
    try:
        body = json.loads(value)
        beat_at = float(body["beat_at"])
        ttl_s = float(body["ttl_s"])
        lease_id = str(body.get("lease_id") or lease_id or "")
    except (ValueError, KeyError, TypeError):
        return
    note_beat(lease_id, ttl_s, at=beat_at)
