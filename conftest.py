"""Root conftest: paths + JAX virtual-device environment.

Must run before anything imports jax: tests exercise multi-chip sharding on a
virtual 8-device CPU mesh (``xla_force_host_platform_device_count``), per the
repo build contract.  Real-TPU tests opt out via the ``tpu`` marker and are
deselected by default.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this image's sitecustomize registers an 'axon' TPU plugin and pins
# jax.config.jax_platforms — env vars alone don't win; override the config
# directly (safe: runs before any backend initializes)
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass
