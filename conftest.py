"""Root conftest: paths + JAX virtual-device environment.

Must run before anything imports jax: tests exercise multi-chip sharding on a
virtual 8-device CPU mesh (``xla_force_host_platform_device_count``), per the
repo build contract.  Real-TPU tests opt out via the ``tpu`` marker and are
deselected by default.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))


from tests._env import tpu_lane_enabled  # noqa: E402


def pytest_configure(config):
    """With the real-chip lane enabled, a plain ``pytest`` must run the tpu
    lane and ONLY the tpu lane: override the default markexpr (which
    deselects tpu) so the combination can't come up empty, and never send
    the CPU suite at a wedge-prone accelerator backend."""
    if tpu_lane_enabled():
        config.option.markexpr = "tpu"


def pytest_collection_modifyitems(config, items):
    """Belt for the buckle above: with the lane enabled, drop anything
    unmarked even if a caller passed an explicit -m."""
    if not tpu_lane_enabled():
        return
    keep, dropped = [], []
    for item in items:
        (keep if item.get_closest_marker("tpu") else dropped).append(item)
    if dropped:
        config.hook.pytest_deselected(items=dropped)
        items[:] = keep


if tpu_lane_enabled():
    # the real-chip lane (pytest -m tpu): leave the accelerator platform
    # alone so the axon backend can serve the tests
    pass
else:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # this image's sitecustomize registers an 'axon' TPU plugin and pins
    # jax.config.jax_platforms — env vars alone don't win; override the
    # config directly (safe: runs before any backend initializes)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:  # pragma: no cover
        pass
