"""Root conftest: paths + JAX virtual-device environment.

Must run before anything imports jax: tests exercise multi-chip sharding on a
virtual 8-device CPU mesh (``xla_force_host_platform_device_count``), per the
repo build contract.  Real-TPU tests opt out via the ``tpu`` marker and are
deselected by default.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
