"""Headline benchmark: agent-serving decode throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N}

Baseline: 2000 decode tok/s/chip (BASELINE.md north star, stated for
Llama-3-8B TP=8 on v5e-8).  This round measures the TinyLlama-1.1B
architecture (BASELINE configs 2/3: the provider-swap model) under
continuous batching on however many chips are visible; the metric name
carries the exact config so rounds stay comparable.

Uses the persistent XLA compilation cache — the first run on a machine pays
compiles, later runs start hot.
"""

from __future__ import annotations

from calfkit_tpu.effects import no_wallclock

import asyncio
import contextlib
import json
import os
import sys
import time


def _bench_config():
    import jax

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    choice = os.environ.get("CALFKIT_BENCH_CONFIG", "auto")
    if choice not in ("auto", "smoke", "tinyllama", "tinyllama_cpu",
                      "llama8b", "llama8b_int4"):
        raise ValueError(
            f"CALFKIT_BENCH_CONFIG={choice!r} "
            "(want auto | smoke | tinyllama | tinyllama_cpu | llama8b | "
            "llama8b_int4)"
        )
    if choice == "auto":
        choice = "smoke" if platform == "cpu" else "tinyllama"
    if choice == "smoke":
        # offline smoke mode: tiny model, small workload (requests = 4x bs
        # so even the fallback number reflects steady-state batching)
        return dict(
            preset="debug", bs=8, max_seq=256, prefill_chunk=32,
            steps=8, requests=32, new_tokens=32, prompt_len=16,
        )
    if choice == "tinyllama_cpu":
        # CPU-replay shape (VERDICT r3 item 3): the REAL tinyllama
        # architecture with a workload small enough for CPU, so engine /
        # measurement-window changes carry committed evidence even when the
        # chip is wedged.  Same engine code path as the tinyllama config;
        # only batch/requests/token counts shrink.
        return dict(
            preset="tinyllama-1.1b", bs=8, max_seq=256, prefill_chunk=32,
            steps=8, requests=32, new_tokens=16, prompt_len=16,
            quantization="int8",
        )
    if choice == "llama8b":
        # BASELINE north star shape: Llama-3-8B, int8 weights (~8 GB),
        # paged KV (dense at this batch would not fit 16 GB), random
        # int8-shaped params built host-side (no checkpoint in image)
        return dict(
            preset="llama-3-8b", bs=32, max_seq=1024, prefill_chunk=128,
            steps=32, requests=128, new_tokens=128, prompt_len=64,
            quantization="int8", kv_layout="paged", random_quantized=True,
            # 32 slots x 4 pages reserve (64+128+1 tokens) + headroom
            num_kv_pages=32 * 4 + 65,
        )
    if choice == "llama8b_int4":
        # int4 weights (~4 GB): half the int8 weight stream — the freed
        # HBM funds a 2x batch (64 slots) for even better occupancy
        return dict(
            preset="llama-3-8b", bs=64, max_seq=1024, prefill_chunk=128,
            steps=32, requests=256, new_tokens=128, prompt_len=64,
            quantization="int4", kv_layout="paged", random_quantized=True,
            num_kv_pages=64 * 4 + 65,
        )
    return dict(
        # requests = 4x bs so the measured region is steady-state-dominated
        # real continuous batching (admission churn + slot reuse).  The
        # round-2 number used requests=72 at bs=64: the 8-request tail plus
        # ramp put a third of the dispatches in the bottom occupancy
        # quartile (mean occupancy 0.365 on TPU, 0.68 in the CPU replay) —
        # a measurement-window artifact, not engine starvation.  At 4x bs
        # the same engine measures occupancy 1.0 and ~3x the wall tok/s.
        preset="tinyllama-1.1b", bs=64, max_seq=1024, prefill_chunk=128,
        steps=32, requests=256, new_tokens=128, prompt_len=64,
        quantization="int8",  # weight-only: halves the decode HBM stream
    )


# Published per-chip peaks (bf16 TFLOP/s, HBM GB/s) keyed by device_kind
# substring — used ONLY to normalize measured throughput into MFU /
# bandwidth-utilization; unknown kinds (and CPU) report null rather than
# a made-up denominator.
_TPU_PEAKS = {
    "v2": (45.0, 700.0),
    "v3": (123.0, 900.0),
    "v4": (275.0, 1228.0),
    "v5 lite": (197.0, 819.0),
    "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0),
    "v6 lite": (918.0, 1640.0),
    "v6e": (918.0, 1640.0),
}


def _device_peaks() -> "tuple[float, float] | None":
    """(bf16 TFLOP/s, HBM GB/s) for the live chip: the published table
    keyed by device_kind, overridable by ``CALFKIT_DEVICE_PEAKS=
    "<tflops>,<gb_s>"`` so unknown kinds (or a deliberately-normalized
    CPU replay) still get MFU / bandwidth-utilization instead of null —
    the ISSUE 6 satellite: ragged-wave wins must be reported against
    roofline, not just against each other."""
    import jax

    override = os.environ.get("CALFKIT_DEVICE_PEAKS")
    if override:
        try:
            tflops_s, gb_s_s = override.split(",")
            return float(tflops_s), float(gb_s_s)
        except ValueError:
            pass  # malformed override: fall through to the table
    kind = str(getattr(jax.devices()[0], "device_kind", "") or "").lower()
    return next((v for k, v in _TPU_PEAKS.items() if k in kind), None)


@no_wallclock
def _perf_model(
    model, cfg, wall_tps: float, occupancy: float,
    wave_stats: "dict | None" = None,
) -> dict:
    """Model-FLOPs and HBM-traffic per decoded token AND per ragged wave
    (dispatch), and — when the chip's peaks are known (published table or
    $CALFKIT_DEVICE_PEAKS) — MFU and HBM-bandwidth utilization
    (VERDICT r4 item 6: tok/s alone flatters small models; MFU is the
    honest cross-config metric).

    Decode FLOPs/token ≈ 2·params (every weight participates in one MAC)
    + 4·n_layers·d_model·ctx attention score/value FLOPs at mean context.
    Decode HBM bytes/token ≈ weight stream amortized over the effective
    batch + the sequence's own KV read.  ``wave_stats`` (tokens per
    dispatch incl. absorbed prefill, dispatch rate) turns those into the
    analytic per-WAVE numbers the ragged scheduler is judged by: one
    fused dispatch reads the weights once for every token it carries, so
    absorbed prefill tokens amortize the same stream a bifurcated
    schedule paid a second dispatch for."""
    import jax

    kind = str(getattr(jax.devices()[0], "device_kind", "") or "").lower()
    peaks = _device_peaks()
    params = model.param_count
    ctx = cfg["prompt_len"] + cfg["new_tokens"] / 2.0
    attn_flops = 4.0 * model.n_layers * model.d_model * ctx
    flops_per_token = 2.0 * params + attn_flops
    weight_bytes = params * {
        "int8": 1.0, "int4": 0.5,
    }.get(cfg.get("quantization"), 2.0)
    kv_bytes = 2.0 * model.n_layers * model.n_kv_heads * model.head_dim * ctx * 2
    effective_bs = max(cfg["bs"] * max(occupancy, 0.0), 1e-9)
    bytes_per_token = weight_bytes / effective_bs + kv_bytes
    out = {
        "model_params_b": round(params / 1e9, 3),
        "decode_flops_per_token_g": round(flops_per_token / 1e9, 3),
        "decode_hbm_bytes_per_token_m": round(bytes_per_token / 1e6, 3),
        "device_kind": kind or None,
        "mfu": None,
        "hbm_bw_util": None,
    }
    if wave_stats:
        # per-ragged-wave roofline: tokens carried per dispatch (decode +
        # absorbed prefill) × per-token FLOPs, against ONE weight stream
        # per dispatch — the fused wave's arithmetic intensity
        tokens_per_wave = wave_stats.get("tokens_per_dispatch", 0.0)
        if tokens_per_wave:
            wave_flops = tokens_per_wave * flops_per_token
            wave_bytes = weight_bytes + tokens_per_wave * kv_bytes
            out["per_wave"] = {
                "tokens_per_dispatch": round(tokens_per_wave, 2),
                "flops_per_wave_g": round(wave_flops / 1e9, 3),
                "hbm_bytes_per_wave_m": round(wave_bytes / 1e6, 3),
                "arith_intensity_flop_per_byte": round(
                    wave_flops / max(wave_bytes, 1e-9), 2
                ),
                "prefill_absorbed_tokens": wave_stats.get(
                    "prefill_absorbed_tokens", 0
                ),
            }
    if peaks is not None:
        tflops, gb_s = peaks
        out["mfu"] = round(wall_tps * flops_per_token / (tflops * 1e12), 4)
        out["hbm_bw_util"] = round(
            wall_tps * bytes_per_token / (gb_s * 1e9), 4
        )
    return out


async def run() -> dict:
    import jax

    from calfkit_tpu.inference.config import RuntimeConfig, preset
    from calfkit_tpu.inference.engine import InferenceEngine

    cfg = _bench_config()
    n_dev = len(jax.devices())
    model = preset(cfg["preset"], max_seq_len=cfg["max_seq"])
    runtime = RuntimeConfig(
        max_batch_size=cfg["bs"],
        max_seq_len=cfg["max_seq"],
        prefill_chunk=cfg["prefill_chunk"],
        decode_steps_per_dispatch=cfg["steps"],
        tp=1,
        dp=1,
        quantization=cfg.get("quantization"),
        kv_layout=cfg.get("kv_layout", "dense"),
        num_kv_pages=cfg.get("num_kv_pages", 0),
        # chunked admission is the ragged unified lane's substrate
        # (ISSUE 6): the bench measures the default serving path —
        # prefill chunks absorbed into decode dispatches
        chunked_prefill=True,
    )
    params = None
    if cfg.get("random_quantized"):
        # big-model bench without a checkpoint: int8 params built on host
        # (a device-side random init would transiently need the full bf16
        # tree — the whole chip for 8B)
        from calfkit_tpu.inference.quant import random_quantized_params_host

        params = random_quantized_params_host(
            model, bits=4 if cfg.get("quantization") == "int4" else 8
        )
    engine = InferenceEngine(model, runtime, params=params)
    await engine.start()

    # warm every specialization the measured run will touch: each power-of-
    # two prefill-wave size (deterministic sequential batches) + the decode
    # window
    async def _warm(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [5 + i, *range(6, 5 + cfg["prompt_len"])],
            max_new_tokens=cfg["new_tokens"],
        ):
            n += 1
        return n

    for size in (1, 2, 4, 8):
        if size > cfg["bs"]:
            break
        warm = await asyncio.gather(*[_warm(i) for i in range(size)])
        assert all(warm), "warmup produced no tokens"
    # oversubscribe with SHORT generations: waiting admissions + imminent
    # retirements trigger the short decode variant, compiling it outside the
    # measured region at minimal token cost
    async def _warm_short(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [9 + i, *range(6, 5 + cfg["prompt_len"])], max_new_tokens=8
        ):
            n += 1
        return n

    warm = await asyncio.gather(*[_warm_short(i) for i in range(cfg["bs"] + 2)])
    assert all(warm), "oversubscribed warmup produced no tokens"

    stats = engine.stats
    stats.decode_tokens = 0
    stats.decode_time_s = 0.0
    stats.decode_dispatches = 0
    stats.occupancy_sum = 0.0
    stats.occupancy_hist = [0, 0, 0, 0]
    stats.short_dispatches = 0
    # ragged-wave counters reset with the dispatch counters they are
    # divided by — warmup absorption must not inflate the measured
    # tokens_per_dispatch / per_wave roofline
    stats.prefill_absorbed_tokens = 0
    stats.unified_dispatches = 0

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [3 + (i % 41), *range(7, 6 + cfg["prompt_len"])],
            max_new_tokens=cfg["new_tokens"],
        ):
            n += 1
        return n

    started = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(cfg["requests"])])
    wall = time.perf_counter() - started
    # snapshot throughput-phase stats NOW: the TTFT phase below pushes 12
    # deliberately single-stream requests through the same engine, and its
    # occ=1/bs dispatches must not pollute the batching metrics (this was
    # a third of the round-2 "0.365 mean occupancy" mystery)
    decode_tps = stats.tokens_per_second / n_dev
    mean_occupancy = stats.mean_occupancy
    occupancy_hist = list(stats.occupancy_hist)
    short_dispatches = stats.short_dispatches
    wave_stats = {
        "tokens_per_dispatch": stats.mean_tokens_per_dispatch,
        "prefill_absorbed_tokens": stats.prefill_absorbed_tokens,
        "unified_dispatches": stats.unified_dispatches,
        "ragged_waves": engine._ragged,
    }

    # ---- TTFT phase: p50 mesh-msg -> first streamed token through the FULL
    # agent path (client -> mesh -> agent -> engine -> token step -> client)
    ttft_p50_ms, ttft_error, ttft_transport = await _ttft_phase(engine)
    await engine.stop()

    spec_row = await _spec_phase(model, cfg)

    total = sum(counts)
    wall_tps = total / wall / n_dev
    # the 2,000 tok/s/chip bar is STATED for Llama-3-8B TP=8 — comparing a
    # smaller model's throughput against it flatters the number, so any
    # other config reports vs_baseline: null with an explicit note
    is_baseline_model = model.name == "llama-3-8b"
    return {
        "metric": (
            f"decode_tok_s_per_chip[{model.name} bs={cfg['bs']}"
            f"{' ' + cfg['quantization'] if cfg.get('quantization') else ''}"
            f"{' paged-kv' if cfg.get('kv_layout') == 'paged' else ''}"
            f"{' ragged-waves' if wave_stats['ragged_waves'] else ''} "
            f"continuous-batching wall]"
        ),
        "value": round(wall_tps, 1),
        "unit": "tok/s/chip",
        "vs_baseline": (
            round(wall_tps / 2000.0, 3) if is_baseline_model else None
        ),
        **(
            {}
            if is_baseline_model
            else {"vs_baseline_note": "baseline_model_mismatch"}
        ),
        "detail": {
            **({"speculative": spec_row} if spec_row else {}),
            "decode_only_tok_s_per_chip": round(decode_tps, 1),
            "mean_batch_occupancy": round(mean_occupancy, 3),
            # dispatch counts per occupancy quartile [0-25%, .., 75-100%]
            "occupancy_hist": occupancy_hist,
            "short_dispatches": short_dispatches,
            # ragged unified waves (ISSUE 6): whether the fused lane ran,
            # and what each dispatch actually carried
            "ragged_waves": wave_stats["ragged_waves"],
            "prefill_absorbed_tokens": wave_stats["prefill_absorbed_tokens"],
            "unified_dispatches": wave_stats["unified_dispatches"],
            "tokens_per_dispatch": round(
                wave_stats["tokens_per_dispatch"], 2
            ),
            "p50_mesh_to_first_token_ms": ttft_p50_ms,
            "ttft_transport": ttft_transport,
            **({"ttft_error": ttft_error} if ttft_error else {}),
            "requests": cfg["requests"],
            "new_tokens_per_request": cfg["new_tokens"],
            "devices": n_dev,
            "platform": jax.devices()[0].platform,
            **_perf_model(model, cfg, wall_tps, mean_occupancy, wave_stats),
        },
    }


async def _spec_phase(model, cfg) -> dict | None:
    """Speculative-decoding row: a fresh engine at the same model config
    with the n-gram drafter on, driven by agent-shaped (self-repetitive)
    prompts.  Reports measured tokens_per_dispatch and acceptance_rate —
    the speculation win is measured here, never asserted (SPEC_DECODE.json
    carries the host-stub scheduler-level artifact)."""
    import time as _time

    from calfkit_tpu.inference.config import RuntimeConfig, SpecConfig
    from calfkit_tpu.inference.engine import InferenceEngine

    if model.param_count > 2e9:
        # the spec row builds a SECOND engine with fresh random params; at
        # 8B that doubles HBM for an auxiliary detail row — skip (the
        # host-stub SPEC_DECODE.json artifact carries speculation evidence)
        return {"skipped": "model too large for the auxiliary spec row"}
    engine = None
    try:
        runtime = RuntimeConfig(
            max_batch_size=min(8, cfg["bs"]),
            max_seq_len=cfg["max_seq"],
            prefill_chunk=cfg["prefill_chunk"],
            decode_steps_per_dispatch=cfg["steps"],
            quantization=cfg.get("quantization"),
            kv_layout=cfg.get("kv_layout", "dense"),
            num_kv_pages=cfg.get("num_kv_pages", 0),
            speculative=SpecConfig(k=4),
        )
        engine = InferenceEngine(model, runtime)
        await engine.start()
        pattern = [11, 7, 23, 5, 17, 9, 13, 3]
        new_tokens = min(cfg["new_tokens"], 32)

        async def one(i: int) -> int:
            # repeated structure = the n-gram drafter's home turf
            prompt = ([31 + i] + pattern * 3)[: cfg["max_seq"] // 4]
            n = 0
            async for _ in engine.generate(prompt, max_new_tokens=new_tokens):
                n += 1
            return n

        await asyncio.gather(*[one(i) for i in range(4)])  # warm compiles
        from calfkit_tpu.inference.engine import EngineStats

        stats = engine.stats = EngineStats()
        started = _time.perf_counter()
        counts = await asyncio.gather(*[one(i) for i in range(16)])
        wall = _time.perf_counter() - started
        return {
            "drafter": "ngram",
            "k": 4,
            "requests": len(counts),
            "tokens_per_dispatch": round(stats.tokens_per_dispatch, 3),
            "acceptance_rate": round(stats.acceptance_rate, 4),
            "spec_proposed": stats.spec_proposed,
            "spec_accepted": stats.spec_accepted,
            "wall_tok_s": round(sum(counts) / wall, 1),
        }
    except Exception as e:  # noqa: BLE001 - the spec row is auxiliary detail
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        # a leaked engine would keep its scheduler task + a whole second
        # model's HBM alive through the remaining bench phases
        if engine is not None:
            await engine.stop()


class _BenchTokenizer:
    """Renders EVERY generated id as visible text.

    The default ByteTokenizer drops ids outside the byte range, and a
    random-weights model generates mostly such ids — decoded text came out
    empty, no token step was ever streamed, and the round-1 TTFT detail was
    silently null.  TTFT measures pipeline latency, not tokenizer quality,
    so the bench maps ids to text unconditionally.
    """

    pad_id, bos_id, eos_id = 0, 1, 2

    @property
    def vocab_size(self) -> int:
        return 32000

    def encode(self, text: str) -> list[int]:
        return [3 + (b % 250) for b in text.encode("utf-8")]

    def decode(self, ids: list[int]) -> str:
        return " ".join(f"t{i}" for i in ids)


async def _ttft_phase(engine) -> tuple[float | None, str | None, str]:
    """Median client-publish -> first-token latency over the live mesh.

    BASELINE phrases the north star as "Kafka-msg -> first-token": the
    preferred lane is therefore the in-repo ``kafkad`` broker over the
    REAL Kafka wire protocol (worker and client as separate wire
    clients); next the native meshd TCP broker; ANY failure falls through
    to InMemoryMesh — a broken broker spawn must not cost the TTFT
    number, hardware captures can be hours apart.  The returned transport
    label says which lane carried the measurement."""
    notes = []
    try:
        from calfkit_tpu.mesh.kafka_wire import find_kafkad

        if find_kafkad() is not None:
            p50, err = await _ttft_over_kafkad(engine)
            if p50 is not None or err is None:
                return p50, err, "kafkad-wire"
            notes.append(f"kafkad lane failed ({err})")
    except Exception as e:  # noqa: BLE001 - fall through
        notes.append(f"kafkad lane failed ({type(e).__name__}: {e})")
    try:
        from calfkit_tpu.mesh.tcp import find_meshd

        if find_meshd() is not None:
            p50, err = await _ttft_over_meshd(engine)
            if p50 is not None or err is None:
                err = "; ".join(notes + ([err] if err else [])) or None
                return p50, err, "meshd-tcp"
            notes.append(f"meshd lane failed ({err})")
    except Exception as e:  # noqa: BLE001 - fall through
        notes.append(f"meshd lane failed ({type(e).__name__}: {e})")
    from calfkit_tpu.mesh import InMemoryMesh

    p50, err = await _ttft_runs(engine, InMemoryMesh(), None)
    notes and notes.append("fell back to inmemory")
    err = "; ".join(notes + ([err] if err else [])) or None
    return p50, err, "inmemory"


async def _ttft_over_kafkad(engine) -> tuple[float | None, str | None]:
    """Measure over the real Kafka wire protocol: spawn kafkad, run the
    worker and client as separate KafkaWireMesh connections."""
    import contextlib as _ctx

    from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh, spawn_kafkad

    proc = spawn_kafkad(0)
    port = proc.kafkad_port
    try:
        mesh = KafkaWireMesh(f"127.0.0.1:{port}")
        client_mesh = KafkaWireMesh(f"127.0.0.1:{port}")
        await client_mesh.start()
        try:
            return await _ttft_runs(engine, mesh, client_mesh)
        finally:
            await client_mesh.stop()
    finally:
        proc.terminate()
        with _ctx.suppress(Exception):
            proc.wait(timeout=5)


async def _ttft_over_meshd(engine) -> tuple[float | None, str | None]:
    """Spawn a meshd broker on an OS-assigned port and measure over real
    TCP (port 0 → the broker binds and reports it: no probe-then-spawn
    TOCTOU race on busy hosts; r3 advisor)."""
    import contextlib as _ctx

    from calfkit_tpu.mesh.tcp import TcpMesh, spawn_meshd

    proc = spawn_meshd(0)
    port = proc.meshd_port
    try:
        mesh = TcpMesh(f"127.0.0.1:{port}")
        client_mesh = TcpMesh(f"127.0.0.1:{port}")
        await client_mesh.start()
        try:
            return await _ttft_runs(engine, mesh, client_mesh)
        finally:
            await client_mesh.stop()
    finally:
        proc.terminate()
        with _ctx.suppress(Exception):
            proc.wait(timeout=5)


async def _ttft_runs(engine, mesh, client_mesh) -> tuple[float | None, str | None]:
    """Drive 12 single-turn runs (2 warmup) and return (p50_ms, error)."""
    try:
        from calfkit_tpu.client import Client
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        model = JaxLocalModelClient(
            engine=engine, max_new_tokens=8, tokenizer=_BenchTokenizer()
        )
        await model.start()
        agent = Agent("bench_agent", model=model, stream_tokens=True)
        samples: list[float] = []
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(client_mesh or mesh)
            # 2 unmeasured warmup runs absorb the agent-path jit variants
            # (prompt-length buckets the throughput phase never touched)
            for i in range(12):
                t0 = time.perf_counter()
                handle = await client.agent("bench_agent").start(
                    f"ping {i}", timeout=120
                )
                got = False
                async for event in handle.stream():
                    if getattr(getattr(event, "step", None), "kind", "") == "token":
                        if i >= 2:
                            samples.append((time.perf_counter() - t0) * 1000.0)
                        got = True
                        break
                # drain the rest of the run
                if got:
                    with contextlib.suppress(Exception):
                        await handle.result(timeout=120)
            await client.close()
        samples.sort()
        if not samples:
            return None, "no token step observed in any TTFT run"
        return round(samples[len(samples) // 2], 1), None
    except Exception as e:  # noqa: BLE001 - TTFT is auxiliary detail
        import traceback

        traceback.print_exc()
        return None, f"{type(e).__name__}: {e}"


def _inner_main() -> None:
    # honor an explicit JAX_PLATFORMS=cpu even where a sitecustomize pins a
    # TPU plugin platform (this image's axon site does)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


_PROBE_SRC = """
import jax
devs = jax.devices()
import jax.numpy as jnp, numpy as np
x = jnp.ones((128, 128), jnp.bfloat16)
s = float(np.asarray(jnp.float32(x @ x)).sum())
assert s > 0
print("PROBE_OK", devs[0].platform, len(devs))
"""


def _run_sub(env_extra: dict, timeout_s: int, argv=None) -> tuple[int, str, str]:
    """Run a subprocess with a hard timeout; return (rc, stdout, stderr)."""
    import subprocess

    def _text(v) -> str:
        if isinstance(v, bytes):
            return v.decode(errors="replace")
        return v or ""

    env = dict(os.environ, **env_extra)
    try:
        proc = subprocess.run(
            argv or [sys.executable, __file__],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        return proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        return (
            124,
            _text(e.stdout),
            _text(e.stderr) + f"\n[timeout after {timeout_s}s]",
        )


def _probe_accelerator(timeout_s: int = 120) -> tuple[bool, str, str]:
    """Check the accelerator backend is alive, in a killable subprocess.

    A wedged axon/TPU grant makes ``jax.devices()`` HANG (not raise) in this
    image, so the probe must never run in-process.  A hang (rc=124) is not
    retried — the wedge persists for hours and the retry only burns the
    driver's step budget; a fast failure gets one retry for transient
    unavailability.

    Returns (ok, info, status): ``status`` is the structured probe
    verdict the artifact carries when no fresh number exists (ISSUE 6
    satellite — "no number" must be machine-distinguishable from "bad
    number"): ``"wedged"`` = the runtime HUNG (a chip exists but its
    grant is stuck), ``"absent"`` = no accelerator answered at all.
    """
    last = ""
    status = "absent"
    for attempt in range(2):
        rc, out, err = _run_sub(
            {"CALFKIT_BENCH_INNER": "1"},
            timeout_s,
            argv=[sys.executable, "-c", _PROBE_SRC],
        )
        if rc == 0 and "PROBE_OK" in out and "PROBE_OK cpu" not in out:
            return True, out.strip().splitlines()[-1], "ok"
        last = (out + "\n" + err)[-400:]
        if rc == 124:
            status = "wedged"
            break
        if attempt == 1:
            break
        time.sleep(10)
    return False, last, status


_TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_TPU_CACHE.json")


def _git(*args: str) -> str | None:
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)), *args],
            capture_output=True, text=True, timeout=20,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return proc.stdout.strip() if proc.returncode == 0 else None


def _save_tpu_cache(result: dict) -> None:
    if result.get("detail", {}).get("platform") != "tpu":
        return
    try:
        stamped = dict(result)
        stamped["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # the SHA lets a later wedged-chip capture tell whether the cached
        # number still describes the CURRENT inference code
        sha = _git("rev-parse", "HEAD")
        if sha:
            stamped["git_sha"] = sha
        with open(_TPU_CACHE, "w") as f:
            json.dump(stamped, f)
    except OSError:  # cache is best-effort
        pass


def _cache_is_stale_code(cached: dict) -> bool:
    """True when HEAD has touched the inference path since the cached
    capture — the number is then labeled stale-code (a perf regression in
    new code must not hide behind an old cached headline)."""
    sha = cached.get("git_sha")
    if not isinstance(sha, str) or not sha:
        return False  # legacy cache: can't tell; keep prior behavior
    if _git("rev-parse", "HEAD") is None:
        return False  # git itself unavailable: can't tell either way
    # a sha git doesn't know (rebase dropped it, shallow clone) means the
    # capture can't be tied to current code — that is stale, not clean
    if _git("cat-file", "-e", f"{sha}^{{commit}}") is None:
        return True
    changed = _git(
        "diff", "--name-only", sha, "HEAD", "--",
        "calfkit_tpu/inference", "bench.py",
    )
    if changed is None:
        return True  # sha exists but diff failed: cannot certify freshness
    return bool(changed.strip())


def _load_tpu_cache() -> dict | None:
    """The cache file is committed ON PURPOSE: the round-end driver capture
    may land while the chip is wedged, and the labeled last-good number is
    the honest headline then.  Shape-guarded so a hand-edited/legacy file
    can never break main()'s always-one-JSON-line contract."""
    try:
        with open(_TPU_CACHE) as f:
            cached = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(cached, dict) or not isinstance(cached.get("metric"), str):
        return None
    return cached


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> None:
    """Containment wrapper: ALWAYS print one JSON line and exit 0.

    Round-1 failure mode (VERDICT "weak" #2): the axon backend was wedged and
    the bare ``jax.devices()`` call turned the round's perf artifact into a
    traceback.  Strategy: probe the accelerator in a subprocess with a hard
    timeout; run the real bench in a subprocess too (a hang is then bounded);
    on any failure fall back to a CPU smoke run and record the error in the
    JSON instead of dying.
    """
    if os.environ.get("CALFKIT_BENCH_INNER") == "1":
        _inner_main()
        return

    bench_timeout = int(os.environ.get("CALFKIT_BENCH_TIMEOUT", "2400"))
    error = None
    explicit_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    if explicit_cpu:
        # a deliberately-chosen CPU run is a healthy artifact, not a
        # degraded one — status stays "ok"
        ok, info, probe_status = False, "explicit JAX_PLATFORMS=cpu", "ok"
    else:
        ok, info, probe_status = _probe_accelerator()

    if ok:
        rc, out, err = _run_sub({"CALFKIT_BENCH_INNER": "1"}, timeout_s=bench_timeout)
        result = _last_json_line(out)
        if rc == 0 and result is not None:
            result["status"] = "ok"
            _save_tpu_cache(result)
            print(json.dumps(result))
            return
        error = f"accelerator bench failed rc={rc}: {(out + chr(10) + err)[-400:]}"
        # the chip answered the probe but yielded no number (hang OR
        # crash): the artifact must not claim "ok" — "wedged" = chip
        # present but unusable this capture, vs "absent" = no chip
        probe_status = "wedged"
    elif not explicit_cpu:
        error = f"accelerator unavailable: {info}"

    # ---- the chip comes and goes in this image (wedged for most of rounds
    # 1-2): a successful on-hardware run is cached on disk, and when the
    # accelerator is gone at capture time that cached number — clearly
    # labeled with its capture time and the current error — beats reporting
    # a meaningless CPU-smoke value as the round's headline
    if not explicit_cpu:
        cached = _load_tpu_cache()
        if cached is not None:
            # a cache file may carry a machine-readable stale stamp
            # (ISSUE 11 satellite): once a capture is KNOWN bad — taken
            # against a wedged chip, or preceding a code change the sha
            # diff cannot see — the stamp forces the stale path forever,
            # so bench.py can never again report the number as current
            stamped = cached.get("stale_reason")
            stale = bool(stamped) or _cache_is_stale_code(cached)
            label = f" cached@{cached.get('captured_at', '?')}"
            if stale:
                label += " stale-code"
            cached["metric"] = cached["metric"].replace("]", label + "]", 1)
            # structured provenance (ISSUE 6 satellite): "stale" = a
            # number exists but may not describe the current code; else
            # the probe's verdict ("wedged" hung grant / "absent" no
            # chip) says WHY there is no fresh number
            cached["status"] = "stale" if stale else probe_status
            cached["error"] = (
                f"accelerator unavailable at capture; value is the last "
                f"successful on-TPU run"
                + (
                    f" (STALE: {stamped.get('detail', stamped.get('code', 'stamped stale'))})"
                    if isinstance(stamped, dict)
                    else (
                        " (STALE: calfkit_tpu/inference or bench.py "
                        "changed since capture)" if stale else ""
                    )
                )
                + f" | {error}"
            ).strip()
            print(json.dumps(cached))
            return

    # ---- CPU fallback smoke: a real number from the same engine code path
    # (pin the smoke config: an inherited CALFKIT_BENCH_CONFIG=llama8b must
    # not turn the guaranteed-small fallback into an 8B build on CPU)
    rc, out, err = _run_sub(
        {
            "CALFKIT_BENCH_INNER": "1",
            "JAX_PLATFORMS": "cpu",
            "CALFKIT_BENCH_CONFIG": "smoke",
        },
        timeout_s=900,
    )
    result = _last_json_line(out) if rc == 0 else None
    if result is None:
        result = {
            "metric": "decode_tok_s_per_chip[unrunnable]",
            "value": 0.0,
            "unit": "tok/s/chip",
            "vs_baseline": None,
        }
        error = (error or "") + (
            f" | cpu fallback failed rc={rc}: {(out + chr(10) + err)[-400:]}"
        )
    result["status"] = probe_status
    if error:
        result["error"] = error.strip()
        result["metric"] = result["metric"].replace("]", " cpu-fallback]", 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
