"""Recovery behaviors pinned from the round-4 advisor findings.

1. A groupless tap attached to a zero-partition topic must come alive
   when partitions appear (not sleep forever looking started).
2. The heartbeat loop must force a rejoin on persistent transport
   failure (broker restart) instead of exiting silently and leaving the
   consumer fetching heartbeat-less until the session expires.
3. ``spawn_port_reporting`` must honor its deadline even when the child
   writes a partial line with no newline.
"""

from __future__ import annotations

import asyncio
import os
import stat

import pytest

from calfkit_tpu.mesh._native import spawn_port_reporting
from calfkit_tpu.mesh.kafka_wire import _WireConsumer, encode_record_batch
from calfkit_tpu.mesh.transport import Record


class _FakeClient:
    """Stands in for KafkaWireClient: a topic whose partition count is
    mutable after attach."""

    def __init__(self):
        self.partitions: list[int] = []
        self.records: dict[int, list[bytes]] = {}

    async def metadata(self, topics):
        return {
            "brokers": [(0, "127.0.0.1", 0)],
            "topics": {"t": {"error": 0, "partitions": list(self.partitions)}},
        }

    async def list_offsets(self, wants, *, earliest=False):
        return {tp: 0 for tp in wants}

    async def fetch(self, wants, *, max_wait_ms=300, max_bytes=0):
        out = []
        for topic, part, off in wants:
            blobs = self.records.get(part, [])
            blob = b"".join(blobs[off:]) if off < len(blobs) else b""
            out.append((topic, part, 0, blob))
        if not any(blob for *_x, blob in out):
            await asyncio.sleep(0.05)
        return out

    async def close(self):
        pass


class TestTapRevival:
    def test_zero_partition_tap_revives_when_partitions_appear(self):
        async def run() -> None:
            got: list[Record] = []

            async def deliver(record: Record) -> None:
                got.append(record)

            consumer = _WireConsumer(
                "127.0.0.1", 0, ["t"], None, False, deliver
            )
            fake = _FakeClient()
            consumer._client = fake  # type: ignore[assignment]
            consumer.start()
            # subscription reports started despite zero partitions...
            await asyncio.wait_for(consumer.started.wait(), timeout=5)
            assert consumer._positions == {}
            # ...then the topic gains a partition with a record
            fake.partitions = [0]
            fake.records[0] = [encode_record_batch([(b"k", b"late", [])], 1)]
            deadline = asyncio.get_running_loop().time() + 10
            while not got and asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.1)
            await consumer.stop()
            assert got and got[0].value == b"late"

        asyncio.run(run())


class TestPoisonBatch:
    def test_poison_partition_stalls_without_killing_the_consumer(self):
        """A crc-corrupt batch on one partition must not kill the consume
        loop nor block the OTHER partition (review finding r5)."""

        async def run() -> None:
            got: list[Record] = []

            async def deliver(record: Record) -> None:
                got.append(record)

            consumer = _WireConsumer(
                "127.0.0.1", 0, ["t"], None, False, deliver
            )
            fake = _FakeClient()
            fake.partitions = [0, 1]
            poison = bytearray(encode_record_batch([(b"p", b"bad", [])], 1))
            poison[-1] ^= 0xFF  # crc mismatch
            fake.records[0] = [bytes(poison)]
            fake.records[1] = [encode_record_batch([(b"k", b"good", [])], 1)]
            consumer._client = fake  # type: ignore[assignment]
            consumer.start()
            await asyncio.wait_for(consumer.started.wait(), timeout=5)
            deadline = asyncio.get_running_loop().time() + 10
            while not got and asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.05)
            # loop alive, good partition delivered, poison not skipped
            assert [r.value for r in got] == [b"good"]
            assert consumer._positions[("t", 0)] == 0
            assert not consumer._task.done()
            await consumer.stop()

        asyncio.run(run())


class TestHeartbeatRejoin:
    def test_persistent_heartbeat_failure_forces_rejoin(self, monkeypatch):
        async def run() -> None:
            consumer = _WireConsumer(
                "127.0.0.1", 0, ["t"], "g", False, lambda r: None,
                session_timeout_ms=1500,
            )
            consumer._member_id = "m-1"
            consumer._generation = 3

            class _DeadHB:
                def __init__(self, *a, **k):
                    pass

                async def heartbeat(self, *a):
                    raise ConnectionResetError("broker restarted")

                async def close(self):
                    pass

            monkeypatch.setattr(
                "calfkit_tpu.mesh.kafka_wire.KafkaWireClient", _DeadHB
            )
            await asyncio.wait_for(consumer._heartbeat_loop(), timeout=15)
            assert consumer._rejoin.is_set()

        asyncio.run(run())


class TestSpawnDeadline:
    def _script(self, tmp_path, body: str) -> str:
        path = tmp_path / "fake_broker.sh"
        path.write_text("#!/bin/sh\n" + body)
        path.chmod(path.stat().st_mode | stat.S_IEXEC)
        return str(path)

    def test_partial_line_without_newline_hits_deadline(self, tmp_path):
        script = self._script(tmp_path, "printf 'PORT 12'\nsleep 60\n")
        with pytest.raises(TimeoutError, match="did not report"):
            spawn_port_reporting(script, 0, name="fake", timeout=1.5)
        # and the child did not outlive the failure
        assert "fake_broker" not in os.popen("ps -eo args").read()

    def test_line_assembled_across_partial_writes(self, tmp_path):
        script = self._script(
            tmp_path, "printf 'PORT '\nsleep 0.3\necho 4242\nsleep 30\n"
        )
        proc, port = spawn_port_reporting(script, 0, name="fake", timeout=5)
        try:
            assert port == 4242
        finally:
            proc.terminate()
            proc.wait(timeout=5)
