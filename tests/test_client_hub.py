"""Client hub / run-channel / handle semantics, in isolation.

Reference analogs: tests/test_caller_surface_hub.py, test_run_channel.py,
test_wait.py, test_send.py in /root/reference/tests/ — the race-free handle
registration and cancel-safe channel details SURVEY §7 flags as a hard part.
"""

import asyncio
import gc

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.client.hub import (
    Hub,
    InvocationHandle,
    RunCompleted,
    RunFailed,
)
from calfkit_tpu.exceptions import ClientTimeoutError, NodeFaultError
from calfkit_tpu.mesh.transport import Record
from calfkit_tpu.models.error_report import ErrorReport
from calfkit_tpu.models.payload import TextPart
from calfkit_tpu.models.reply import FaultMessage, ReturnMessage
from calfkit_tpu.models.session_context import Envelope
from calfkit_tpu.models.step import AgentMessageStep, StepEvent, StepMessage


def _return_envelope(text: str = "ok") -> Envelope:
    return Envelope(reply=ReturnMessage(parts=[TextPart(text=text)]))


def _fault_envelope(msg: str = "broke") -> Envelope:
    return Envelope(
        reply=FaultMessage(
            report=ErrorReport.build_safe(error_type="calf.node.error", message=msg)
        )
    )


def _record(
    value: bytes, *, correlation: str, wire: str = "envelope", task: str = "t1"
) -> Record:
    return Record(
        topic="client.inbox",
        value=value,
        headers={
            protocol.HDR_CORRELATION: correlation,
            protocol.HDR_TASK: task,
            protocol.HDR_WIRE: wire,
        },
    )


def _step_record(correlation: str, text: str) -> Record:
    message = StepMessage(steps=[AgentMessageStep(text=text)], emitter="agent/a")
    return _record(message.to_wire(), correlation=correlation, wire="step")


class TestRunChannel:
    async def test_result_after_terminal(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        channel.complete(RunCompleted(envelope=_return_envelope("hi"), headers={}))
        result = await handle.result(timeout=1)
        assert result.output == "hi"

    async def test_result_twice_both_succeed(self):
        """The terminal is a future, not a one-shot queue: every await
        observes it."""
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        channel.complete(RunCompleted(envelope=_return_envelope("hi"), headers={}))
        assert (await handle.result(timeout=1)).output == "hi"
        assert (await handle.result(timeout=1)).output == "hi"

    async def test_terminal_is_first_writer_wins(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        channel.complete(RunCompleted(envelope=_return_envelope("first"), headers={}))
        channel.complete(
            RunFailed(report=ErrorReport.build_safe("calf.node.error", "late"))
        )
        handle = InvocationHandle(channel, str)
        assert (await handle.result(timeout=1)).output == "first"

    async def test_timeout_then_late_terminal_still_consumable(self):
        """wait_for is shielded: a timed-out result() must NOT cancel the
        terminal future — a later reply still completes a retry."""
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        with pytest.raises(ClientTimeoutError):
            await handle.result(timeout=0.05)
        channel.complete(RunCompleted(envelope=_return_envelope("late"), headers={}))
        assert (await handle.result(timeout=1)).output == "late"

    async def test_fault_raises_typed_with_report_and_envelope(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        env = _fault_envelope("kaput")
        channel.complete(RunFailed(report=env.reply.report, envelope=env))
        with pytest.raises(NodeFaultError) as exc_info:
            await handle.result(timeout=1)
        assert "kaput" in exc_info.value.report.message
        assert exc_info.value.envelope is env

    async def test_step_overflow_drops_oldest(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        for i in range(1025):  # queue maxsize is 1024
            channel.push_step(
                StepEvent(
                    correlation_id="c1",
                    step=AgentMessageStep(text=f"s{i}"),
                )
            )
        assert channel.steps.qsize() == 1024
        first = channel.steps.get_nowait()
        assert first.step.text == "s1"  # s0 was dropped, newest kept

    async def test_stream_yields_steps_then_result(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        channel.push_step(
            StepEvent(correlation_id="c1", step=AgentMessageStep(text="working"))
        )
        channel.complete(RunCompleted(envelope=_return_envelope("done"), headers={}))
        items = [item async for item in handle.stream(timeout=2)]
        assert items[0].step.text == "working"
        assert items[-1].output == "done"

    async def test_stream_drains_steps_racing_the_terminal(self):
        """Steps enqueued before the terminal must all surface even when
        the terminal is already set when streaming starts."""
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        for i in range(5):
            channel.push_step(
                StepEvent(correlation_id="c1", step=AgentMessageStep(text=f"s{i}"))
            )
        channel.complete(RunCompleted(envelope=_return_envelope("end"), headers={}))
        items = [item async for item in handle.stream(timeout=2)]
        texts = [it.step.text for it in items[:-1]]
        assert texts == [f"s{i}" for i in range(5)]

    async def test_stream_timeout(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        with pytest.raises(ClientTimeoutError):
            async for _ in handle.stream(timeout=0.05):
                pass

    async def test_stream_raises_on_fault(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        handle = InvocationHandle(channel, str)
        channel.complete(
            RunFailed(report=ErrorReport.build_safe("calf.node.error", "mid"))
        )
        with pytest.raises(NodeFaultError):
            async for _ in handle.stream(timeout=1):
                pass


class TestHubDemux:
    async def test_reply_routes_by_correlation(self):
        hub = Hub()
        channel = hub.track("c1", "t1")
        await hub.on_record(
            _record(_return_envelope("routed").to_wire(), correlation="c1")
        )
        terminal = channel.terminal.result()
        assert isinstance(terminal, RunCompleted)

    async def test_step_routes_to_channel_and_taps(self):
        hub = Hub()
        channel = hub.track("c1", "t1")

        class Tap:
            def __init__(self):
                self.events = []

            def push(self, event):
                self.events.append(event)

        tap = Tap()
        hub.add_tap(tap)
        await hub.on_record(_step_record("c1", "hello"))
        assert channel.steps.qsize() == 1
        assert len(tap.events) == 1
        # a foreign run's steps hit the firehose but not this channel
        await hub.on_record(_step_record("OTHER", "other"))
        assert channel.steps.qsize() == 1
        assert len(tap.events) == 2

    async def test_abandoned_handle_is_weakly_dropped(self):
        """The hub holds channels weakly: dropping the handle lets the
        channel die, and late replies for it are ignored without error."""
        hub = Hub()
        channel = hub.track("c-gone", "t1")
        del channel
        gc.collect()
        await hub.on_record(
            _record(_return_envelope("too late").to_wire(), correlation="c-gone")
        )  # must not raise

    async def test_undecodable_reply_dropped_not_crashed(self):
        hub = Hub()
        hub.track("c1", "t1")
        await hub.on_record(_record(b"\x00not json", correlation="c1"))

    async def test_undecodable_step_dropped_not_crashed(self):
        hub = Hub()
        hub.track("c1", "t1")
        await hub.on_record(
            _record(b"\x00not json", correlation="c1", wire="step")
        )

    async def test_terminal_without_reply_is_failure_not_hang(self):
        """An envelope with no reply slot on the inbox must complete the
        run as a typed failure, never leave the caller hanging."""
        hub = Hub()
        channel = hub.track("c1", "t1")
        await hub.on_record(
            _record(Envelope().to_wire(), correlation="c1")
        )
        terminal = channel.terminal.result()
        assert isinstance(terminal, RunFailed)

    async def test_removed_tap_stops_receiving(self):
        hub = Hub()

        class Tap:
            def __init__(self):
                self.events = []

            def push(self, event):
                self.events.append(event)

        tap = Tap()
        hub.add_tap(tap)
        hub.remove_tap(tap)
        hub.remove_tap(tap)  # double-remove is harmless
        await hub.on_record(_step_record("c1", "x"))
        assert tap.events == []
