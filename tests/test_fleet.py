"""Fleet routing unit suite (ISSUE 7).

Policies are pure functions over pre-filtered candidates, so their
distribution and stickiness properties pin here WITHOUT a mesh:
least-loaded vs power-of-two-choices on skewed load, prefix-affinity
stickiness (and its stable fallback when the home replica drains or
goes stale), and the shed-retry exclusion law.  The registry and router
halves run against an in-memory mesh; the end-to-end drain/stale/shed
drills live in tests/test_chaos.py (TestFleetChaos).
"""

import random

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.client import Client
from calfkit_tpu.cli.obs import render_fleet_table
from calfkit_tpu.fleet import (
    FleetRouter,
    LeastLoaded,
    PowerOfTwoChoices,
    PrefixAffinity,
    RandomChoice,
    Replica,
    ReplicaRegistry,
    RouteRequest,
    affinity_key_for,
    parse_replicas,
    resolve_policy,
)
from calfkit_tpu.fleet.selection import (
    lane_of,
    page_aligned_prefix,
    rendezvous_rank,
    stable_hash,
)
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models.records import (
    ControlPlaneRecord,
    ControlPlaneStamp,
    EngineStatsRecord,
)

from tests._chaos import FleetTopology, ServingStubModel, settle, virtual_clock

NOW = 1_700_000_000.0


def _replica(
    instance: str,
    *,
    active: int = 0,
    pending: int = 0,
    agent: str = "svc",
    heartbeat_at: float = NOW,
    ready: bool = True,
    draining: bool = False,
    topic: "str | None" = None,
    ewma: float = 0.0,
) -> Replica:
    node_id = f"agent.{agent}"
    stats = EngineStatsRecord(
        node_id=node_id,
        model_name="m8b",
        instance_id=instance,
        replica_topic=(
            protocol.agent_replica_topic(agent, instance)
            if topic is None
            else topic
        ),
        ready=ready,
        draining=draining,
        active_requests=active,
        pending_requests=pending,
        dispatch_ewma_ms=ewma,
    )
    return Replica(
        key=f"{node_id}@{instance}",
        node_id=node_id,
        instance_id=instance,
        heartbeat_at=heartbeat_at,
        stats=stats,
    )


def _wire(replica: Replica) -> "tuple[str, bytes]":
    record = ControlPlaneRecord(
        stamp=ControlPlaneStamp(
            node_name=replica.node_id,
            node_kind="agent",
            instance_id=replica.instance_id,
            started_at=replica.heartbeat_at,
            heartbeat_at=replica.heartbeat_at,
        ),
        record=replica.stats.model_dump(),
    )
    return replica.key, record.to_wire()


REQ = RouteRequest(agent="svc")


# ---------------------------------------------------------------- selection
class TestSelectionPrimitives:
    def test_lane_of_matches_historical_dispatch_law(self):
        import zlib

        for key in (b"", b"a", b"task-123", b"\x00\xff"):
            assert lane_of(key, 8) == zlib.crc32(key) % 8
        assert lane_of(None, 8) == 0

    def test_stable_hash_is_process_independent(self):
        # pinned value: the router must agree with itself across
        # restarts AND with peer routers — a changed constant here is a
        # fleet-wide affinity reshuffle and must be a conscious decision
        assert stable_hash(b"session-1") == stable_hash(b"session-1")
        assert stable_hash(b"session-1") != stable_hash(b"session-2")
        assert stable_hash(b"session-1", salt=b"a") != stable_hash(
            b"session-1", salt=b"b"
        )

    def test_long_candidate_ids_still_differentiate(self):
        """Review regression: the rendezvous salt used to ride blake2b's
        key parameter, which silently caps at 64 bytes — replica keys
        sharing a ≥64-byte 'agent.<long-name>@' prefix all hashed
        identically and affinity collapsed onto one replica.  Long-named
        fleets must still spread sessions."""
        name = "agent." + "x" * 80 + "@"
        pool = [f"{name}{i:04d}" for i in range(8)]
        homes = {
            rendezvous_rank(f"session-{k}".encode(), pool)[0]
            for k in range(64)
        }
        assert len(homes) > 1, "long replica keys collapsed to one home"

    def test_rendezvous_rank_minimal_disruption(self):
        keys = [f"k{i}".encode() for i in range(64)]
        pool = [f"replica-{c}" for c in "abcde"]
        homes = {k: rendezvous_rank(k, pool)[0] for k in keys}
        # removing ONE candidate moves only the keys homed on it
        smaller = [c for c in pool if c != "replica-c"]
        for k in keys:
            new_home = rendezvous_rank(k, smaller)[0]
            if homes[k] == "replica-c":
                assert new_home != "replica-c"
            else:
                assert new_home == homes[k]

    def test_page_aligned_prefix(self):
        assert page_aligned_prefix("ab", 4) is None
        assert page_aligned_prefix("abcdefgh", 4) == b"abcdefgh"
        # sub-page tail does not change the key, and neither does a
        # GROWING session history past the max_pages head: every turn of
        # one session maps to one affinity key
        assert page_aligned_prefix("abcdefgh-x", 4, max_pages=2) == b"abcdefgh"
        assert page_aligned_prefix(
            "abcdefgh" + "history" * 40, 4, max_pages=2
        ) == b"abcdefgh"
        assert page_aligned_prefix([1, 2, 3], 2) == page_aligned_prefix(
            [1, 2, 7], 2
        )
        assert page_aligned_prefix([1], 2) is None
        assert page_aligned_prefix("abc", 0) is None


# ----------------------------------------------------------------- policies
class TestPolicies:
    def test_least_loaded_picks_global_minimum(self):
        pool = [
            _replica("a", active=3),
            _replica("b", active=1, pending=1),
            _replica("c", active=0, pending=1),
        ]
        assert LeastLoaded().select(pool, REQ).instance_id == "c"

    def test_least_loaded_tie_breaks_on_stable_key(self):
        pool = [_replica("b"), _replica("a")]
        assert LeastLoaded().select(pool, REQ).instance_id == "a"
        assert LeastLoaded().select(list(reversed(pool)), REQ).instance_id == "a"

    def test_empty_candidates(self):
        for policy in (
            LeastLoaded(), PowerOfTwoChoices(), PrefixAffinity(),
            RandomChoice(),
        ):
            assert policy.select([], REQ) is None

    def test_p2c_skewed_load_distribution(self):
        """On a fleet with one hot replica, p2c must (a) send almost
        nothing to the hot one and (b) spread the rest across the cold
        ones rather than herding onto a single global minimum — the
        herd is exactly least-loaded's failure mode between heartbeats."""
        pool = [_replica("hot", active=50)] + [
            _replica(f"cold{i}", active=i % 2) for i in range(8)
        ]
        rng = random.Random(7).random
        policy = PowerOfTwoChoices(rng=rng)
        picks = [policy.select(pool, REQ).instance_id for _ in range(600)]
        hot = picks.count("hot")
        # the hot replica loses every comparison it appears in: picked 0
        assert hot == 0, f"p2c sent {hot} picks to the saturated replica"
        spread = {p for p in picks}
        assert len(spread) >= 6, f"p2c herded: only {spread}"

    def test_least_loaded_vs_p2c_on_skew(self):
        """The documented contrast: least-loaded herds every pick onto
        the single minimum; p2c spreads across the cold majority."""
        pool = [
            _replica("hot", active=50),
            _replica("min", active=0),
            _replica("mid1", active=1),
            _replica("mid2", active=1),
        ]
        ll_picks = {
            LeastLoaded().select(pool, REQ).instance_id for _ in range(50)
        }
        assert ll_picks == {"min"}
        rng = random.Random(3).random
        p2c_picks = {
            PowerOfTwoChoices(rng=rng).select(pool, REQ).instance_id
            for _ in range(200)
        }
        assert "hot" not in p2c_picks
        assert len(p2c_picks) >= 2  # not herded onto "min"

    def test_p2c_two_or_fewer_is_least_loaded(self):
        pool = [_replica("a", active=2), _replica("b", active=1)]
        assert PowerOfTwoChoices().select(pool, REQ).instance_id == "b"
        assert PowerOfTwoChoices().select(pool[:1], REQ).instance_id == "a"

    def test_affinity_stickiness(self):
        pool = [_replica(c) for c in "abcdef"]
        key = affinity_key_for("You are a support agent. " * 16)
        assert key is not None
        req = RouteRequest(agent="svc", affinity_key=key)
        picks = {
            PrefixAffinity().select(pool, req).instance_id
            for _ in range(20)
        }
        assert len(picks) == 1, f"affinity not sticky: {picks}"
        # candidate list ORDER must not matter
        shuffled = list(pool)
        random.Random(1).shuffle(shuffled)
        assert PrefixAffinity().select(shuffled, req).instance_id in picks

    def test_affinity_pick_matches_rendezvous_rank(self):
        """The policy's O(n) max and selection.rendezvous_rank share one
        ordering law — a drift between them would re-home sessions."""
        pool = [_replica(c) for c in "abcdef"]
        keys = [r.key for r in pool]
        for i in range(16):
            key = affinity_key_for(f"sess {i}: " + "y" * 100)
            pick = PrefixAffinity().select(
                pool, RouteRequest(agent="svc", affinity_key=key)
            )
            assert pick.key == rendezvous_rank(key, keys)[0]

    def test_affinity_fallback_when_home_ineligible(self):
        """A draining/stale/excluded home never reaches the candidate
        list; the key's next-ranked replica takes over, stably — and the
        OTHER keys' homes do not move (no fleet-wide reshuffle)."""
        pool = [_replica(c) for c in "abcd"]
        keys = [
            affinity_key_for(f"session {i}: " + "x" * 128) for i in range(32)
        ]
        policy = PrefixAffinity()
        homes = {
            i: policy.select(
                pool, RouteRequest(agent="svc", affinity_key=k)
            ).instance_id
            for i, k in enumerate(keys)
        }
        victim = homes[0]
        survivors = [r for r in pool if r.instance_id != victim]
        for i, k in enumerate(keys):
            moved = policy.select(
                survivors, RouteRequest(agent="svc", affinity_key=k)
            ).instance_id
            if homes[i] == victim:
                assert moved != victim
            else:
                assert moved == homes[i], "unrelated session reshuffled"
        # and the fallback is itself stable
        again = policy.select(
            survivors, RouteRequest(agent="svc", affinity_key=keys[0])
        ).instance_id
        first = policy.select(
            survivors, RouteRequest(agent="svc", affinity_key=keys[0])
        ).instance_id
        assert again == first

    def test_affinity_without_key_uses_load_aware_fallback(self):
        pool = [_replica("a", active=9), _replica("b", active=0)]
        req = RouteRequest(agent="svc", affinity_key=None)
        assert PrefixAffinity().select(pool, req).instance_id == "b"

    def test_short_prompt_has_no_affinity_key(self):
        assert affinity_key_for("hi") is None
        assert affinity_key_for([1, 2, 3], page=16) is None

    def test_resolve_policy_names(self):
        assert isinstance(resolve_policy("least-loaded"), LeastLoaded)
        assert isinstance(resolve_policy("p2c"), PowerOfTwoChoices)
        assert isinstance(resolve_policy("prefix-affinity"), PrefixAffinity)
        assert isinstance(resolve_policy("random"), RandomChoice)
        custom = LeastLoaded()
        assert resolve_policy(custom) is custom
        with pytest.raises(ValueError, match="unknown routing policy"):
            resolve_policy("bogus")


# ----------------------------------------------------------------- registry
class TestReplicaRegistry:
    async def test_per_instance_records_not_collapsed(self):
        """N replicas of ONE node name must all surface — the exact
        read ControlPlaneView's freshest-wins collapse cannot serve."""
        mesh = InMemoryMesh()
        await mesh.start()
        writer = mesh.table_writer(protocol.ENGINE_STATS_TOPIC)
        for replica in (_replica("i1", active=1), _replica("i2", active=2)):
            key, wire = _wire(replica)
            await writer.put(key, wire)
        registry = ReplicaRegistry(mesh)
        await registry.start()
        with virtual_clock(NOW + 1):
            live = registry.replicas(agent="svc")
            assert [r.instance_id for r in live] == ["i1", "i2"]
            assert [r.queue_depth for r in live] == [1, 2]
            assert registry.eligible("svc") == live
        await registry.stop()
        await mesh.stop()

    async def test_eligibility_filters(self):
        mesh = InMemoryMesh()
        await mesh.start()
        writer = mesh.table_writer(protocol.ENGINE_STATS_TOPIC)
        fleet = [
            _replica("ok"),
            _replica("draining", draining=True),
            _replica("unready", ready=False),
            _replica("stale", heartbeat_at=NOW - 60),
            _replica("sharedonly", topic=""),
            _replica("excluded"),
        ]
        for replica in fleet:
            key, wire = _wire(replica)
            await writer.put(key, wire)
        registry = ReplicaRegistry(mesh, stale_after=15.0)
        await registry.start()
        with virtual_clock(NOW + 1):
            assert len(registry.replicas(agent="svc")) == 6  # render view
            eligible = registry.eligible("svc", exclude={"excluded"})
            assert [r.instance_id for r in eligible] == ["ok"]
            # the stale replica re-advertising restores eligibility
            key, wire = _wire(_replica("stale", heartbeat_at=NOW + 1))
            await writer.put(key, wire)
            assert [
                r.instance_id
                for r in registry.eligible("svc", exclude={"excluded"})
            ] == ["ok", "stale"]
        await registry.stop()
        await mesh.stop()

    async def test_undecodable_records_skipped(self):
        mesh = InMemoryMesh()
        await mesh.start()
        writer = mesh.table_writer(protocol.ENGINE_STATS_TOPIC)
        await writer.put("garbage", b"\xff not json")
        key, wire = _wire(_replica("ok"))
        await writer.put(key, wire)
        registry = ReplicaRegistry(mesh)
        await registry.start()
        with virtual_clock(NOW + 1):
            assert [r.instance_id for r in registry.replicas()] == ["ok"]
        await registry.stop()
        await mesh.stop()


# ------------------------------------------------------------------- router
class TestFleetRouter:
    async def test_routes_to_policy_pick_and_falls_back_shared(self):
        with virtual_clock(NOW):
            mesh = InMemoryMesh()
            await mesh.start()
            writer = mesh.table_writer(protocol.ENGINE_STATS_TOPIC)
            router = FleetRouter(mesh, "least-loaded")
            # zero replicas advertised: shared-topic fallback
            route = await router.route("svc")
            assert route.topic == protocol.agent_input_topic("svc")
            assert route.replica is None
            key, wire = _wire(_replica("i1", active=0))
            await writer.put(key, wire)
            key, wire = _wire(_replica("i2", active=4))
            await writer.put(key, wire)
            route = await router.route("svc")
            assert route.instance_id == "i1"
            assert route.topic == protocol.agent_replica_topic("svc", "i1")
            # excluding the pick moves to the next replica; excluding
            # everything falls back to the shared topic
            route = await router.route("svc", exclude={"i1"})
            assert route.instance_id == "i2"
            route = await router.route("svc", exclude={"i1", "i2"})
            assert route.replica is None
            await router.stop()
            await mesh.stop()

    async def test_concurrent_first_routes_start_registry_once(self):
        """Review regression: N concurrent FIRST route() calls must
        single-flight the registry start — unguarded, each would start
        its own table reader (leaked broker clients/pumps on a real
        transport)."""
        import asyncio

        starts = []

        class _CountingMesh(InMemoryMesh):
            def table_reader(self, topic):
                inner = super().table_reader(topic)

                class _Reader:
                    async def start(self, *, timeout=30.0):
                        starts.append(topic)
                        await asyncio.sleep(0)  # widen the race window
                        await inner.start(timeout=timeout)

                    def __getattr__(self, name):
                        return getattr(inner, name)

                return _Reader()

        with virtual_clock(NOW):
            mesh = _CountingMesh()
            await mesh.start()
            router = FleetRouter(mesh)
            routes = await asyncio.gather(
                *[router.route("svc") for _ in range(8)]
            )
            assert len(starts) == 1, f"registry started {len(starts)} times"
            assert all(
                r.topic == protocol.agent_input_topic("svc") for r in routes
            )
            await router.stop()
            await mesh.stop()

    async def test_router_failure_degrades_to_shared_topic(self):
        class _BrokenReader:
            async def start(self, *, timeout=30.0):
                raise RuntimeError("directory unavailable")

        class _BrokenMesh(InMemoryMesh):
            def table_reader(self, topic):
                return _BrokenReader()

        mesh = _BrokenMesh()
        await mesh.start()
        router = FleetRouter(mesh)
        route = await router.route("svc")
        assert route.topic == protocol.agent_input_topic("svc")
        # the known-broken directory is not re-paid per call
        route = await router.route("svc")
        assert route.replica is None
        await mesh.stop()

    async def test_execute_retries_shed_on_different_replica_unit(self):
        """The exclusion law at the gateway level, without engines: a
        fleet-routed execute() whose first attempt faults OVERLOADED
        must exclude the shed source on the retry — the second attempt's
        call lands on the OTHER replica's topic."""
        from calfkit_tpu.client.caller import RetryPolicy

        with virtual_clock(NOW):
            mesh = InMemoryMesh()
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                # the low replica sheds at the engine seam: its model
                # raises the typed overload error
                from calfkit_tpu.exceptions import EngineOverloadedError

                async def shed(messages, settings=None, params=None):
                    raise EngineOverloadedError(
                        "synthetic shed", lane="short", pending=9, limit=1
                    )

                models[low].request = shed
                router = FleetRouter(
                    mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(mesh, router=router)
                await router.start()
                # boot adverts say ready=False; wait for the first
                # post-boot heartbeat so both replicas are routable
                await settle(
                    lambda: len(router.registry.eligible("svc")) == 2,
                    message="replicas never became eligible",
                )
                result = await client.agent("svc").execute(
                    "hello",
                    timeout=10,
                    retry=RetryPolicy(attempts=3, base_delay=0.01),
                )
                other = 1 - low
                assert result.output == f"r{other}"
                assert models[other].replies == 1
                # the shed source was tried once, then excluded
                assert fleet.calls_delivered(low) == 1
                assert fleet.calls_delivered(other) == 1
                await client.close()
            await mesh.stop()


# ----------------------------------------------------------------- ck fleet
class TestFleetCli:
    def test_render_fleet_table_verdicts(self):
        fleet = [
            _replica("aaaa", active=2, pending=1),
            _replica("bbbb", draining=True),
            _replica("cccc", heartbeat_at=NOW - 120),
            _replica("dddd", ready=False),
            _replica("eeee", topic=""),
        ]
        out = render_fleet_table(fleet, stale_after=15.0, now=NOW + 1)
        lines = out.splitlines()
        assert lines[0].startswith("MODEL")
        by_instance = {line.split()[2]: line for line in lines[1:]}
        assert " yes" in by_instance["aaaa"]
        assert by_instance["aaaa"].split()[7] == "3"  # DEPTH = active+pending
        assert " drain" in by_instance["bbbb"]
        # the dead-placement law (ISSUE 9) outranks the routing verdict:
        # stale and unready-without-drain replicas render as DEAD (runs
        # placed there are being failed over), with the heartbeat age
        # visible in the HB AGE S column
        assert " dead(stale)" in by_instance["cccc"]
        assert by_instance["cccc"].split()[6] == "121.0"  # HB age
        assert " dead(unready)" in by_instance["dddd"]
        assert " shared-only" in by_instance["eeee"]

    def test_render_fleet_table_empty(self):
        assert "no advertised replicas" in render_fleet_table(
            [], stale_after=15.0, now=NOW
        )

    def test_parse_replicas_round_trip(self):
        key, wire = _wire(_replica("i1", active=2))
        out = parse_replicas({key: wire})
        assert len(out) == 1
        assert out[0].key == key
        assert out[0].queue_depth == 2
        assert out[0].agent_name == "svc"

    def test_pinned_instance_id_makes_replica_topic_stable(self):
        """Clusters where topics must PRE-exist (provisioning disabled)
        need the replica topic knowable before boot: a pinned
        instance_id yields a deterministic topic, across 'restarts'
        (re-construction), and illegal ids fail loudly."""
        from calfkit_tpu.nodes import Agent

        a = Agent("svc", model=ServingStubModel(), instance_id="r0")
        b = Agent("svc", model=ServingStubModel(), instance_id="r0")
        expected = protocol.agent_replica_topic("svc", "r0")
        assert a.replica_topic() == b.replica_topic() == expected
        assert expected in a.input_topics()
        with pytest.raises(ValueError, match="instance_id"):
            Agent("svc", model=ServingStubModel(), instance_id="bad id!")

    async def test_fleet_command_reads_live_topology(self):
        """The `ck fleet` read path against a live in-memory fleet: one
        row per replica, both marked routable."""
        with virtual_clock(NOW):
            mesh = InMemoryMesh()
            models = [ServingStubModel() for _ in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                reader = mesh.table_reader(protocol.ENGINE_STATS_TOPIC)
                await reader.start()
                # the first advert lands mid-boot (ready=False by
                # design: a booting worker must not draw traffic); the
                # first post-boot heartbeat tick flips it
                await settle(
                    lambda: all(
                        r.stats.ready
                        for r in parse_replicas(reader.items())
                    )
                    and len(parse_replicas(reader.items())) == 2,
                    message="replicas never advertised ready",
                )
                replicas = parse_replicas(reader.items())
                await reader.stop()
                out = render_fleet_table(
                    replicas, stale_after=fleet.config.stale_after
                )
                assert out.count(" yes") == 2
                for i in range(2):
                    assert fleet.instance_id(i) in out
            await mesh.stop()


# ---------------------------------------------------------- failure recovery
class TestFailureRecoveryLaws:
    """Pure-law units for ISSUE 9: the dead-placement verdict, the
    stream-resume dedupe ledger, RetryPolicy jitter bounds, and the
    registry's version-counter fast path."""

    def test_placement_verdict_law(self):
        from calfkit_tpu.fleet import placement_verdict

        alive = _replica("a1")
        assert placement_verdict(alive, stale_after=15.0, now=NOW) == "alive"
        # gone: the advert left the table without a drain
        assert (
            placement_verdict(None, stale_after=15.0, now=NOW) == "dead:gone"
        )
        # stale: heartbeat lapsed past stale_after on the wall_clock seam
        stale = _replica("a2", heartbeat_at=NOW - 20)
        assert (
            placement_verdict(stale, stale_after=15.0, now=NOW)
            == "dead:stale"
        )
        # unready WITHOUT draining: the wedge watchdog's signature
        wedged = _replica("a3", ready=False)
        assert (
            placement_verdict(wedged, stale_after=15.0, now=NOW)
            == "dead:unready"
        )
        # draining is ALIVE: in-flight work finishes by contract — even
        # when the drain also flipped readiness
        draining = _replica("a4", ready=False, draining=True)
        assert (
            placement_verdict(draining, stale_after=15.0, now=NOW) == "alive"
        )

    def test_stream_ledger_contiguity(self):
        from calfkit_tpu.fleet import StreamLedger

        ledger = StreamLedger()
        # first attempt: everything is fresh
        assert ledger.filter("alpha ") == "alpha "
        assert ledger.filter("beta ") == "beta "
        assert ledger.delivered == len("alpha beta ")
        # failover: the replay suppresses exactly the delivered prefix,
        # across chunk boundaries that do not line up with the original
        ledger.begin_attempt()
        assert ledger.filter("alp") == ""
        assert ledger.filter("ha bet") == ""
        assert ledger.filter("a gamma ") == "gamma "
        assert ledger.filter("delta") == "delta"
        assert ledger.text == "alpha beta gamma delta"
        # a second failover mid-replay: the cursor resets again
        ledger.begin_attempt()
        assert ledger.filter("alpha beta gamma delta!") == "!"

    def test_retry_delay_jitter_bounds(self):
        """RetryPolicy.delay(attempt) must stay in
        [raw * (1 - jitter), raw] with raw = min(base * mult^attempt,
        max_delay) — a delay outside the band either hammers (too
        short) or wastes deadline budget (too long)."""
        from calfkit_tpu.client.caller import RetryPolicy

        policy = RetryPolicy(
            attempts=5, base_delay=0.05, max_delay=2.0, multiplier=2.0,
            jitter=0.5,
        )
        # rng = 0 draws NO jitter (the full raw delay); rng -> 1 removes
        # the full jitter fraction
        for attempt in range(6):
            raw = min(0.05 * 2.0**attempt, 2.0)
            full = RetryPolicy(
                attempts=5, base_delay=0.05, jitter=0.5, rng=lambda: 0.0
            ).delay(attempt)
            floor = RetryPolicy(
                attempts=5, base_delay=0.05, jitter=0.5,
                rng=lambda: 0.9999999,
            ).delay(attempt)
            assert abs(full - raw) < 1e-12
            assert raw * 0.5 - 1e-9 <= floor <= raw
        # deterministic rng: the whole schedule pins
        rng = random.Random(7).random
        got = [
            round(
                RetryPolicy(
                    attempts=5, base_delay=0.05, jitter=0.5, rng=rng
                ).delay(a),
                6,
            )
            for a in range(4)
        ]
        rng2 = random.Random(7).random
        expected = [
            round(
                min(0.05 * 2.0**a, 2.0) * (1.0 - 0.5 * rng2()), 6
            )
            for a in range(4)
        ]
        assert got == expected
        # the cap: delays never exceed max_delay
        assert policy.delay(50) <= 2.0

    async def test_registry_version_fast_path(self, monkeypatch):
        """The O(1) no-change path (ISSUE 9 satellite): with a version-
        counting reader, an unchanged table re-parses NOTHING — and a
        heartbeat rewrite is detected by the counter, not a byte scan."""
        from calfkit_tpu.fleet import registry as registry_mod

        calls = {"n": 0}
        real = registry_mod.parse_replicas

        def counting(items):
            calls["n"] += 1
            return real(items)

        monkeypatch.setattr(registry_mod, "parse_replicas", counting)
        with virtual_clock(NOW):
            mesh = InMemoryMesh()
            await mesh.start()
            writer = mesh.table_writer(protocol.ENGINE_STATS_TOPIC)
            key, wire = _wire(_replica("i1"))
            await writer.put(key, wire)
            registry = ReplicaRegistry(mesh)
            await registry.start()
            assert registry._reader.version is not None
            assert len(registry.eligible("svc")) == 1
            first = calls["n"]
            assert first == 1
            for _ in range(50):
                registry.eligible("svc")
            assert calls["n"] == first, "unchanged table was re-parsed"
            # a rewrite (same key, fresh heartbeat) bumps the version and
            # re-parses exactly once
            key, wire = _wire(_replica("i1", active=3))
            await writer.put(key, wire)
            assert registry.eligible("svc")[0].stats.active_requests == 3
            assert calls["n"] == first + 1
            # by-key lookup rides the same cache (the failover probe)
            assert registry.replica(key) is not None
            assert registry.replica("agent.svc@nope") is None
            assert calls["n"] == first + 1
            await registry.stop()
            await mesh.stop()

    async def test_exclusion_accumulates_across_shed_and_failover(self):
        """Mixed recovery on one call (ISSUE 9 satellite): attempt 1
        sheds (typed OVERLOADED -> excluded), attempt 2 lands on a
        replica that is already dead (killed -> placement dead ->
        excluded), attempt 3 completes on the last replica.  The
        exclusion set must ACCUMULATE across both mechanisms — neither
        the shed source nor the corpse is ever re-picked."""
        from calfkit_tpu.client.caller import RetryPolicy
        from calfkit_tpu.exceptions import EngineOverloadedError
        from calfkit_tpu.fleet import FailoverPolicy, FleetRouter

        with virtual_clock(NOW) as clock:
            mesh = InMemoryMesh()
            models = [ServingStubModel(text=f"r{i}") for i in range(3)]
            async with FleetTopology(mesh, models) as fleet:
                order = sorted(range(3), key=fleet.replica_key)
                shedder, corpse, survivor = order

                async def shed(messages, settings=None, params=None):
                    raise EngineOverloadedError(
                        "synthetic shed", lane="short", pending=9, limit=1
                    )

                models[shedder].request = shed
                router = FleetRouter(
                    mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(
                    mesh, router=router,
                    failover=FailoverPolicy(
                        probe_interval=0.02, max_failovers=2
                    ),
                )
                await router.start()
                await settle(
                    lambda: len(router.registry.eligible("svc")) == 3,
                    message="fleet never became routable",
                )
                # the corpse dies BEFORE the call: its advert is still
                # fresh, so attempt 2 places onto it after the shed
                fleet.kill(corpse)
                call = __import__("asyncio").create_task(
                    client.agent("svc").execute(
                        "mixed", timeout=30,
                        retry=RetryPolicy(attempts=3, base_delay=0.01),
                    )
                )
                # attempt 1 -> shedder (lowest key) sheds; attempt 2 ->
                # corpse (next key) buffers in the dead gate
                await settle(
                    lambda: fleet.transports[corpse].dead
                    and any(
                        g.buffered for g in fleet.transports[corpse]._gates
                    ),
                    message="attempt 2 never targeted the corpse",
                )
                clock.advance(fleet.config.stale_after + 1)
                result = await call
                assert result.output == f"r{survivor}"
                assert fleet.calls_delivered(shedder) == 1
                assert fleet.calls_delivered(corpse) == 0
                assert fleet.calls_delivered(survivor) == 1
                # the final placement was marked as a failover re-dispatch
                assert fleet.agents[survivor]._failover_requests == 1
                await client.close()
            await mesh.stop()


class TestCallerLivenessLaws:
    """Pure-law units for ISSUE 10: the EWMA dispatch-latency fold and
    its many-router tiebreak, the offset-exact stream-ledger law that
    decode-from-offset resume rides, the lease header wire forms, and
    the typed ``mesh.orphaned`` fault classification."""

    def test_ewma_fold(self):
        from calfkit_tpu.inference.engine import EngineStats

        stats = EngineStats()
        # first sample primes the fold directly (no zero-start bias)
        stats.note_dispatch_ewma(10.0)
        assert stats.dispatch_ewma_ms == 10.0
        # the fold: alpha * sample + (1 - alpha) * prev
        a = EngineStats.EWMA_ALPHA
        stats.note_dispatch_ewma(20.0)
        assert stats.dispatch_ewma_ms == pytest.approx(
            a * 20.0 + (1 - a) * 10.0
        )
        prev = stats.dispatch_ewma_ms
        stats.note_dispatch_ewma(20.0)
        assert stats.dispatch_ewma_ms == pytest.approx(
            a * 20.0 + (1 - a) * prev
        )
        # a constant stream converges toward the constant
        for _ in range(200):
            stats.note_dispatch_ewma(20.0)
        assert stats.dispatch_ewma_ms == pytest.approx(20.0, abs=1e-6)
        # the EWMA is a fold, NOT a window counter: it must never enter
        # the delta machinery (a windowed EWMA delta is meaningless)
        assert "dispatch_ewma_ms" not in EngineStats._COUNTER_FIELDS

    def test_ewma_breaks_depth_ties(self):
        """Depth-tied candidates rank by EWMA latency; depth still
        dominates (a slow-but-empty replica beats a fast-but-deep one);
        EWMA ties fall through to the stable key."""
        fast = _replica("b-fast", active=2, ewma=3.0)
        slow = _replica("a-slow", active=2, ewma=9.0)
        assert LeastLoaded().select([slow, fast], REQ) is fast
        # two candidates: PowerOfTwoChoices degenerates to the same law
        assert PowerOfTwoChoices().select([slow, fast], REQ) is fast
        # depth dominates the tiebreak
        deep_fast = _replica("c-deep", active=5, ewma=0.5)
        assert LeastLoaded().select([deep_fast, slow], REQ) is slow
        # EWMA tie (e.g. two pre-EWMA adverts at 0.0) → stable key
        x = _replica("x1", active=1)
        y = _replica("y1", active=1)
        assert LeastLoaded().select([y, x], REQ) is x
        # 0.0 = NO SIGNAL and ranks LAST among ties: a mixed fleet
        # (rolling upgrade, never-dispatched engine) must not herd all
        # tied traffic onto the one replica with no latency evidence
        unknown = _replica("a-unknown", active=2, ewma=0.0)
        assert LeastLoaded().select([unknown, slow], REQ) is slow
        # p2c over n>2 with a scripted rng: samples 0 and 1, keeps the
        # lower-EWMA one of the pair
        draws = iter([0.0, 0.0])  # i=0; j=0 -> bumped to 1
        policy = PowerOfTwoChoices(rng=lambda: next(draws))
        third = _replica("z9", active=2, ewma=1.0)
        picked = policy.select([slow, fast, third], REQ)
        assert picked is fast

    def test_stream_ledger_offsets(self):
        """The offset-exact dedupe law (ISSUE 10): a decode-from-offset
        RESUME stamps its first chunk at the delivered-prefix length and
        nothing is suppressed; a re-generating attempt stamping from 0
        has exactly the replayed prefix trimmed; unstamped chunks fall
        back to the cumulative law."""
        from calfkit_tpu.fleet import StreamLedger

        # resumed attempt: offset picks up where delivery stopped
        ledger = StreamLedger()
        assert ledger.filter("alpha ", 0) == "alpha "
        ledger.begin_attempt()
        assert ledger.filter("beta", len("alpha ")) == "beta"
        assert ledger.text == "alpha beta"
        # follow-up chunks of the resumed attempt keep flowing, stamped
        # or not (the cumulative cursor advanced with the offset)
        assert ledger.filter(" gamma") == " gamma"
        # re-generating attempt: stamped from zero, prefix suppressed
        ledger2 = StreamLedger()
        assert ledger2.filter("one two ", 0) == "one two "
        ledger2.begin_attempt()
        assert ledger2.filter("one ", 0) == ""
        assert ledger2.filter("two three", 4) == "three"
        assert ledger2.text == "one two three"

    def test_lease_header_wire_forms(self):
        lease = protocol.format_lease("abcd1234", 12.5)
        assert protocol.parse_lease(lease) == ("abcd1234", 12.5)
        assert protocol.parse_lease(lease.encode()) == ("abcd1234", 12.5)
        # malformed degrades to un-leased, never faults
        for bad in (None, "", "noseparator", ":5.0", "x:", "x:nan",
                    "x:inf", "x:-1", "x:0", b"\xff\xfe"):
            assert protocol.parse_lease(bad) is None

    def test_orphaned_fault_is_typed_and_not_retriable(self):
        from calfkit_tpu.exceptions import (
            RETRIABLE_FAULT_TYPES,
            RunOrphanedError,
            error_type_for,
            exception_for,
        )

        assert error_type_for(RunOrphanedError("x")) == "mesh.orphaned"
        assert exception_for("mesh.orphaned") is RunOrphanedError
        # NOT retriable: there is nobody left to answer
        assert "mesh.orphaned" not in RETRIABLE_FAULT_TYPES

    def test_render_leases_table(self):
        import json

        from calfkit_tpu.cli.obs import render_leases_table

        items = {
            "lease-live": json.dumps(
                {"lease_id": "lease-live", "ttl_s": 10.0,
                 "beat_at": NOW - 3.0}
            ).encode(),
            "lease-dead": json.dumps(
                {"lease_id": "lease-dead", "ttl_s": 5.0,
                 "beat_at": NOW - 60.0}
            ).encode(),
            "lease-bad": b"not json",
        }
        table = render_leases_table(items, now=NOW)
        lines = table.splitlines()
        assert lines[0].split() == ["LEASE", "BEAT", "AGE", "S", "TTL",
                                    "S", "VERDICT"]
        by_lease = {line.split()[0]: line for line in lines[1:]}
        assert "live" in by_lease["lease-live"]
        assert "lapsed" in by_lease["lease-dead"]
        assert "undecodable" in by_lease["lease-bad"]
        assert "no caller leases" in render_leases_table({})
