"""Native Kafka wire client + kafkad broker (VERDICT r3 item 4: the
"real broker" lane, in-image).  The transport-contract suite runs
KafkaWireMesh through the shared semantics tests; this file covers the
wire layer itself — codec vectors, RecordBatch round trips, the range
assignor — and the group-coordination behaviors a contract test can't
see (rebalance splits, takeover, commit-resume), plus a full agent
round trip over the wire mesh.

Reference anchor: tests/integration/ + Makefile test-kafka (the
reference's Redpanda lane); here the broker is the in-repo
``native/bin/kafkad`` speaking the same wire format.
"""

import asyncio

import pytest

from calfkit_tpu.mesh.kafka_wire import (
    KafkaWireClient,
    KafkaWireMesh,
    crc32c,
    decode_record_batches,
    encode_record_batch,
    find_kafkad,
    murmur2,
    partition_for,
    range_assign,
    spawn_kafkad,
)

pytestmark = pytest.mark.skipif(
    find_kafkad() is None, reason="kafkad not built (make -C native)"
)


@pytest.fixture(scope="module")
def broker_port():
    proc = spawn_kafkad(0)
    yield proc.kafkad_port
    proc.terminate()
    proc.wait(timeout=5)


class TestWireCodec:
    def test_crc32c_vector(self):
        # the canonical CRC-32C check value
        assert crc32c(b"123456789") == 0xE3069283

    def test_murmur2_vectors(self):
        # librdkafka rdmurmur2.c unittest vectors (Java-compatible)
        assert murmur2(b"kafka") == 0xD067CF64
        assert murmur2(b"") == 0x106E08D9
        assert murmur2(b"1234") == 0x9FC97B14
        assert murmur2(b"giberish123456789") == 0x8F552B0C

    def test_keyed_partitioning_is_deterministic(self):
        counter = [0]
        a = partition_for(b"run-42", 16, counter)
        b = partition_for(b"run-42", 16, counter)
        assert a == b
        # keyless round-robins
        seen = {partition_for(None, 4, counter) for _ in range(8)}
        assert seen == {0, 1, 2, 3}

    def test_record_batch_round_trip(self):
        records = [
            (b"k1", b"v1", [("trace", b"t1"), ("hop", b"2")]),
            (None, b"keyless", []),
            (b"tomb", None, []),  # null value = tombstone
        ]
        blob = encode_record_batch(records, 1_700_000_000_000)
        out = decode_record_batches(blob)
        assert [(k, v, h) for _o, _t, k, v, h in out] == records
        assert [o for o, *_ in out] == [0, 1, 2]

    def test_trace_headers_round_trip_through_record_batch(self):
        """ISSUE 2 satellite: the trace headers the observability layer
        rides on survive encode/decode — wire header values come back as
        BYTES and normalize through protocol.header_map, with missing
        headers tolerated (a None decode, never a KeyError)."""
        from calfkit_tpu import protocol
        from calfkit_tpu.observability.trace import TraceContext

        ctx = TraceContext(trace_id="corr-42", span_id="span-7")
        wire_headers = [
            (name, value.encode("utf-8"))
            for name, value in (
                ctx.headers() | {protocol.HDR_CORRELATION: "corr-42"}
            ).items()
        ]
        blob = encode_record_batch([(b"k", b"v", wire_headers)], 1234)
        [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
        # bytes-vs-str: raw wire values are bytes; header_map normalizes
        assert all(isinstance(v, bytes) for _n, v in decoded)
        normalized = protocol.header_map(dict(decoded))
        back = TraceContext.from_headers(normalized)
        assert back is not None
        assert back.trace_id == "corr-42"
        assert back.span_id == "span-7"
        assert normalized[protocol.HDR_CORRELATION] == "corr-42"

    def test_missing_and_undecodable_trace_headers_tolerated(self):
        from calfkit_tpu import protocol
        from calfkit_tpu.observability.trace import TraceContext

        # no headers at all survives the round trip as an untraced record
        blob = encode_record_batch([(b"k", b"v", [])], 1)
        [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
        assert TraceContext.from_headers(protocol.header_map(dict(decoded))) is None
        # an undecodable trace header value is DROPPED by header_map, so
        # the record degrades to untraced instead of crashing the consumer
        blob = encode_record_batch(
            [(b"k", b"v", [(protocol.HDR_TRACE, b"\xff\xfe\xfd")])], 1
        )
        [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
        normalized = protocol.header_map(dict(decoded))
        assert protocol.HDR_TRACE not in normalized
        assert TraceContext.from_headers(normalized) is None

    def test_run_header_round_trip_through_record_batch(self):
        """ISSUE 17 satellite: the ``x-mesh-run`` header (run identity
        carried verbatim across retries/failover/hedges) survives
        encode/decode and parses back to the exact (run_id, attempt)."""
        from calfkit_tpu import protocol

        value = protocol.format_run("a1b2c3d4e5f60718", 3)
        blob = encode_record_batch(
            [(b"k", b"v", [(protocol.HDR_RUN, value.encode("utf-8"))])], 99
        )
        [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
        normalized = protocol.header_map(dict(decoded))
        assert protocol.parse_run(normalized.get(protocol.HDR_RUN)) == (
            "a1b2c3d4e5f60718",
            3,
        )

    def test_corrupt_run_header_degrades_to_unlinked(self):
        """A corrupt ``x-mesh-run`` value degrades to an UN-LINKED run
        (parse_run → None) — never a shared bogus run id, never a
        delivery fault (the PR 5 corrupt-header law)."""
        from calfkit_tpu import protocol

        for raw in (
            b"\xff\xfe\xfd",  # undecodable utf-8
            b"no-separator",
            b"run:1.5",  # float is not an attempt counter
            b"run:nan",
            b"run:-1",
            b":7",  # empty run id
            b"",
        ):
            blob = encode_record_batch(
                [(b"k", b"v", [(protocol.HDR_RUN, raw)])], 1
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            normalized = protocol.header_map(dict(decoded))
            assert (
                protocol.parse_run(normalized.get(protocol.HDR_RUN)) is None
            )

    def test_priority_header_round_trip_through_record_batch(self):
        """ISSUE 20 satellite: the ``x-mesh-priority`` class header
        survives encode/decode and parses back to the exact class, for
        every class in the vocabulary."""
        from calfkit_tpu import protocol

        for cls in protocol.PRIORITY_CLASSES:
            value = protocol.format_priority(cls)
            blob = encode_record_batch(
                [(b"k", b"v", [(protocol.HDR_PRIORITY, value.encode("utf-8"))])],
                42,
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            normalized = protocol.header_map(dict(decoded))
            assert (
                protocol.parse_priority(normalized.get(protocol.HDR_PRIORITY))
                == cls
            )

    def test_corrupt_priority_header_degrades_to_default(self):
        """A corrupt ``x-mesh-priority`` value parses to None — the
        receiver resolves it to the DEFAULT class (qos.resolve_priority)
        — never a delivery fault, never a third class, and never a
        demotion below the default (the PR 5 corrupt-header law)."""
        from calfkit_tpu import protocol, qos

        for raw in (
            b"\xff\xfe\xfd",  # undecodable utf-8
            b"urgent",  # out-of-vocabulary
            b"INTERACTIVE",  # case matters: the vocabulary is exact
            b"batch ",  # trailing junk
            b"",
        ):
            blob = encode_record_batch(
                [(b"k", b"v", [(protocol.HDR_PRIORITY, raw)])], 1
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            normalized = protocol.header_map(dict(decoded))
            parsed = protocol.parse_priority(
                normalized.get(protocol.HDR_PRIORITY)
            )
            assert parsed is None
            assert qos.resolve_priority(parsed) == protocol.DEFAULT_PRIORITY

    def test_range_assign_splits_evenly(self):
        members = {"m-1": ["a"], "m-2": ["a"]}
        partitions = {"a": [0, 1, 2, 3, 4]}
        out = range_assign(members, partitions)
        assert out["m-1"]["a"] == [0, 1, 2]
        assert out["m-2"]["a"] == [3, 4]
        # a member not subscribed to a topic gets none of it
        members = {"m-1": ["a"], "m-2": ["b"]}
        partitions = {"a": [0, 1], "b": [0]}
        out = range_assign(members, partitions)
        assert out["m-1"] == {"a": [0, 1]}
        assert out["m-2"] == {"b": [0]}


class TestWireBroker:
    async def test_produce_fetch_headers_tombstones(self, broker_port):
        client = KafkaWireClient("127.0.0.1", broker_port)
        await client.metadata(["t1"])
        base = await client.produce(
            "t1", 0,
            encode_record_batch(
                [(b"k", b"v", [("h", b"x")]), (b"k", None, [])], 1234,
            ),
        )
        assert base == 0
        fetched = await client.fetch([("t1", 0, 0)], max_wait_ms=100)
        [(_t, _p, err, blob)] = fetched
        assert err == 0
        recs = decode_record_batches(blob)
        assert recs[0][2:] == (b"k", b"v", [("h", b"x")])
        assert recs[1][3] is None  # tombstone survives the wire
        await client.close()

    async def test_fetch_from_middle_offset(self, broker_port):
        client = KafkaWireClient("127.0.0.1", broker_port)
        await client.metadata(["t2"])
        for i in range(5):
            await client.produce(
                "t2", 1,
                encode_record_batch([(None, b"m%d" % i, [])], 1000 + i),
            )
        fetched = await client.fetch([("t2", 1, 3)], max_wait_ms=100)
        [(_t, _p, _e, blob)] = fetched
        assert [v for _o, _t2, _k, v, _h in decode_record_batches(blob)] == [
            b"m3", b"m4",
        ]
        await client.close()

    async def test_commit_resume_across_group_restarts(self, broker_port):
        """Offsets committed by one consumer generation are where the next
        one resumes — the crash/restart contract."""
        mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await mesh.start()
        topic = "resume-topic"
        await mesh.ensure_topics([topic])
        first, second = [], []

        async def h1(rec):
            first.append(rec.value)

        async def h2(rec):
            second.append(rec.value)

        sub = await mesh.subscribe([topic], h1, group_id="resume-g")
        for i in range(4):
            await mesh.publish(topic, b"a%d" % i, key=b"same-key")
        for _ in range(100):
            if len(first) == 4:
                break
            await asyncio.sleep(0.05)
        assert len(first) == 4
        await sub.stop()  # final commit on stop
        # records published while nobody is subscribed
        for i in range(3):
            await mesh.publish(topic, b"b%d" % i, key=b"same-key")
        sub2 = await mesh.subscribe([topic], h2, group_id="resume-g")
        for _ in range(200):
            if len(second) == 3:
                break
            await asyncio.sleep(0.05)
        # ONLY the gap records: committed offsets were honored
        assert second == [b"b0", b"b1", b"b2"]
        await sub2.stop()
        await mesh.stop()

    async def test_rebalance_splits_and_takeover(self, broker_port):
        mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await mesh.start()
        topic = "split-topic"
        await mesh.ensure_topics([topic])
        got_a, got_b = [], []

        async def ha(rec):
            got_a.append(rec.value)

        async def hb(rec):
            got_b.append(rec.value)

        sub_a = await mesh.subscribe([topic], ha, group_id="split-g")
        sub_b = await mesh.subscribe([topic], hb, group_id="split-g")
        await asyncio.sleep(1.0)  # both generations settle
        # keys spread over all 8 partitions: both members must see work
        for i in range(24):
            await mesh.publish(topic, b"w%d" % i, key=b"key-%d" % i)
        for _ in range(200):
            if len(got_a) + len(got_b) == 24:
                break
            await asyncio.sleep(0.05)
        assert len(got_a) + len(got_b) == 24
        assert got_a and got_b, "range assignment must split the partitions"
        # one member leaves; the survivor owns everything
        await sub_b.stop()
        await asyncio.sleep(1.0)
        mark = len(got_a)
        for i in range(6):
            await mesh.publish(topic, b"z%d" % i, key=b"key-%d" % i)
        for _ in range(200):
            if len(got_a) - mark == 6:
                break
            await asyncio.sleep(0.05)
        assert len(got_a) - mark == 6
        await sub_a.stop()
        await mesh.stop()

    async def test_agent_round_trip_over_wire_mesh(self, broker_port):
        """The whole product path — client → kafkad (real Kafka wire
        protocol) → worker → agent → reply — with zero aiokafka."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await client_mesh.start()
        agent = Agent(
            "wire_agent", model=TestModelClient(custom_output_text="over-kafka")
        )
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(client_mesh)
            result = await client.agent("wire_agent").execute("go", timeout=60)
            assert result.output == "over-kafka"
            await client.close()
        await client_mesh.stop()


class TestConfig4MultiAgent:
    """BASELINE config 4 over the REAL wire broker: 3 Agent nodes on
    shared topics with parallel tool calls, driven concurrently
    (reference analog: tests/test_concurrent_tool_calls.py — there over
    Redpanda, here over kafkad)."""

    async def test_three_agents_parallel_tools_concurrent_runs(self, broker_port):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.models import ModelResponse
        from calfkit_tpu.models.messages import TextOutput, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def city_temp(city: str) -> float:
            """Temperature lookup.

            Args:
                city: City name.
            """
            return {"sf": 18.0, "nyc": 25.0}.get(city.lower(), 20.0)

        def scripted(messages, params):
            # first turn: TWO parallel tool calls; second: final answer
            has_returns = any(
                getattr(part, "kind", "") == "tool_return"
                for m in messages for part in getattr(m, "parts", [])
            )
            if not has_returns:
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id="a", tool_name="city_temp",
                                   args={"city": "SF"}),
                    ToolCallOutput(tool_call_id="b", tool_name="city_temp",
                                   args={"city": "NYC"}),
                ])
            return ModelResponse(parts=[TextOutput(text="SF 18, NYC 25")])

        agents = [
            Agent(f"cfg4_agent_{i}", model=FunctionModelClient(scripted),
                  tools=[city_temp])
            for i in range(3)
        ]
        mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await client_mesh.start()
        async with Worker(
            [*agents, city_temp], mesh=mesh, owns_transport=True
        ):
            client = Client.connect(client_mesh)
            results = await asyncio.gather(*[
                client.agent(f"cfg4_agent_{i % 3}").execute(
                    f"temps {i}?", timeout=120
                )
                for i in range(6)
            ])
            assert [r.output for r in results] == ["SF 18, NYC 25"] * 6
            await client.close()
        await client_mesh.stop()
