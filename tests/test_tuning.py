"""Config value-objects are guard-rails: typo'd knobs and invalid values
fail at construction, and configs are immutable once built (reference:
calfkit/tuning.py strict validation + the reject-by-name kwarg style)."""

import pytest
from pydantic import ValidationError

from calfkit_tpu.controlplane import ControlPlaneConfig
from calfkit_tpu.provisioning import ProvisioningConfig
from calfkit_tpu.tuning import FanoutConfig, TableTuning

ALL_CONFIGS = [TableTuning, FanoutConfig, ControlPlaneConfig, ProvisioningConfig]


class TestStrictness:
    @pytest.mark.parametrize("cls", ALL_CONFIGS)
    def test_unknown_knob_rejected_by_name(self, cls):
        with pytest.raises(ValidationError, match="catchup_tiemout"):
            cls(catchup_tiemout=5)  # the classic typo must not be ignored

    @pytest.mark.parametrize("cls", ALL_CONFIGS)
    def test_frozen_after_construction(self, cls):
        config = cls()
        field = next(iter(cls.model_fields))
        with pytest.raises(ValidationError):
            setattr(config, field, 99)


class TestBounds:
    def test_timeouts_must_be_positive(self):
        with pytest.raises(ValidationError):
            TableTuning(catchup_timeout_s=0)
        with pytest.raises(ValidationError):
            TableTuning(barrier_timeout_s=-1)
        with pytest.raises(ValidationError):
            ControlPlaneConfig(heartbeat_interval=0)

    def test_stale_multiplier_at_least_one(self):
        # below 1x, a node would be declared dead before its next heartbeat
        with pytest.raises(ValidationError):
            ControlPlaneConfig(stale_multiplier=0.5)
        assert ControlPlaneConfig(stale_multiplier=1.0).stale_after == 5.0

    def test_provisioning_attempts_at_least_one(self):
        with pytest.raises(ValidationError):
            ProvisioningConfig(max_attempts=0)
        assert ProvisioningConfig(retry_backoff_s=0.0).retry_backoff_s == 0.0

    def test_stale_after_derivation(self):
        config = ControlPlaneConfig(heartbeat_interval=2.0, stale_multiplier=4.0)
        assert config.stale_after == 8.0


class TestWorkerKnobValidation:
    def test_worker_rejects_wrong_config_types_by_name(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.exceptions import LifecycleConfigError
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        agent = Agent("k", model=TestModelClient())
        mesh = InMemoryMesh()
        with pytest.raises(LifecycleConfigError, match="FanoutConfig"):
            Worker([agent], mesh=mesh, fanout={"table": {}})
        with pytest.raises(LifecycleConfigError, match="ProvisioningConfig"):
            Worker([agent], mesh=mesh, provisioning={"enabled": False})
